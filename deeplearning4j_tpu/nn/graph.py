"""ComputationGraph (≡ deeplearning4j-nn :: graph.ComputationGraph).

DAG-structured network over GraphNode topology: multi-input, multi-output,
per-output losses summed into one scalar — so the whole training step is
still ONE jitted XLA executable (forward over topo order + backward +
updaters), the TPU-native counterpart of the reference's vertex-by-vertex
executioner dispatch."""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import profiler as _prof
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import guardian as _guardian
from deeplearning4j_tpu.resilience import watchdog as _watchdog
from deeplearning4j_tpu.runtime import pipeline as _pipeline
from deeplearning4j_tpu.util.crash_reporting import \
    with_crash_dump
from deeplearning4j_tpu.datasets.dataset import DataSet, MultiDataSet
from deeplearning4j_tpu.nn import accum as _accum
from deeplearning4j_tpu.nn.multilayer import (_apply_layer, _hook_params,
                                              _l1l2_penalty)
from deeplearning4j_tpu.nn.updaters import build_optimizer, same_updater
from deeplearning4j_tpu.ops.ndarray import NDArray, as_jax, resolve_dtype


class ComputationGraph:
    def __init__(self, conf):
        self.conf = conf
        self.nodes = conf.nodes
        self._params = None
        self._state = None
        self._opt_state = None
        self._tx = None
        self._listeners = []
        self._score = None
        self._iteration = 0
        self._epoch = 0
        self._compute_dtype = resolve_dtype(conf.data_type) or jnp.float32
        self._rng_key = jax.random.PRNGKey(conf.seed)
        self._fused_pairs = {}   # bn node -> conv node (nn/fused.py)
        self._fused_convs = set()

    # layer-bearing node names in topo order
    @property
    def _layer_names(self):
        return [n for n in self.conf.topo_order
                if self.nodes[n].kind == "layer"]

    @property
    def _output_layers(self):
        return [self.nodes[n].ref for n in self.conf.output_names]

    # -- lifecycle -------------------------------------------------------
    def init(self):
        if not self.conf.node_output_types:
            raise ValueError("setInputTypes(...) required before init()")
        from deeplearning4j_tpu.nn.fused import (find_conv1x1_bn_fusions,
                                                 fusion_enabled)
        # per-instance execution decision; the shared conf is never mutated
        self._fused_pairs = (find_conv1x1_bn_fusions(self.conf)
                             if fusion_enabled() else {})
        self._fused_convs = set(self._fused_pairs.values())
        key = jax.random.PRNGKey(self.conf.seed)
        ps, ss = {}, {}
        for name in self.conf.topo_order:
            node = self.nodes[name]
            if node.kind == "vertex" and hasattr(node.ref, "initialize"):
                # parameterized vertex (AttentionVertex): params thread
                # through the same jitted step as layer params
                key, sub = jax.random.split(key)
                p, s = node.ref.initialize(sub, *node.resolved_input_types)
                if p:
                    ps[name] = p
                if s:
                    ss[name] = s
                continue
            if node.kind != "layer":
                continue
            key, sub = jax.random.split(key)
            p, s, _ = node.ref.initialize(sub, node.resolved_input_type)
            if p:
                ps[name] = p
            if s:
                ss[name] = s
        self._params = ps
        self._state = ss
        self._build_optimizer()
        return self

    def _build_optimizer(self):
        defaults = self.conf.defaults
        global_updater = defaults.get("updater")
        overrides = {n: self.nodes[n].ref.updater for n in self._layer_names
                     if self.nodes[n].ref.updater is not None
                     and not same_updater(self.nodes[n].ref.updater,
                                          global_updater)}
        gn = defaults.get("gradientNormalization")
        gn_thr = defaults.get("gradientNormalizationThreshold", 1.0)
        wd = defaults.get("weightDecay", 0.0) or 0.0
        if not overrides:
            self._tx = build_optimizer(global_updater, gn, gn_thr, wd)
        else:
            transforms = {"__global__": build_optimizer(global_updater, gn, gn_thr, wd)}
            transforms.update({k: build_optimizer(u, gn, gn_thr, wd)
                               for k, u in overrides.items()})
            labels = {k: (k if k in overrides else "__global__")
                      for k in self._params}
            self._tx = optax.multi_transform(transforms, labels)
        self._opt_state = self._tx.init(self._params)

    def clone(self):
        m = ComputationGraph(self.conf)
        m._fused_pairs = dict(self._fused_pairs)
        m._fused_convs = set(self._fused_convs)
        if self._params is not None:
            # real copies — the live net's jitted train step donates buffers
            m._params = jax.tree_util.tree_map(jnp.copy, self._params)
            m._state = jax.tree_util.tree_map(jnp.copy, self._state)
            m._build_optimizer()
        return m

    # -- parameters ------------------------------------------------------
    def numParams(self):
        return sum(int(np.prod(l.shape))
                   for l in jax.tree_util.tree_leaves(self._params))

    def params(self):
        names = sorted(self._params)
        leaves = jax.tree_util.tree_leaves({n: self._params[n] for n in names})
        if not leaves:
            return NDArray(jnp.zeros((0,)))
        return NDArray(jnp.concatenate([l.ravel() for l in leaves]))

    def paramTable(self):
        flat = {}
        for name, p in (self._params or {}).items():
            for k, v in p.items():
                flat[f"{name}_{k}"] = NDArray(v)
        return flat

    def getLayer(self, name):
        return self.nodes[name].ref

    # -- forward ---------------------------------------------------------
    def _forward(self, params, state, inputs, train, rng, fmasks=None,
                 want=None, carries=None):
        """inputs: dict name->array. Returns (acts dict, preacts dict for
        output layers, new_state[, new_carries when carries given]).

        carries: optional {node_name: carry} — recurrent layer nodes then
        run via scan_apply so hidden state threads across calls
        (≡ ComputationGraph.rnnTimeStep's stored state)."""
        if (train and carries is None
                and getattr(self.conf, "remat_policy", "none") == "blocks"
                and not getattr(self, "_fused_pairs", None)
                and (fmasks is None
                     or all(m is None for m in fmasks.values()))):
            # per-residual-block selective recompute: only block-boundary
            # activations are saved for backward, block internals re-run
            # under jax.checkpoint (ROADMAP item 3's FLOPs-for-bytes
            # trade; gradients equal the un-rematted step — tier-1)
            return self._forward_remat_blocks(params, state, inputs, rng)
        acts = {}
        preacts = {}
        new_state = dict(state)
        new_carries = {} if carries is not None else None
        mask0 = None
        if fmasks:
            mask0 = next((m for m in fmasks.values() if m is not None), None)
        node_masks = {}
        for name, x in inputs.items():
            acts[name] = x.astype(self._compute_dtype)
            node_masks[name] = (fmasks.get(name, mask0) if fmasks else None)
        li = 0
        for name in self.conf.topo_order:
            node = self.nodes[name]
            if node.kind == "input":
                continue
            parents = [acts[p] for p in node.inputs]
            parent_masks = [node_masks.get(p) for p in node.inputs]
            if node.kind == "vertex":
                pmask = next((m for m in parent_masks if m is not None), None)
                if fmasks and getattr(node.ref, "maskName", None):
                    pmask = fmasks.get(node.ref.maskName, pmask)
                if hasattr(node.ref, "initialize"):
                    acts[name] = node.ref.apply(
                        *parents, params=params.get(name, {}), mask=pmask)
                else:
                    acts[name] = node.ref.apply(*parents, mask=pmask)
                node_masks[name] = node.ref.feed_forward_mask(*parent_masks)
                continue
            layer = node.ref
            # frozen layers (transfer learning) always run inference-mode
            ltrain = train and not getattr(layer, "frozen", False)
            x = parents[0]
            pmask = parent_masks[0]
            if node.preprocessor is not None:
                x = node.preprocessor.preProcess(x)
            if name in getattr(self, "_fused_convs", ()):
                # conv half of a conv1x1+BN fused pair (nn/fused.py):
                # pass the input through; the BN node runs the fused
                # kernel with both param groups and back-fills this
                # node's true activation. li still advances so every
                # layer keeps its rng stream slot.
                acts[name] = x
                node_masks[name] = pmask
                li += 1
                continue
            lrng = jax.random.fold_in(rng, li) if rng is not None else None
            li += 1
            p = _hook_params(layer, params.get(name, {}), ltrain, lrng)
            s = state.get(name, {})
            fc = getattr(self, "_fused_pairs", {}).get(name)
            if fc is not None:
                from deeplearning4j_tpu.nn.fused import fused_apply
                y, ns, y_conv = fused_apply(self.nodes[fc].ref, layer,
                                            params.get(fc, {}), p, s, x,
                                            ltrain)
                acts[name] = y
                acts[fc] = y_conv  # feedForward sees the real conv output
                if ns:
                    new_state[name] = ns
                node_masks[name] = pmask
                continue
            if name in self.conf.output_names and hasattr(layer, "compute_loss"):
                xd = layer._dropout_in(x, ltrain, lrng)
                if getattr(layer, "pre_activation_takes_mask", False):
                    pre = layer.pre_activation(p, xd, mask=pmask)
                else:
                    pre = layer.pre_activation(p, xd)
                preacts[name] = pre
                from deeplearning4j_tpu.nn.activations import get_activation
                acts[name] = get_activation(layer.activation)(pre)
                node_masks[name] = pmask
            elif carries is not None and getattr(layer, "is_recurrent",
                                                 False):
                if not hasattr(layer, "scan_apply"):
                    # Bidirectional/MaskZeroLayer etc. have no single
                    # forward carry — silently stateless results would be
                    # wrong (the reference throws here too)
                    raise ValueError(
                        f"rnnTimeStep: {type(layer).__name__} '{name}' "
                        "cannot run step-by-step (no carried state "
                        "protocol); use output() on whole sequences")
                x = layer._dropout_in(x, ltrain, lrng)
                y, carry = layer.scan_apply(p, x, carries.get(name), pmask)
                acts[name] = y
                new_carries[name] = carry
                node_masks[name] = (layer.feed_forward_mask(pmask)
                                    if pmask is not None else None)
            else:
                y, ns = _apply_layer(layer, p, s, x, ltrain, lrng, pmask)
                acts[name] = y
                if ns:
                    new_state[name] = ns
                node_masks[name] = (layer.feed_forward_mask(pmask)
                                    if pmask is not None else None)
        if carries is not None:
            return acts, preacts, new_state, new_carries
        return acts, preacts, new_state

    # -- per-block selective recompute (rematPolicy "blocks") ------------
    @functools.cached_property
    def _remat_plan(self):
        """(plan, rng_index): conf.remat_plan() — segments plus their
        ACTUALLY-SAVED outputs (shared with the traffic ledger) — and
        the layer→rng-stream index map (the SAME fold_in(rng, i)
        stream the plain path uses, so dropout/weight-noise draws are
        identical with remat on or off)."""
        plan = self.conf.remat_plan()
        rng_index = {}
        li = 0
        for name in self.conf.topo_order:
            if self.nodes[name].kind == "layer":
                rng_index[name] = li
                li += 1
        return plan, rng_index

    def _run_node_plain(self, name, params, state, acts, new_state,
                        preacts, rng, rng_index, train=True):
        """One node of the mask-free forward (block-remat segments and
        the quantized-graph executor run nodes through this — masked/
        carried/fused forwards use the general loop above). Mirrors
        that loop's per-node semantics exactly: preprocessors, frozen
        layers, param hooks, dropout-in + pre_activation for loss
        heads."""
        node = self.nodes[name]
        parents = [acts[p] for p in node.inputs]
        if node.kind == "vertex":
            if hasattr(node.ref, "initialize"):
                acts[name] = node.ref.apply(
                    *parents, params=params.get(name, {}), mask=None)
            else:
                acts[name] = node.ref.apply(*parents, mask=None)
            return
        layer = node.ref
        ltrain = train and not getattr(layer, "frozen", False)
        x = parents[0]
        if node.preprocessor is not None:
            x = node.preprocessor.preProcess(x)
        lrng = (jax.random.fold_in(rng, rng_index[name])
                if rng is not None else None)
        p = _hook_params(layer, params.get(name, {}), ltrain, lrng)
        s = state.get(name, {})
        if name in self.conf.output_names and hasattr(layer,
                                                      "compute_loss"):
            xd = layer._dropout_in(x, ltrain, lrng)
            if getattr(layer, "pre_activation_takes_mask", False):
                pre = layer.pre_activation(p, xd, mask=None)
            else:
                pre = layer.pre_activation(p, xd)
            preacts[name] = pre
            from deeplearning4j_tpu.nn.activations import get_activation
            acts[name] = get_activation(layer.activation)(pre)
        else:
            y, ns = _apply_layer(layer, p, s, x, ltrain, lrng, None)
            acts[name] = y
            if ns:
                new_state[name] = ns

    def _forward_remat_blocks(self, params, state, inputs, rng):
        """Training forward where each residual-block segment runs under
        jax.checkpoint: backward sees only the BLOCK-BOUNDARY
        activations (the fan-out tensors a residual graph must keep
        anyway) and recomputes the conv/BN internals — on an HBM-bound
        step that converts the measured ~27%-of-MFU conv FLOP headroom
        into eliminated activation reads."""
        plan, rng_index = self._remat_plan
        acts = {name: x.astype(self._compute_dtype)
                for name, x in inputs.items()}
        preacts = {}
        new_state = dict(state)
        for seg, outs in plan:
            seg_set = set(seg)
            ext = []
            for name in seg:
                for p in self.nodes[name].inputs:
                    if p not in seg_set and p not in ext:
                        ext.append(p)
            seg_params = {n: params[n] for n in seg if n in params}
            seg_state = {n: state[n] for n in seg if n in state}

            def seg_fn(sp, ss, ext_acts, key, _seg=tuple(seg),
                       _ext=tuple(ext), _outs=tuple(outs)):
                a = dict(zip(_ext, ext_acts))
                ns, pre = {}, {}
                for n in _seg:
                    self._run_node_plain(n, sp, ss, a, ns, pre, key,
                                         rng_index)
                return tuple(a[n] for n in _outs), ns, pre

            out, ns, pre = jax.checkpoint(seg_fn)(
                seg_params, seg_state,
                tuple(acts[p] for p in ext), rng)
            acts.update(zip(outs, out))
            new_state.update(ns)
            preacts.update(pre)
        return acts, preacts, new_state

    def _as_input_dict(self, inputs):
        if isinstance(inputs, dict):
            return {k: as_jax(v) for k, v in inputs.items()}
        if not isinstance(inputs, (list, tuple)):
            inputs = [inputs]
        return {n: as_jax(v) for n, v in zip(self.conf.input_names, inputs)}

    @with_crash_dump
    def output(self, *inputs, train=False, fmasks=None):
        if len(inputs) == 1:
            inputs = inputs[0]
        ins = self._as_input_dict(inputs)
        if fmasks is not None:
            fmasks = {k: (None if v is None else as_jax(v))
                      for k, v in fmasks.items()}
        acts, _, _ = self._forward(self._params, self._state, ins, train,
                                   None, fmasks)
        outs = [NDArray(acts[n]) for n in self.conf.output_names]
        return outs[0] if len(outs) == 1 else outs

    def outputSingle(self, *inputs):
        out = self.output(*inputs)
        return out[0] if isinstance(out, list) else out

    def getOutputLayer(self, index=0):
        """≡ ComputationGraph.getOutputLayer(idx) — conf object of the
        idx-th output layer."""
        return self._output_layers[index]

    def getPredictedObjects(self, inputs, confThreshold=0.5,
                            nmsThreshold=0.4):
        """Detection convenience over a Yolo2OutputLayer output (≡
        YoloUtils.getPredictedObjects). `inputs` is one array, or a
        list/dict for multi-input graphs (NOT *args — thresholds stay
        positional like the MultiLayerNetwork twin).
        Returns List[List[DetectedObject]]."""
        out_layer = self._output_layers[0]
        if not hasattr(out_layer, "getPredictedObjects"):
            raise TypeError(
                f"output layer {type(out_layer).__name__} has no detection "
                "decode — getPredictedObjects needs a Yolo2OutputLayer head")
        y = self.outputSingle(inputs)
        return out_layer.getPredictedObjects(as_jax(y), confThreshold,
                                             nmsThreshold)

    def feedForward(self, inputs, train=False):
        ins = self._as_input_dict(inputs)
        acts, _, _ = self._forward(self._params, self._state, ins, train, None)
        return {k: NDArray(v) for k, v in acts.items()}

    # -- stateful RNN inference (≡ ComputationGraph.rnnTimeStep) ---------
    def rnnTimeStep(self, *inputs):
        if len(inputs) == 1:
            inputs = inputs[0]
        ins = self._as_input_dict(inputs)
        squeeze = any(v.ndim == 2 for v in ins.values())
        ins = {k: (v[:, None, :] if v.ndim == 2 else v)
               for k, v in ins.items()}
        if getattr(self, "_rnn_carries", None) is None:
            self._rnn_carries = {}
        acts, _, _, self._rnn_carries = self._forward(
            self._params, self._state, ins, False, None,
            carries=self._rnn_carries)
        outs = []
        for n in self.conf.output_names:
            y = acts[n]
            outs.append(NDArray(y[:, -1, :] if squeeze and y.ndim == 3
                                else y))
        return outs[0] if len(outs) == 1 else outs

    def rnnClearPreviousState(self):
        self._rnn_carries = None

    def rnnGetPreviousState(self, node_name):
        return (getattr(self, "_rnn_carries", None) or {}).get(node_name)

    # -- loss ------------------------------------------------------------
    def _loss(self, params, state, inputs, labels, fmasks, lmasks, rng,
              train=True):
        acts, preacts, new_state = self._forward(params, state, inputs, train,
                                                 rng, fmasks)
        total = 0.0
        for i, name in enumerate(self.conf.output_names):
            layer = self.nodes[name].ref
            if not hasattr(layer, "compute_loss"):
                raise ValueError(f"Output node '{name}' is not an output layer")
            y = labels[i].astype(jnp.float32)
            lm = None if lmasks is None else lmasks[i]
            if getattr(layer, "needs_features", False):
                node = self.nodes[name]
                feats = acts[node.inputs[0]]
                if node.preprocessor is not None:
                    feats = node.preprocessor.preProcess(feats)
                total = total + layer.compute_loss_with_features(
                    params.get(name, {}), y,
                    preacts[name].astype(jnp.float32),
                    feats.astype(jnp.float32), lm)
            else:
                total = total + layer.compute_loss(
                    y, preacts[name].astype(jnp.float32), lm)
        layer_list = [self.nodes[n].ref for n in self._layer_names]
        reg_params = {str(i): params.get(n, {})
                      for i, n in enumerate(self._layer_names)}
        total = total + _l1l2_penalty(layer_list, reg_params)
        return total, new_state

    def score(self, dataset=None):
        if dataset is None:
            # lazy score: _score may hold the device loss scalar; this
            # is the on-demand sync point (dl4j.pipeline.syncs)
            return _pipeline.materialize_score(self)
        ins, labels, fmasks, lmasks = self._unpack(dataset)
        # inference-mode forward (≡ reference score(DataSet) semantics)
        loss, _ = self._loss(self._params, self._state, ins, labels, fmasks,
                             lmasks, None, train=False)
        return float(loss)

    # -- training --------------------------------------------------------
    @functools.cached_property
    def _train_step(self):
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, opt_state, state, inputs, labels, fmasks, lmasks, rng):
            (loss, new_state), grads = jax.value_and_grad(
                lambda p: self._loss(p, state, inputs, labels, fmasks, lmasks,
                                     rng), has_aux=True)(params)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params = self._apply_constraints(params)
            return params, opt_state, new_state, loss

        return step

    @functools.cached_property
    def _train_step_guarded(self):
        """Guardian variant of `_train_step` (see
        MultiLayerNetwork._train_step_guarded): same update + device
        health verdict, update applied only when loss and global grad
        norm are finite and the norm is under the guardian's threshold;
        `lr_scale` implements the reduce-LR escalation rung."""
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, opt_state, state, inputs, labels, fmasks,
                 lmasks, rng, lr_scale, max_gnorm):
            (loss, new_state), grads = jax.value_and_grad(
                lambda p: self._loss(p, state, inputs, labels, fmasks,
                                     lmasks, rng), has_aux=True)(params)
            params, opt_state, (state,), gnorm, ok = \
                _guardian.guarded_apply(
                    tx, grads, loss, params, opt_state, lr_scale,
                    max_gnorm, constraints=self._apply_constraints,
                    extra=((new_state, state),))
            return params, opt_state, state, loss, gnorm, ok

        return step

    def _apply_constraints(self, params):
        """Post-update constraints per layer vertex (≡ BaseConstraint)."""
        pairs = [(n, self.nodes[n].ref) for n in self._layer_names]
        if not any(getattr(l, "constraints", None) for _, l in pairs):
            return params
        from deeplearning4j_tpu.nn.constraints import apply_layer_constraints
        return apply_layer_constraints(pairs, params)

    def _pack_single(self, x, y, fmask=None, lmask=None):
        """THE single-input/single-output packing convention — the one
        place that maps flat (x, y, masks) onto this graph's kwargs
        (also used by ParallelWrapper's dp step)."""
        ins = {self.conf.input_names[0]: x}
        labels = [y]
        fmasks = None if fmask is None \
            else {self.conf.input_names[0]: fmask}
        lmasks = None if lmask is None else [lmask]
        return ins, labels, fmasks, lmasks

    def _unpack(self, ds):
        if isinstance(ds, (MultiDataSet, _pipeline.StagedMultiBatch)):
            ins = {n: jnp.asarray(f) for n, f in
                   zip(self.conf.input_names, ds.features)}
            labels = [jnp.asarray(l) for l in ds.labels]
            fmasks = None
            if ds.featuresMasks is not None:
                fmasks = {n: (None if m is None else jnp.asarray(m))
                          for n, m in zip(self.conf.input_names, ds.featuresMasks)}
            lmasks = None
            if ds.labelsMasks is not None:
                lmasks = [None if m is None else jnp.asarray(m)
                          for m in ds.labelsMasks]
            return ins, labels, fmasks, lmasks
        if isinstance(ds, (DataSet, _pipeline.StagedBatch)):
            return self._pack_single(
                jnp.asarray(ds.features), jnp.asarray(ds.labels),
                None if ds.featuresMask is None
                else jnp.asarray(ds.featuresMask),
                None if ds.labelsMask is None
                else jnp.asarray(ds.labelsMask))
        raise TypeError(f"Cannot fit on {type(ds)}")

    def _fit_batch(self, ds):
        with _mon.span("train.stage"):
            unpacked = self._unpack(ds)
        self._fit_unpacked(unpacked)

    def _fit_unpacked(self, unpacked):
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"graph@{id(self):x}")
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_start()
        ins, labels, fmasks, lmasks = unpacked
        with _mon.span("train.stage"):
            self._rng_key, sub = jax.random.split(self._rng_key)
        _g = _guardian.ACTIVE
        with _mon.span("train.dispatch"):
            if _g is not None:
                (self._params, self._opt_state, self._state, loss,
                 gnorm, ok) = self._train_step_guarded(
                    self._params, self._opt_state, self._state, ins,
                    labels, fmasks, lmasks, sub, _g.lr_scale,
                    _g.max_gnorm)
            else:
                self._params, self._opt_state, self._state, loss = \
                    self._train_step(
                        self._params, self._opt_state, self._state, ins,
                        labels, fmasks, lmasks, sub)
            self._score = loss    # device scalar; score() floats it
        if _g is not None:
            # device scalars only — materialized at the guardian's
            # check cadence, never per step
            _g.on_step(loss, gnorm, ok)
        self._iteration += 1
        self._last_features = ins     # for StatsListener histograms
        self._params_version = getattr(self, "_params_version", 0) + 1
        with _mon.span("train.listeners"):
            for listener in self._listeners:
                listener.iterationDone(self, self._iteration, self._epoch)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()

    @functools.cached_property
    def _train_scan(self):
        """K graph train steps in ONE lax.scan dispatch (see
        MultiLayerNetwork._train_scan for the rationale): the scan body is
        the same update as _train_step over stacked input/label/mask
        pytrees, so k scanned steps == k sequential steps exactly."""
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def scan_steps(params, opt_state, state, ins, labels, fmasks,
                       lmasks, rngs):
            def body(carry, inp):
                p, o, s = carry
                i_, l_, fm, lm, rng = inp
                (loss, ns), grads = jax.value_and_grad(
                    lambda pp: self._loss(pp, s, i_, l_, fm, lm, rng),
                    has_aux=True)(p)
                updates, o = tx.update(grads, o, p)
                p = optax.apply_updates(p, updates)
                p = self._apply_constraints(p)
                return (p, o, ns), loss

            (params, opt_state, state), losses = jax.lax.scan(
                body, (params, opt_state, state),
                (ins, labels, fmasks, lmasks, rngs))
            return params, opt_state, state, losses

        return scan_steps

    def _fit_batches_scanned(self, unpacked):
        """Flush a group of already-unpacked same-structure batches. Only
        full groups go through the scan — sub-k remainders run singly so
        lax.scan is traced for exactly ONE length per batch shape (each
        distinct scan length is a fresh compile)."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"graph@{id(self):x}")
        _ps = _prof.ACTIVE             # armed ProfileSession: the whole
        if _ps is not None:            # scanned dispatch is one "step"
            _ps.step_start()
        with _mon.span("train.stage"):
            subs = []
            for _ in unpacked:  # identical key stream to _fit_batch
                self._rng_key, sub = jax.random.split(self._rng_key)
                subs.append(sub)
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                             *unpacked)
            ins, labels, fmasks, lmasks = stacked
        with _mon.span("train.scan_dispatch"):
            (self._params, self._opt_state, self._state,
             losses) = self._train_scan(self._params, self._opt_state,
                                        self._state, ins, labels, fmasks,
                                        lmasks, jnp.stack(subs))
        self._last_features = jax.tree_util.tree_map(lambda a: a[-1], ins)
        self._params_version = getattr(self, "_params_version", 0) + 1
        with _mon.span("train.listeners"):
            if self._listeners:
                # device slices, not device_get: score() syncs only for
                # listeners that actually read it
                for i in range(len(unpacked)):
                    self._score = losses[i]
                    self._iteration += 1
                    for listener in self._listeners:
                        listener.iterationDone(self, self._iteration,
                                               self._epoch)
            else:
                self._score = losses[len(unpacked) - 1]
                self._iteration += len(unpacked)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()

    # -- in-step gradient accumulation (ISSUE 14): see
    # MultiLayerNetwork._train_step_accum — G microbatches, ONE update.
    @functools.cached_property
    def _train_accum(self):
        """Accumulated graph step: `nn/accum.accum_scan` over G stacked
        batch pytrees (grads/loss summed on device, vertex state
        threaded sequentially), then ONE updater application."""
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, opt_state, state, ins, labels, fmasks, lmasks,
                 rngs):
            grads, loss, _, state = _accum.accum_scan(
                self._accum_grad_fn, params, state,
                (ins, labels, fmasks, lmasks, rngs))
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            params = self._apply_constraints(params)
            return params, opt_state, state, loss

        return step

    def _accum_grad_fn(self, params, state, inp):
        """One microbatch's ((loss, new_state), grads) for accum_scan."""
        i_, l_, fm, lm, rng = inp
        (loss, ns), grads = jax.value_and_grad(
            lambda p: self._loss(p, state, i_, l_, fm, lm, rng),
            has_aux=True)(params)
        return (loss, ns), grads

    @functools.cached_property
    def _train_accum_guarded(self):
        """Guardian variant of `_train_accum`: one verdict gates the
        accumulated update; a NaN in any microbatch poisons the
        inspected loss (see MultiLayerNetwork._train_step_accum_guarded
        for the full contract)."""
        tx = self._tx

        @functools.partial(jax.jit, donate_argnums=(0, 1, 2))
        def step(params, opt_state, state, ins, labels, fmasks, lmasks,
                 rngs, lr_scale, max_gnorm):
            grads, loss, micro_ok, new_state = _accum.accum_scan(
                self._accum_grad_fn, params, state,
                (ins, labels, fmasks, lmasks, rngs))
            vloss = jnp.where(micro_ok, loss, jnp.float32(jnp.nan))
            params, opt_state, (state,), gnorm, ok = \
                _guardian.guarded_apply(
                    tx, grads, vloss, params, opt_state, lr_scale,
                    max_gnorm, constraints=self._apply_constraints,
                    extra=((new_state, state),))
            return params, opt_state, state, loss, gnorm, ok

        return step

    def _fit_batches_accum(self, group):
        """Flush a FULL G-batch group of unpacked batches through one
        accumulated optimizer step (one real update: iteration count
        and listeners advance once)."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"graph@{id(self):x}")
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_start()
        with _mon.span("train.stage"):
            subs = []
            for _ in group:
                self._rng_key, sub = jax.random.split(self._rng_key)
                subs.append(sub)
            stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                             *group)
            ins, labels, fmasks, lmasks = stacked
        _g = _guardian.ACTIVE
        with _mon.span("train.accum_dispatch"):
            if _g is not None:
                (self._params, self._opt_state, self._state, loss,
                 gnorm, ok) = self._train_accum_guarded(
                    self._params, self._opt_state, self._state, ins,
                    labels, fmasks, lmasks, jnp.stack(subs),
                    _g.lr_scale, _g.max_gnorm)
            else:
                (self._params, self._opt_state, self._state,
                 loss) = self._train_accum(
                    self._params, self._opt_state, self._state, ins,
                    labels, fmasks, lmasks, jnp.stack(subs))
            self._score = loss
        if _g is not None:
            _g.on_step(loss, gnorm, ok)   # one verdict per real update
        self._iteration += 1
        self._last_features = jax.tree_util.tree_map(lambda a: a[-1], ins)
        self._params_version = getattr(self, "_params_version", 0) + 1
        with _mon.span("train.listeners"):
            for listener in self._listeners:
                listener.iterationDone(self, self._iteration, self._epoch)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()

    @staticmethod
    def _batch_sig(unpacked_or_ds):
        leaves, treedef = jax.tree_util.tree_flatten(unpacked_or_ds)
        return (str(treedef), tuple(jnp.shape(x) for x in leaves))

    @with_crash_dump
    def fit(self, data, labels=None, epochs=None, stepsPerDispatch=1,
            prefetch=None):
        """stepsPerDispatch > 1 (iterator form): group consecutive
        same-structure batches into one scanned dispatch — numerically
        identical to the sequential loop (tested); ragged/odd batches
        flush the group early and run singly.

        prefetch: staging queue depth for the background device-staging
        prefetcher (async-supporting iterators; default
        runtime.pipeline.DEFAULT_PREFETCH, 0 disables) — batch N+1 is
        staged to XLA-owned device buffers while step N computes."""
        if self._params is None:
            self.init()
        if labels is not None:
            try:
                with _mon.span("fit"):
                    self._fit_batch(DataSet(as_jax(data), as_jax(labels)))
            finally:           # retire even on a raise: a FAILED fit is
                #                not a wedged one (see iterator path)
                if _watchdog.ACTIVE is not None:
                    _watchdog.ACTIVE.retire(f"graph@{id(self):x}")
            return self
        if isinstance(data, (DataSet, MultiDataSet)):
            try:
                with _mon.span("fit"):
                    self._fit_batch(data)
            finally:
                if _watchdog.ACTIVE is not None:
                    _watchdog.ACTIVE.retire(f"graph@{id(self):x}")
            return self
        accum = int(self.conf.defaults.get("gradientAccumulation", 1)
                    or 1)
        k = max(1, int(stepsPerDispatch))
        if accum > 1:
            k = accum   # accumulation owns the grouping (one update)
        elif _guardian.ACTIVE is not None:
            k = 1    # guardian needs per-step health verdicts; a scan
            #          group would hide k-1 of them inside one dispatch
            #          (an accumulated group is ONE update/verdict, so
            #          accum > 1 stays on)
        n_epochs = int(epochs) if epochs is not None else 1

        def flush(group):
            if len(group) == k and accum > 1:
                self._fit_batches_accum(group)
            elif len(group) == k:
                self._fit_batches_scanned(group)
            else:        # sub-k remainder: avoid a fresh per-length trace
                for unpacked in group:
                    self._fit_unpacked(unpacked)

        it, _pf = _pipeline.maybe_prefetch(data, prefetch)
        try:
            for _ in range(n_epochs):
                with _mon.span("fit.epoch"):
                    if hasattr(it, "reset"):
                        it.reset()
                    group, group_sig = [], None
                    for ds in _mon.traced_iter(it):
                        if _faults.ACTIVE is not None:
                            _faults.ACTIVE.fire(_faults.DATA_NEXT)
                        if k == 1:
                            self._fit_batch(ds)
                            continue
                        unpacked = self._unpack(ds)
                        sig = self._batch_sig(unpacked)
                        if group and (sig != group_sig or len(group) >= k):
                            flush(group)
                            group = []
                        group_sig = sig
                        group.append(unpacked)
                    if group:
                        flush(group)
                    self._epoch += 1
                    with _mon.span("fit.epoch_listeners"):
                        for listener in self._listeners:
                            if hasattr(listener, "onEpochEnd"):
                                listener.onEpochEnd(self)
        finally:
            # fit over: this trainer's heartbeat is no longer stall
            # evidence (see multilayer.fit)
            if _watchdog.ACTIVE is not None:
                _watchdog.ACTIVE.retire(f"graph@{id(self):x}")
            if _pf is not None:
                _pf.close()
        return self

    # -- evaluation ------------------------------------------------------
    def _eval_loop(self, iterator, evaluator, prefetch=None):
        # overlap host batch prep with the device forward pass: features
        # stage to device in the background, labels stay host-side;
        # prefetch=0 forces fully synchronous eval (mirrors fit())
        it, _pf = _pipeline.maybe_prefetch(
            iterator, prefetch, stage=_pipeline.stage_for_eval)
        try:
            if hasattr(it, "reset"):
                it.reset()
            for ds in _mon.traced_iter(it, "eval.data_next"):
                if _faults.ACTIVE is not None:
                    _faults.ACTIVE.fire(_faults.EVAL_FORWARD)
                with _mon.span("eval.batch"):
                    out = self.output(ds.features)
                    out0 = out[0] if isinstance(out, list) else out
                    evaluator.eval(ds.labels, out0.numpy(),
                                   mask=ds.labelsMask)
        finally:
            if _pf is not None:
                _pf.close()
        return evaluator

    def evaluate(self, iterator, prefetch=None):
        from deeplearning4j_tpu.eval.evaluation import Evaluation
        return self._eval_loop(iterator, Evaluation(), prefetch=prefetch)

    def evaluateROC(self, iterator, threshold_steps=0, prefetch=None):
        from deeplearning4j_tpu.eval.evaluation import ROC
        return self._eval_loop(iterator, ROC(threshold_steps),
                               prefetch=prefetch)

    def evaluateROCMultiClass(self, iterator, threshold_steps=0,
                              prefetch=None):
        from deeplearning4j_tpu.eval.evaluation import ROCMultiClass
        return self._eval_loop(iterator, ROCMultiClass(threshold_steps),
                               prefetch=prefetch)

    def evaluateCalibration(self, iterator, reliabilityDiagNumBins=10,
                            histogramNumBins=10, prefetch=None):
        from deeplearning4j_tpu.eval.evaluation import EvaluationCalibration
        return self._eval_loop(
            iterator, EvaluationCalibration(reliabilityDiagNumBins,
                                            histogramNumBins),
            prefetch=prefetch)

    # -- listeners / misc ------------------------------------------------
    def setListeners(self, *listeners):
        if len(listeners) == 1 and isinstance(listeners[0], (list, tuple)):
            listeners = listeners[0]
        self._listeners = list(listeners)
        return self

    def getIterationCount(self):
        return self._iteration

    def getEpochCount(self):
        return self._epoch

    def summary(self):
        lines = ["=" * 78,
                 f"{'Name':<20}{'Kind':<10}{'Inputs':<26}{'nParams':>10}",
                 "-" * 78]
        total = 0
        for name in self.conf.topo_order:
            node = self.nodes[name]
            p = (self._params or {}).get(name, {})
            n = sum(int(np.prod(v.shape)) for v in jax.tree_util.tree_leaves(p))
            total += n
            kind = node.kind if node.kind != "layer" else type(node.ref).__name__
            lines.append(f"{name:<20}{kind:<10}{','.join(node.inputs):<26}{n:>10,}")
        lines += ["-" * 78, f"Total params: {total:,}", "=" * 78]
        return "\n".join(lines)

    def save(self, path, saveUpdater=True):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        ModelSerializer.writeModel(self, path, saveUpdater)

    @staticmethod
    def load(path, loadUpdater=True):
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        return ModelSerializer.restoreComputationGraph(path, loadUpdater)
