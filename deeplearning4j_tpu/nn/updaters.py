"""Updaters (≡ nd4j-api :: learning.config.IUpdater: Sgd, Adam, AdaMax,
Nadam, AMSGrad, Nesterovs, RmsProp, AdaGrad, AdaDelta, NoOp).

Each updater lowers to an optax GradientTransformation; the whole update is
part of the single jitted train step (the reference dispatches a separate
updater CUDA kernel per parameter — here XLA fuses it with the backward
pass). Schedules (nn.schedules) pass through as optax-style callables.
"""
from __future__ import annotations

import optax

from deeplearning4j_tpu.nn.schedules import Schedule, as_schedule


def _lr(value):
    sched = as_schedule(value)
    if isinstance(sched, Schedule):
        return lambda step: sched(step)
    return sched


class Updater:
    def to_optax(self):
        raise NotImplementedError

    def config(self):
        return {"type": type(self).__name__, **self.__dict__}


def same_updater(a, b):
    """Structural equality (identity breaks after config JSON roundtrip)."""
    return a is b or (type(a) is type(b)
                      and getattr(a, "__dict__", None) == getattr(
                          b, "__dict__", None))


class Sgd(Updater):
    def __init__(self, learningRate=0.1):
        self.learningRate = learningRate

    def to_optax(self):
        return optax.sgd(_lr(self.learningRate))


class Nesterovs(Updater):
    """≡ learning.config.Nesterovs. `momentumDtype="bfloat16"` keeps the
    momentum buffer in bf16 — halves the optimizer-state HBM traffic per
    step on TPU (the ResNet step is HBM-bound; see BENCH.md). Parameters
    stay fp32 masters; only the velocity accumulator is cast."""

    def __init__(self, learningRate=0.1, momentum=0.9, momentumDtype=None):
        self.learningRate, self.momentum = learningRate, momentum
        self.momentumDtype = momentumDtype

    def to_optax(self):
        acc = None
        if self.momentumDtype is not None:
            import jax.numpy as jnp

            acc = jnp.dtype(self.momentumDtype)
        return optax.sgd(_lr(self.learningRate), momentum=self.momentum,
                         nesterov=True, accumulator_dtype=acc)


class Adam(Updater):
    def __init__(self, learningRate=1e-3, beta1=0.9, beta2=0.999, epsilon=1e-8):
        self.learningRate, self.beta1, self.beta2, self.epsilon = learningRate, beta1, beta2, epsilon

    def to_optax(self):
        return optax.adam(_lr(self.learningRate), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


class AdaMax(Adam):
    def to_optax(self):
        return optax.adamax(_lr(self.learningRate), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


class Nadam(Adam):
    def to_optax(self):
        return optax.nadam(_lr(self.learningRate), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


class AMSGrad(Adam):
    def to_optax(self):
        return optax.amsgrad(_lr(self.learningRate), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


class RmsProp(Updater):
    def __init__(self, learningRate=1e-1, rmsDecay=0.95, epsilon=1e-8):
        self.learningRate, self.rmsDecay, self.epsilon = learningRate, rmsDecay, epsilon

    def to_optax(self):
        return optax.rmsprop(_lr(self.learningRate), decay=self.rmsDecay, eps=self.epsilon)


class AdaGrad(Updater):
    def __init__(self, learningRate=1e-1, epsilon=1e-6):
        self.learningRate, self.epsilon = learningRate, epsilon

    def to_optax(self):
        return optax.adagrad(_lr(self.learningRate), eps=self.epsilon)


class AdaDelta(Updater):
    def __init__(self, rho=0.95, epsilon=1e-6):
        self.rho, self.epsilon = rho, epsilon

    def to_optax(self):
        return optax.adadelta(rho=self.rho, eps=self.epsilon)


class NoOp(Updater):
    def to_optax(self):
        return optax.set_to_zero()


class GradientNormalization:
    NONE = "none"
    RENORMALIZE_L2_PER_LAYER = "renormalizel2perlayer"
    CLIP_ELEMENTWISE_ABSOLUTE_VALUE = "clipelementwiseabsolutevalue"
    CLIP_L2_PER_LAYER = "clipl2perlayer"
    CLIP_L2_PER_PARAM_TYPE = "clipl2perparamtype"


def build_optimizer(updater, gradient_normalization=None,
                    gradient_normalization_threshold=1.0,
                    weight_decay=0.0):
    """Chain gradient normalization (≡ GradientNormalization enum) +
    decoupled weightDecay + the updater into one optax transform."""
    import jax
    import jax.numpy as jnp

    chain = []
    gn = (gradient_normalization or "none").lower().replace("_", "")
    thr = float(gradient_normalization_threshold)
    if gn in ("clipelementwiseabsolutevalue",):
        chain.append(optax.clip(thr))
    elif gn in ("clipl2perlayer", "clipl2perparamtype"):
        # per-leaf L2 clip (param-type granularity: each leaf is one
        # parameter tensor, matching the reference's per-param-type clip)
        def per_leaf_clip(updates, state, params=None):
            del params
            def clipleaf(g):
                n = jnp.sqrt(jnp.sum(g * g) + 1e-12)
                return g * jnp.minimum(1.0, thr / n)
            return jax.tree_util.tree_map(clipleaf, updates), state
        chain.append(optax.GradientTransformation(lambda p: optax.EmptyState(), per_leaf_clip))
    elif gn in ("renormalizel2perlayer",):
        def renorm(updates, state, params=None):
            del params
            def norml(g):
                n = jnp.sqrt(jnp.sum(g * g) + 1e-12)
                return g / n
            return jax.tree_util.tree_map(norml, updates), state
        chain.append(optax.GradientTransformation(lambda p: optax.EmptyState(), renorm))
    elif gn in ("none",):
        pass
    else:
        raise ValueError(f"Unknown GradientNormalization '{gradient_normalization}'")

    if weight_decay:
        chain.append(optax.add_decayed_weights(float(weight_decay)))
    chain.append(updater.to_optax() if isinstance(updater, Updater) else updater)
    return optax.chain(*chain) if len(chain) > 1 else chain[0]
