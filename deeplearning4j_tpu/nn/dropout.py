"""Dropout variants (≡ org.deeplearning4j.nn.conf.dropout.* :
Dropout, GaussianDropout, GaussianNoise, AlphaDropout).

A layer's `dropOut` may be the reference's float shorthand (p = RETAIN
probability, inverted dropout) or one of these objects; either is applied
to the layer INPUT at train time inside the jitted step.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class IDropout:
    def apply(self, x, rng):
        raise NotImplementedError


class Dropout(IDropout):
    """p = retain probability (the reference's convention)."""

    def __init__(self, p):
        self.p = float(p)

    def apply(self, x, rng):
        if self.p <= 0.0 or self.p >= 1.0:
            return x
        keep = jax.random.bernoulli(rng, self.p, x.shape)
        return jnp.where(keep, x / self.p, 0.0).astype(x.dtype)


class GaussianDropout(IDropout):
    """Multiplicative N(1, sqrt(rate/(1-rate))) noise (≡ GaussianDropout)."""

    def __init__(self, rate):
        self.rate = float(rate)

    def apply(self, x, rng):
        if self.rate <= 0.0:
            return x
        std = (self.rate / (1.0 - self.rate)) ** 0.5
        noise = 1.0 + std * jax.random.normal(rng, x.shape, jnp.float32)
        return (x * noise.astype(x.dtype))


class GaussianNoise(IDropout):
    """Additive N(0, stddev) noise (≡ GaussianNoise)."""

    def __init__(self, stddev):
        self.stddev = float(stddev)

    def apply(self, x, rng):
        if self.stddev <= 0.0:
            return x
        return x + (self.stddev * jax.random.normal(rng, x.shape, jnp.float32)
                    ).astype(x.dtype)


class AlphaDropout(IDropout):
    """SELU-preserving dropout (≡ AlphaDropout): dropped units take the
    negative saturation value α′ and the output is affinely rescaled so the
    self-normalizing mean/variance survive. p = retain probability."""

    _ALPHA = 1.6732632423543772
    _SCALE = 1.0507009873554805

    def __init__(self, p):
        self.p = float(p)

    def apply(self, x, rng):
        p = self.p
        if p <= 0.0 or p >= 1.0:
            return x
        alpha_p = -self._ALPHA * self._SCALE
        a = (p + alpha_p ** 2 * p * (1.0 - p)) ** -0.5
        b = -a * alpha_p * (1.0 - p)
        keep = jax.random.bernoulli(rng, p, x.shape)
        y = jnp.where(keep, x, jnp.asarray(alpha_p, x.dtype))
        return (a * y + b).astype(x.dtype)


class SpatialDropout(IDropout):
    """≡ conf.dropout.SpatialDropout — drops ENTIRE feature maps: one
    Bernoulli draw per (example, channel), broadcast over the spatial or
    time axes. Internal layouts are channels-LAST (NHWC conv, (B, T, F)
    sequences), so the mask is (B, 1, ..., 1, C). p = retain probability,
    inverted scaling, matching the reference's convention."""

    def __init__(self, p):
        self.p = float(p)

    def apply(self, x, rng):
        if self.p <= 0.0 or self.p >= 1.0 or x.ndim < 2:
            return x
        mask_shape = (x.shape[0],) + (1,) * (x.ndim - 2) + (x.shape[-1],)
        keep = jax.random.bernoulli(rng, self.p, mask_shape)
        return jnp.where(keep, x / self.p, 0.0).astype(x.dtype)
