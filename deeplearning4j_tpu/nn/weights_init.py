"""Weight initialization (≡ deeplearning4j-nn :: weights.WeightInit enum).

fan_in/fan_out follow the reference's conventions: for a dense kernel
(nIn, nOut) fan_in=nIn; for a conv kernel (kh, kw, cin, cout) [we are
NHWC-native] fan_in = kh*kw*cin, fan_out = kh*kw*cout.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def _fans(shape):
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    rf = int(np.prod(shape[:-2]))
    return rf * shape[-2], rf * shape[-1]


def init_weight(key, shape, scheme="xavier", distribution=None, dtype=jnp.float32):
    scheme = str(scheme).lower()
    fan_in, fan_out = _fans(shape)

    def uni(limit):
        return jax.random.uniform(key, shape, dtype, -limit, limit)

    def norm(std):
        return std * jax.random.normal(key, shape, dtype)

    if scheme == "xavier":
        return norm(float(np.sqrt(2.0 / (fan_in + fan_out))))
    if scheme == "xavier_uniform":
        return uni(float(np.sqrt(6.0 / (fan_in + fan_out))))
    if scheme in ("relu", "he", "he_normal"):
        return norm(float(np.sqrt(2.0 / fan_in)))
    if scheme in ("relu_uniform", "he_uniform"):
        return uni(float(np.sqrt(6.0 / fan_in)))
    if scheme in ("lecun_normal", "normal"):
        # ND4J WeightInit.NORMAL is N(0, 1/sqrt(fanIn)) == LeCun normal.
        return norm(float(np.sqrt(1.0 / fan_in)))
    if scheme == "lecun_uniform":
        return uni(float(np.sqrt(3.0 / fan_in)))
    if scheme == "uniform":
        return uni(float(np.sqrt(1.0 / fan_in)))
    if scheme == "zero":
        return jnp.zeros(shape, dtype)
    if scheme == "ones":
        return jnp.ones(shape, dtype)
    if scheme == "constant":
        value = 0.0 if distribution is None else float(distribution)
        return jnp.full(shape, value, dtype)
    if scheme == "identity":
        if len(shape) != 2 or shape[0] != shape[1]:
            raise ValueError("IDENTITY init requires a square 2-D kernel")
        return jnp.eye(shape[0], dtype=dtype)
    if scheme == "distribution":
        if distribution is None:
            raise ValueError("WeightInit.DISTRIBUTION requires a distribution")
        kind = distribution.get("type", "normal")
        if kind == "normal":
            return distribution.get("mean", 0.0) + distribution.get("std", 1.0) * jax.random.normal(key, shape, dtype)
        if kind == "uniform":
            return jax.random.uniform(key, shape, dtype,
                                      distribution.get("lower", -1.0),
                                      distribution.get("upper", 1.0))
        raise ValueError(f"Unknown distribution type {kind}")
    if scheme in ("var_scaling_normal_fan_in",):
        return norm(float(np.sqrt(1.0 / fan_in)))
    if scheme in ("var_scaling_normal_fan_out",):
        return norm(float(np.sqrt(1.0 / fan_out)))
    if scheme in ("var_scaling_normal_fan_avg",):
        return norm(float(np.sqrt(2.0 / (fan_in + fan_out))))
    if scheme in ("var_scaling_uniform_fan_in",):
        return uni(float(np.sqrt(3.0 / fan_in)))
    if scheme in ("var_scaling_uniform_fan_out",):
        return uni(float(np.sqrt(3.0 / fan_out)))
    if scheme in ("var_scaling_uniform_fan_avg",):
        return uni(float(np.sqrt(6.0 / (fan_in + fan_out))))
    raise ValueError(f"Unknown WeightInit scheme '{scheme}'")


class WeightInit:
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    NORMAL = "normal"
    UNIFORM = "uniform"
    ZERO = "zero"
    ONES = "ones"
    CONSTANT = "constant"
    IDENTITY = "identity"
    DISTRIBUTION = "distribution"
    VAR_SCALING_NORMAL_FAN_IN = "var_scaling_normal_fan_in"
    VAR_SCALING_NORMAL_FAN_OUT = "var_scaling_normal_fan_out"
    VAR_SCALING_NORMAL_FAN_AVG = "var_scaling_normal_fan_avg"
    VAR_SCALING_UNIFORM_FAN_IN = "var_scaling_uniform_fan_in"
    VAR_SCALING_UNIFORM_FAN_OUT = "var_scaling_uniform_fan_out"
    VAR_SCALING_UNIFORM_FAN_AVG = "var_scaling_uniform_fan_avg"
