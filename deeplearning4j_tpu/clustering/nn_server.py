"""NearestNeighborsServer (≡ deeplearning4j-nearestneighbors-server ::
org.deeplearning4j.nearestneighbor.server.NearestNeighborsServer +
client model NearestNeighborRequest/NearestNeighborsResult).

Reference shape: a REST service loaded with a serialized INDArray corpus,
answering `POST /knn` (k nearest of an indexed corpus point) and
`POST /knnnew` (k nearest of a posted vector) via a VPTree.

TPU-first inversion: queries are answered by the batched exact-kNN GEMM
path (`clustering.vptree.knn` — one (Q, N) matmul + top-k on device),
not tree traversal; the VPTree remains available for host-only
deployments (`useVpTree=True`). Dependency-free stdlib http.server, like
the UI dashboard.

Endpoints (JSON):
- POST /knn     {"index": i, "k": k}            → {"results": [...]}
- POST /knnnew  {"arr": [[...]] | [...], "k": k} → {"results": [[...]]}
  (a single flat vector returns one result list, batched input a list
  per query — batching is free on the GEMM path)
- GET  /status  → {"points": N, "dim": D, "similarity": "..."}

Each result entry is {"index": i, "distance": d} sorted nearest-first.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from deeplearning4j_tpu.clustering.vptree import VPTree, knn

__all__ = ["NearestNeighborsServer"]


class NearestNeighborsServer:
    def __init__(self, points, similarity_function="euclidean", port=9000,
                 useVpTree=False, host="127.0.0.1"):
        self.points = np.asarray(points, np.float32)
        self.fn = str(similarity_function).lower()
        self.port = int(port)
        self.host = str(host)    # "0.0.0.0" to serve non-local clients
        # VPTree refuses 'dot' (not a metric — tree pruning would be
        # inexact); degrade to the exact batched GEMM path instead of
        # failing server construction.
        if useVpTree and self.fn == "dot":
            import sys
            print("NearestNeighborsServer: useVpTree ignored for 'dot' "
                  "(not a metric); serving via the exact batched knn path",
                  file=sys.stderr, flush=True)
            useVpTree = False
        self._tree = (VPTree(self.points, self.fn) if useVpTree else None)
        self._httpd = None
        self._thread = None

    # -- query core (usable without the HTTP layer) ----------------------
    def query_index(self, index, k):
        """k nearest of corpus point `index` (excluding itself)."""
        index = int(index)
        if not -self.points.shape[0] <= index < self.points.shape[0]:
            raise IndexError(f"index {index} out of range for "
                             f"{self.points.shape[0]} points")
        index %= self.points.shape[0]      # normalize so self-exclusion works
        idx, dist = self._query(self.points[index][None, :], k + 1)
        out = [{"index": int(i), "distance": float(d)}
               for i, d in zip(idx[0], dist[0]) if int(i) != index]
        return out[:k]

    def query_vectors(self, arr, k):
        arr = np.asarray(arr, np.float32)
        single = arr.ndim == 1
        idx, dist = self._query(arr[None, :] if single else arr, k)
        res = [[{"index": int(i), "distance": float(d)}
                for i, d in zip(row_i, row_d)]
               for row_i, row_d in zip(idx, dist)]
        return res[0] if single else res

    def _query(self, q, k):
        k = min(int(k), self.points.shape[0])
        if self._tree is not None:
            idx, dist = [], []
            for row in q:
                results, ds = self._tree.search(row, k)
                idx.append([r.getIndex() for r in results])
                dist.append(ds)
            return np.asarray(idx), np.asarray(dist)
        return knn(q, self.points, k, self.fn)

    # -- HTTP layer ------------------------------------------------------
    def start(self):
        server = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # silence request logging
                pass

            def _send(self, code, payload):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/status":
                    self._send(200, {"points": int(server.points.shape[0]),
                                     "dim": int(server.points.shape[1]),
                                     "similarity": server.fn})
                else:
                    self._send(404, {"error": "unknown path"})

            def do_POST(self):
                try:
                    n = int(self.headers.get("Content-Length", 0))
                    req = json.loads(self.rfile.read(n) or b"{}")
                    k = int(req.get("k", 1))
                    if self.path == "/knn":
                        self._send(200, {"results": server.query_index(
                            req["index"], k)})
                    elif self.path == "/knnnew":
                        self._send(200, {"results": server.query_vectors(
                            req["arr"], k)})
                    else:
                        self._send(404, {"error": "unknown path"})
                except Exception as e:  # noqa: BLE001 — report to client
                    self._send(400, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]   # resolves port=0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._thread.join(timeout=5)
            self._httpd = None
