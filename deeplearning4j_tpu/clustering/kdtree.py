"""KD-tree (≡ deeplearning4j-nearestneighbors ::
org.deeplearning4j.clustering.kdtree.KDTree).

Reference shape: ``new KDTree(dims)``, ``insert(INDArray)``,
``nn(INDArray)`` → (point, distance), ``knn(INDArray, k)``, and a
``delete`` the reference barely uses. Axis-cycling splits, branch-and-
bound search.

Host-side structure like VPTree (pointer-shaped); for batched/serving
queries prefer ``clustering.vptree.knn`` — one (Q, N) GEMM + top-k on
the MXU beats any tree walk at reference-era corpus sizes.
"""
from __future__ import annotations

import heapq

import numpy as np

__all__ = ["KDTree"]


class _KDNode:
    __slots__ = ("point", "left", "right")

    def __init__(self, point):
        self.point = point
        self.left = None
        self.right = None


class KDTree:
    def __init__(self, dims):
        self.dims = int(dims)
        self._root = None
        self._size = 0

    def size(self):
        return self._size

    def insert(self, point):
        p = np.asarray(point, np.float32).reshape(-1)
        if p.shape[0] != self.dims:
            raise ValueError(f"point has {p.shape[0]} dims, tree expects "
                             f"{self.dims}")
        self._size += 1
        if self._root is None:
            self._root = _KDNode(p)
            return
        node, depth = self._root, 0
        while True:
            axis = depth % self.dims
            side = "left" if p[axis] < node.point[axis] else "right"
            child = getattr(node, side)
            if child is None:
                setattr(node, side, _KDNode(p))
                return
            node, depth = child, depth + 1

    @staticmethod
    def _dist2(a, b):
        return float(((a - b) ** 2).sum())

    def nn(self, point):
        """Nearest neighbor: returns (point, distance)."""
        res = self.knn(point, 1)
        return res[0] if res else (None, float("inf"))

    def knn(self, point, k):
        """k nearest: [(point, distance)] sorted nearest-first."""
        q = np.asarray(point, np.float32).reshape(-1)
        if q.shape[0] != self.dims:
            raise ValueError(f"query has {q.shape[0]} dims, tree expects "
                             f"{self.dims}")
        k = min(int(k), self._size)
        if self._root is None or k <= 0:
            return []
        heap = []  # max-heap of (-squared_dist, counter, point)
        counter = 0
        # explicit stack (no recursion — a sorted-insert tree is O(n)
        # deep); `plane2` is the SQUARED split-plane distance that must
        # beat the current kth-best for the subtree to matter, re-checked
        # at pop time when tau is tightest. Comparisons stay in squared
        # space; sqrt only touches the final k results.
        stack = [(self._root, 0, None)]
        while stack:
            node, depth, plane2 = stack.pop()
            if node is None:
                continue
            tau2 = -heap[0][0] if len(heap) == k else float("inf")
            if plane2 is not None and plane2 > tau2:
                continue
            d2 = self._dist2(q, node.point)
            if len(heap) < k:
                heapq.heappush(heap, (-d2, counter, node.point))
                counter += 1
            elif d2 < -heap[0][0]:
                heapq.heapreplace(heap, (-d2, counter, node.point))
                counter += 1
            axis = depth % self.dims
            delta = float(q[axis] - node.point[axis])
            near, far = ((node.left, node.right) if delta < 0
                         else (node.right, node.left))
            stack.append((far, depth + 1, delta * delta))
            stack.append((near, depth + 1, None))   # popped first
        out = sorted(((-nd2, pt) for nd2, _, pt in heap), key=lambda t: t[0])
        return [(pt, float(np.sqrt(d2))) for d2, pt in out]
