"""t-SNE (≡ deeplearning4j :: org.deeplearning4j.plot.BarnesHutTsne /
Tsne + its Builder surface).

Reference shape: Barnes-Hut approximated gradients via a quad-tree
(``theta`` trades accuracy for CPU time), perplexity-calibrated input
affinities, early exaggeration, momentum switch, optional AdaGrad.

TPU-first inversion: the Barnes-Hut quad-tree exists because O(N²) is
slow on a CPU. On the MXU the O(N²) pairwise term IS the fast path —
blocked (rowBlock, N) GEMMs per iteration — so this implementation
computes EXACT t-SNE gradients entirely on device: perplexity
calibration is a vectorized per-row bisection (``lax.fori_loop``), and
the whole descent (early exaggeration, momentum schedule, gains/AdaGrad)
is one jitted ``lax.fori_loop``. ``theta`` is accepted for API parity
and ignored (exact ≡ theta=0).

Memory (round-5, VERDICT r4 weak #4): every O(N²) pass is ROW-BLOCKED —
peak device memory is the stored conditional-P matrix (N² fp32) plus
O(rowBlock·N) temporaries; the symmetrized P is never materialized (each
block reads P rows + P columns and symmetrizes on the fly). That puts
the one-chip (16 GB v5e) ceiling at the storage of P itself: N≈50k
(10 GB) fits with the default rowBlock=4096; N=20k (1.6 GB) is validated
end-to-end in tests. Beyond that the honest path is sparse-P (the
reference's 3·perplexity-neighbor approximation), not a bigger dense P.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.kmeans import _pairwise

__all__ = ["BarnesHutTsne", "Tsne"]


@functools.partial(jax.jit,
                   static_argnames=("perplexity", "n", "block", "iters"))
def _calibrated_p_rows(x, perplexity, n, block, iters=50):
    """UNsymmetrized conditional P (Npad, Npad), one row-block at a time:
    per block, a (block, Npad) distance GEMM + per-row bisection on the
    Gaussian precision so each row's conditional distribution has entropy
    log(perplexity). Rows/cols ≥ n (padding) are zero. Only (block, Npad)
    temporaries are ever live besides the output."""
    npad = x.shape[0]
    log_u = jnp.log(jnp.float32(perplexity))
    col_valid = jnp.arange(npad) < n

    def block_rows(b):
        r0 = b * block
        xb = jax.lax.dynamic_slice_in_dim(x, r0, block, 0)
        d2 = _pairwise(xb, x, "sqeuclidean")          # (block, Npad)
        rows = r0 + jnp.arange(block)
        dead = ((jnp.arange(npad)[None, :] == rows[:, None])
                | ~col_valid[None, :])                # self + padding

        def row_entropy(beta):
            logits = jnp.where(dead, -jnp.inf, -d2 * beta)
            p = jax.nn.softmax(logits, axis=-1)
            h = -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0), -1)
            return h, p

        def body(_, state):
            beta, lo, hi = state
            h, _ = row_entropy(beta)
            too_high = (h > log_u)[:, None]   # entropy too high -> raise
            lo = jnp.where(too_high, beta, lo)
            hi = jnp.where(too_high, hi, beta)
            beta = jnp.where(jnp.isinf(hi), beta * 2.0,
                             jnp.where(jnp.isinf(lo), beta / 2.0,
                                       (lo + hi) / 2.0))
            return beta, lo, hi

        beta0 = jnp.ones((block, 1), jnp.float32)
        beta, _, _ = jax.lax.fori_loop(
            0, iters, body,
            (beta0, jnp.full((block, 1), -jnp.inf),
             jnp.full((block, 1), jnp.inf)))
        _, p = row_entropy(beta)
        return jnp.where((rows < n)[:, None], p, 0.0)

    nb = npad // block
    return jax.lax.map(block_rows, jnp.arange(nb)).reshape(npad, npad)


@functools.partial(jax.jit, static_argnames=(
    "max_iter", "stop_lying", "switch_momentum", "use_adagrad", "n",
    "block"))
def _descend(p_cond, y0, n, block, max_iter, stop_lying, switch_momentum,
             lr, momentum, final_momentum, use_adagrad):
    """Blocked exact descent. Per iteration: pass 1 accumulates the
    student-t partition Z block-by-block; pass 2 emits gradient rows per
    block, symmetrizing P on the fly from the stored conditional matrix
    (P rows + P columns — the (Npad, Npad) symmetric P never exists)."""
    npad = y0.shape[0]
    nb = npad // block
    valid = jnp.arange(npad) < n
    inv2n = 1.0 / (2.0 * jnp.float32(n))

    def num_block(y, b):
        r0 = b * block
        yb = jax.lax.dynamic_slice_in_dim(y, r0, block, 0)
        d2 = _pairwise(yb, y, "sqeuclidean")          # (block, Npad)
        rows = r0 + jnp.arange(block)
        mask = ((jnp.arange(npad)[None, :] != rows[:, None])
                & valid[None, :] & (rows < n)[:, None])
        num = jnp.where(mask, 1.0 / (1.0 + d2), 0.0)  # student-t kernel
        return num, r0

    def body(it, state):
        y, vel, gains, hist = state
        z = jax.lax.fori_loop(
            0, nb, lambda b, z: z + num_block(y, b)[0].sum(),
            jnp.float32(0.0))
        z = jnp.maximum(z, 1e-12)
        exag = jnp.where(it < stop_lying, 12.0, 1.0)

        def grad_block(b):
            num, r0 = num_block(y, b)
            p_rows = jax.lax.dynamic_slice_in_dim(p_cond, r0, block, 0)
            p_cols = jax.lax.dynamic_slice_in_dim(p_cond, r0, block, 1)
            p = jnp.maximum((p_rows + p_cols.T) * inv2n, 1e-12)
            q = jnp.maximum(num / z, 1e-12)
            pq = (exag * p - q) * num                 # (block, Npad)
            yb = jax.lax.dynamic_slice_in_dim(y, r0, block, 0)
            return 4.0 * (jnp.sum(pq, -1, keepdims=True) * yb - pq @ y)

        grad = jax.lax.map(grad_block, jnp.arange(nb)).reshape(npad, -1)
        mom = jnp.where(it < switch_momentum, momentum, final_momentum)
        if use_adagrad:
            hist = hist + grad * grad
            step = lr * grad / jnp.sqrt(hist + 1e-8)
            vel = mom * vel - step
        else:
            # classic vdM adaptive gains
            same_sign = (jnp.sign(grad) == jnp.sign(vel))
            gains = jnp.maximum(
                jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
            vel = mom * vel - lr * gains * grad
        y = y + vel
        center = jnp.sum(jnp.where(valid[:, None], y, 0.0), 0,
                         keepdims=True) / n
        y = jnp.where(valid[:, None], y - center, 0.0)
        return y, vel, gains, hist

    zeros = jnp.zeros_like(y0)
    y, _, _, _ = jax.lax.fori_loop(
        0, max_iter, body, (y0, zeros, jnp.ones_like(y0), zeros))
    return y


class BarnesHutTsne:
    """Builder-built (≡ BarnesHutTsne.Builder). ``theta`` accepted and
    ignored — gradients are exact on the MXU (see module docstring)."""

    class Builder:
        def __init__(self):
            self._max_iter = 1000
            self._theta = 0.5
            self._normalize = True
            self._lr = 200.0
            self._use_adagrad = False
            self._perplexity = 30.0
            self._num_dim = 2
            self._stop_lying = 250
            self._switch_momentum = 250
            self._momentum = 0.5
            self._final_momentum = 0.8
            self._seed = 42
            self._row_block = 4096

        def setMaxIter(self, v):
            self._max_iter = int(v); return self

        def theta(self, v):
            self._theta = float(v); return self

        def normalize(self, v):
            self._normalize = bool(v); return self

        def learningRate(self, v):
            self._lr = float(v); return self

        def useAdaGrad(self, v):
            self._use_adagrad = bool(v); return self

        def perplexity(self, v):
            self._perplexity = float(v); return self

        def numDimension(self, v):
            self._num_dim = int(v); return self

        def stopLyingIteration(self, v):
            self._stop_lying = int(v); return self

        def setMomentum(self, v):
            self._momentum = float(v); return self

        def setFinalMomentum(self, v):
            self._final_momentum = float(v); return self

        def setSwitchMomentumIteration(self, v):
            self._switch_momentum = int(v); return self

        def seed(self, v):
            self._seed = int(v); return self

        def rowBlockSize(self, v):
            """Rows per O(N²)-pass block — caps peak temporaries at
            O(rowBlock · N) (no reference equivalent; TPU memory knob)."""
            self._row_block = int(v); return self

        def build(self):
            return BarnesHutTsne(self)

    def __init__(self, b):
        self._b = b
        self._y = None

    def fit(self, x):
        x = np.asarray(x, np.float32)
        b = self._b
        if b._normalize:
            x = (x - x.mean(0)) / np.maximum(x.std(0), 1e-12)
        n = x.shape[0]
        block = max(1, min(b._row_block, n))
        npad = -(-n // block) * block
        if npad != n:
            x = np.pad(x, ((0, npad - n), (0, 0)))
        perp = min(b._perplexity, max((n - 1) / 3.0, 1.0))
        p_cond = _calibrated_p_rows(jnp.asarray(x), float(perp), n, block)
        key = jax.random.PRNGKey(b._seed)
        y0 = 1e-4 * jax.random.normal(key, (npad, b._num_dim), jnp.float32)
        y = _descend(p_cond, y0, n, block, b._max_iter, b._stop_lying,
                     b._switch_momentum, jnp.float32(b._lr),
                     jnp.float32(b._momentum),
                     jnp.float32(b._final_momentum), b._use_adagrad)
        self._y = np.asarray(y)[:n]
        return self

    def getData(self):
        return self._y

    def saveAsFile(self, labels, path):
        """≡ saveAsFile: one "y0 y1 ... label" line per point."""
        with open(path, "w") as f:
            for row, lab in zip(self._y, labels):
                f.write(" ".join(f"{v:.6f}" for v in row) + f" {lab}\n")


Tsne = BarnesHutTsne  # ≡ plot.Tsne — same surface, exact solver
