"""t-SNE (≡ deeplearning4j :: org.deeplearning4j.plot.BarnesHutTsne /
Tsne + its Builder surface).

Reference shape: Barnes-Hut approximated gradients via a quad-tree
(``theta`` trades accuracy for CPU time), perplexity-calibrated input
affinities, early exaggeration, momentum switch, optional AdaGrad.

TPU-first inversion: the Barnes-Hut quad-tree exists because O(N²) is
slow on a CPU. On the MXU the O(N²) pairwise term IS the fast path —
one (N, N) GEMM per iteration — so this implementation computes EXACT
t-SNE gradients entirely on device: perplexity calibration is a
vectorized per-row bisection (``lax.fori_loop``), and the whole descent
(early exaggeration, momentum schedule, gains/AdaGrad) is one jitted
``lax.fori_loop``. ``theta`` is accepted for API parity and ignored
(exact ≡ theta=0); at reference-era N (≤ ~50k points) this is faster
than the JVM tree walk while being more accurate.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.kmeans import _pairwise

__all__ = ["BarnesHutTsne", "Tsne"]


def _sq_dists(x):
    return _pairwise(x, x, "sqeuclidean")   # shared impl (kmeans)


@functools.partial(jax.jit, static_argnames=("perplexity", "iters"))
def _calibrated_p(x, perplexity, iters=50):
    """Per-row bisection on the Gaussian precision so each row's
    conditional distribution has entropy log(perplexity)."""
    n = x.shape[0]
    d2 = _sq_dists(x)
    eye = jnp.eye(n, dtype=bool)
    log_u = jnp.log(jnp.float32(perplexity))

    def row_entropy(beta):
        # beta: (N, 1); returns (entropy (N,), P (N, N)) with diag zeroed
        logits = jnp.where(eye, -jnp.inf, -d2 * beta)
        p = jax.nn.softmax(logits, axis=-1)
        h = -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0), -1)
        return h, p

    def body(_, state):
        beta, lo, hi = state
        h, _ = row_entropy(beta)
        too_high = (h > log_u)[:, None]  # entropy too high -> raise beta
        lo = jnp.where(too_high, beta, lo)
        hi = jnp.where(too_high, hi, beta)
        beta = jnp.where(jnp.isinf(hi), beta * 2.0,
                         jnp.where(jnp.isinf(lo), beta / 2.0,
                                   (lo + hi) / 2.0))
        return beta, lo, hi

    beta0 = jnp.ones((n, 1), jnp.float32)
    beta, _, _ = jax.lax.fori_loop(
        0, iters, body,
        (beta0, jnp.full((n, 1), -jnp.inf), jnp.full((n, 1), jnp.inf)))
    _, p = row_entropy(beta)
    p = (p + p.T) / (2.0 * n)                       # symmetrize
    return jnp.maximum(p, 1e-12)


@functools.partial(jax.jit, static_argnames=(
    "max_iter", "stop_lying", "switch_momentum", "use_adagrad"))
def _descend(p, y0, max_iter, stop_lying, switch_momentum, lr,
             momentum, final_momentum, use_adagrad):
    n = y0.shape[0]
    eye = jnp.eye(n, dtype=bool)

    def body(it, state):
        y, vel, gains, hist = state
        d2 = _sq_dists(y)
        num = jnp.where(eye, 0.0, 1.0 / (1.0 + d2))     # student-t kernel
        q = jnp.maximum(num / jnp.maximum(num.sum(), 1e-12), 1e-12)
        exag = jnp.where(it < stop_lying, 12.0, 1.0)
        pq = (exag * p - q) * num                        # (N, N)
        grad = 4.0 * (jnp.sum(pq, -1, keepdims=True) * y - pq @ y)
        mom = jnp.where(it < switch_momentum, momentum, final_momentum)
        if use_adagrad:
            hist = hist + grad * grad
            step = lr * grad / jnp.sqrt(hist + 1e-8)
            vel = mom * vel - step
        else:
            # classic vdM adaptive gains
            same_sign = (jnp.sign(grad) == jnp.sign(vel))
            gains = jnp.maximum(
                jnp.where(same_sign, gains * 0.8, gains + 0.2), 0.01)
            vel = mom * vel - lr * gains * grad
        y = y + vel
        y = y - y.mean(0, keepdims=True)
        return y, vel, gains, hist

    zeros = jnp.zeros_like(y0)
    y, _, _, _ = jax.lax.fori_loop(
        0, max_iter, body, (y0, zeros, jnp.ones_like(y0), zeros))
    return y


class BarnesHutTsne:
    """Builder-built (≡ BarnesHutTsne.Builder). ``theta`` accepted and
    ignored — gradients are exact on the MXU (see module docstring)."""

    class Builder:
        def __init__(self):
            self._max_iter = 1000
            self._theta = 0.5
            self._normalize = True
            self._lr = 200.0
            self._use_adagrad = False
            self._perplexity = 30.0
            self._num_dim = 2
            self._stop_lying = 250
            self._switch_momentum = 250
            self._momentum = 0.5
            self._final_momentum = 0.8
            self._seed = 42

        def setMaxIter(self, v):
            self._max_iter = int(v); return self

        def theta(self, v):
            self._theta = float(v); return self

        def normalize(self, v):
            self._normalize = bool(v); return self

        def learningRate(self, v):
            self._lr = float(v); return self

        def useAdaGrad(self, v):
            self._use_adagrad = bool(v); return self

        def perplexity(self, v):
            self._perplexity = float(v); return self

        def numDimension(self, v):
            self._num_dim = int(v); return self

        def stopLyingIteration(self, v):
            self._stop_lying = int(v); return self

        def setMomentum(self, v):
            self._momentum = float(v); return self

        def setFinalMomentum(self, v):
            self._final_momentum = float(v); return self

        def setSwitchMomentumIteration(self, v):
            self._switch_momentum = int(v); return self

        def seed(self, v):
            self._seed = int(v); return self

        def build(self):
            return BarnesHutTsne(self)

    def __init__(self, b):
        self._b = b
        self._y = None

    def fit(self, x):
        x = np.asarray(x, np.float32)
        b = self._b
        if b._normalize:
            x = (x - x.mean(0)) / np.maximum(x.std(0), 1e-12)
        n = x.shape[0]
        perp = min(b._perplexity, max((n - 1) / 3.0, 1.0))
        p = _calibrated_p(jnp.asarray(x), float(perp))
        key = jax.random.PRNGKey(b._seed)
        y0 = 1e-4 * jax.random.normal(key, (n, b._num_dim), jnp.float32)
        y = _descend(p, y0, b._max_iter, b._stop_lying, b._switch_momentum,
                     jnp.float32(b._lr), jnp.float32(b._momentum),
                     jnp.float32(b._final_momentum), b._use_adagrad)
        self._y = np.asarray(y)
        return self

    def getData(self):
        return self._y

    def saveAsFile(self, labels, path):
        """≡ saveAsFile: one "y0 y1 ... label" line per point."""
        with open(path, "w") as f:
            for row, lab in zip(self._y, labels):
                f.write(" ".join(f"{v:.6f}" for v in row) + f" {lab}\n")


Tsne = BarnesHutTsne  # ≡ plot.Tsne — same surface, exact solver
