"""Clustering / manifold / nearest-neighbors (≡ deeplearning4j-clustering,
deeplearning4j-nearestneighbors, org.deeplearning4j.plot)."""
from deeplearning4j_tpu.clustering.kmeans import (Cluster, ClusterSet,
                                                  KMeansClustering, Point,
                                                  PointClassification)
from deeplearning4j_tpu.clustering.kdtree import KDTree
from deeplearning4j_tpu.clustering.nn_server import NearestNeighborsServer
from deeplearning4j_tpu.clustering.tsne import BarnesHutTsne, Tsne
from deeplearning4j_tpu.clustering.vptree import DataPoint, VPTree, knn

__all__ = ["KMeansClustering", "Point", "Cluster", "ClusterSet",
           "PointClassification", "BarnesHutTsne", "Tsne", "VPTree",
           "DataPoint", "knn", "NearestNeighborsServer", "KDTree"]
