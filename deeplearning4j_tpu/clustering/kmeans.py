"""KMeans clustering (≡ deeplearning4j-clustering ::
org.deeplearning4j.clustering.kmeans.KMeansClustering +
cluster.Point/Cluster/ClusterSet/PointClassification).

Reference shape: ``KMeansClustering.setup(k, maxIter, distanceFn)`` →
``applyTo(List<Point>)`` → ``ClusterSet`` (iterative Lloyd refinement on
the JVM, one distance computation per point per cluster per iteration,
optionally ``useKmeansPlusPlus`` seeding).

TPU-first inversion: the whole Lloyd loop is ONE jitted
``lax.while_loop`` over static-shape tensors. The (N, K) distance matrix
is a single ``X @ Cᵀ`` GEMM on the MXU per iteration (‖x‖² − 2x·c + ‖c‖²
for euclidean), assignments are an argmin, and the new centers are a
segment-sum (one-hot matmul — also MXU) — no per-point host loop exists
anywhere. k-means++ seeding runs as a ``lax.fori_loop`` of K distance
updates on device with a seeded PRNG stream.

Convergence matches the reference's ``ClusteringStrategy`` surface:
either a fixed ``maxIterationCount`` or a ``minDistributionVariationRate``
(fraction of points that changed cluster between iterations).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Point", "Cluster", "ClusterSet", "PointClassification",
           "KMeansClustering"]


class Point:
    """≡ clustering.cluster.Point — an id/label-carrying vector."""

    def __init__(self, array, id=None, label=None):
        self.array = np.asarray(array, np.float32).reshape(-1)
        self.id = id
        self.label = label

    def getArray(self):
        return self.array

    def getId(self):
        return self.id

    def getLabel(self):
        return self.label

    @staticmethod
    def toPoints(matrix):
        """≡ Point.toPoints(INDArray): one Point per row."""
        m = np.asarray(matrix, np.float32)
        return [Point(row, id=str(i)) for i, row in enumerate(m)]


class Cluster:
    def __init__(self, id, center):
        self.id = id
        self._center = np.asarray(center, np.float32)
        self._points = []

    def getCenter(self):
        return self._center

    def getPoints(self):
        return self._points

    def getId(self):
        return self.id

    def addPoint(self, point):
        self._points.append(point)


class PointClassification:
    """≡ cluster.PointClassification (cluster, distance, moved-flag)."""

    def __init__(self, cluster, distance, new_location):
        self._cluster = cluster
        self._distance = float(distance)
        self._new_location = bool(new_location)

    def getCluster(self):
        return self._cluster

    def getDistanceFromCenter(self):
        return self._distance

    def isNewLocation(self):
        return self._new_location


def _pairwise(x, c, distance):
    """(N, D) x (K, D) -> (N, K) distances. euclidean rides the MXU."""
    if distance in ("euclidean", "sqeuclidean"):
        x2 = jnp.sum(x * x, -1, keepdims=True)           # (N, 1)
        c2 = jnp.sum(c * c, -1)                          # (K,)
        d2 = jnp.maximum(x2 - 2.0 * (x @ c.T) + c2, 0.0)
        return d2 if distance == "sqeuclidean" else jnp.sqrt(d2)
    if distance == "manhattan":
        return jnp.abs(x[:, None, :] - c[None, :, :]).sum(-1)
    if distance == "cosinesimilarity":  # distance = 1 - cosine
        xn = x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-12)
        cn = c / jnp.maximum(jnp.linalg.norm(c, axis=-1, keepdims=True), 1e-12)
        return 1.0 - xn @ cn.T
    if distance == "dot":
        return -(x @ c.T)
    raise ValueError(f"unknown distance function: {distance!r}")


@functools.partial(jax.jit, static_argnames=("k", "distance"))
def _kmeanspp_init(x, key, k, distance):
    """k-means++ seeding as a fori_loop of device distance updates."""
    n = x.shape[0]
    k0, key = jax.random.split(key)
    first = jax.random.randint(k0, (), 0, n)
    centers = jnp.zeros((k, x.shape[1]), x.dtype).at[0].set(x[first])
    d2 = _pairwise(x, centers[:1], "sqeuclidean")[:, 0]

    def body(i, state):
        centers, d2, key = state
        key, sub = jax.random.split(key)
        p = d2 / jnp.maximum(d2.sum(), 1e-12)
        idx = jax.random.choice(sub, n, p=p)
        centers = centers.at[i].set(x[idx])
        nd = _pairwise(x, x[idx][None, :], "sqeuclidean")[:, 0]
        return centers, jnp.minimum(d2, nd), key

    centers, _, _ = jax.lax.fori_loop(1, k, body, (centers, d2, key))
    del distance  # seeding always uses squared euclidean, as the reference
    return centers


@functools.partial(jax.jit,
                   static_argnames=("k", "distance", "max_iter", "min_var"))
def _lloyd(x, centers0, k, distance, max_iter, min_var):
    """Whole Lloyd refinement as ONE while_loop; returns (centers, assign,
    iterations). Empty clusters keep their previous center (reference's
    allowEmptyClusters=True behavior; False is handled by the caller via
    farthest-point reseeding between convergence checks)."""
    n = x.shape[0]

    def assign_of(c):
        return jnp.argmin(_pairwise(x, c, distance), axis=-1)

    def cond(state):
        _, _, changed_rate, it = state
        return jnp.logical_and(it < max_iter, changed_rate > min_var)

    def body(state):
        centers, assign, _, it = state
        onehot = jax.nn.one_hot(assign, k, dtype=x.dtype)   # (N, K)
        counts = onehot.sum(0)                              # (K,)
        sums = onehot.T @ x                                 # (K, D) on MXU
        new_centers = jnp.where(counts[:, None] > 0,
                                sums / jnp.maximum(counts[:, None], 1.0),
                                centers)
        new_assign = assign_of(new_centers)
        changed = jnp.mean((new_assign != assign).astype(jnp.float32))
        return new_centers, new_assign, changed, it + 1

    a0 = assign_of(centers0)
    centers, assign, _, it = jax.lax.while_loop(
        cond, body, (centers0, a0, jnp.float32(1.0), jnp.int32(0)))
    return centers, assign, it


class ClusterSet:
    def __init__(self, clusters, distance):
        self._clusters = clusters
        self._distance = distance

    def getClusters(self):
        return self._clusters

    def getClusterCount(self):
        return len(self._clusters)

    def classifyPoint(self, point):
        """≡ ClusterSet.classifyPoint: nearest cluster + distance."""
        arr = point.array if isinstance(point, Point) else \
            np.asarray(point, np.float32).reshape(-1)
        centers = np.stack([c.getCenter() for c in self._clusters])
        d = np.asarray(_pairwise(jnp.asarray(arr[None, :]),
                                 jnp.asarray(centers), self._distance))[0]
        idx = int(d.argmin())
        return PointClassification(self._clusters[idx], d[idx], True)


class KMeansClustering:
    """≡ kmeans.KMeansClustering. Build via ``setup(...)``, run via
    ``applyTo(points)`` where points is a list[Point] or an (N, D) array."""

    def __init__(self, k, max_iter, distance, inverse=False,
                 min_distribution_variation_rate=0.0,
                 allow_empty_clusters=True, use_kmeans_plus_plus=False,
                 seed=123):
        self.k = int(k)
        self.max_iter = int(max_iter)
        # reference distance-function names are e.g. "euclidean",
        # "cosinesimilarity", "manhattan"; `inverse` marks similarity fns
        self.distance = str(distance).lower()
        if inverse and self.distance not in ("cosinesimilarity", "dot"):
            raise ValueError("inverse=True expects a similarity function")
        self.min_var = float(min_distribution_variation_rate)
        self.allow_empty = bool(allow_empty_clusters)
        self.use_pp = bool(use_kmeans_plus_plus)
        self.seed = int(seed)

    # -- reference factory surface --------------------------------------
    @staticmethod
    def setup(clusterCount, maxIterationCount=None, distanceFunction="euclidean",
              inverse=False, minDistributionVariationRate=None,
              allowEmptyClusters=True, useKMeansPlusPlus=False, seed=123):
        """≡ KMeansClustering.setup overloads: pass maxIterationCount for
        fixed-iteration mode, or minDistributionVariationRate for
        variation-converged mode (both is fine — first bound wins)."""
        if maxIterationCount is None and minDistributionVariationRate is None:
            raise ValueError("need maxIterationCount or "
                             "minDistributionVariationRate")
        return KMeansClustering(
            clusterCount,
            maxIterationCount if maxIterationCount is not None else 1000,
            distanceFunction, inverse=inverse,
            min_distribution_variation_rate=(
                minDistributionVariationRate or 0.0),
            allow_empty_clusters=allowEmptyClusters,
            use_kmeans_plus_plus=useKMeansPlusPlus, seed=seed)

    def applyTo(self, points):
        pts = points
        if isinstance(points, (list, tuple)):
            x_np = np.stack([p.array for p in points])
        else:
            x_np = np.asarray(points, np.float32)
            pts = None
        if x_np.shape[0] < self.k:
            raise ValueError(
                f"need >= k={self.k} points, got {x_np.shape[0]}")
        x = jnp.asarray(x_np)
        key = jax.random.PRNGKey(self.seed)
        if self.use_pp:
            centers0 = _kmeanspp_init(x, key, self.k, self.distance)
        else:
            perm = jax.random.permutation(key, x_np.shape[0])[: self.k]
            centers0 = x[perm]
        centers, assign, _ = _lloyd(x, centers0, self.k, self.distance,
                                    self.max_iter, self.min_var)
        if not self.allow_empty:
            # reseed any empty cluster at the globally farthest point and
            # re-refine; RE-CHECK because refinement can re-empty a
            # cluster. Bounded retries, then a forced reassignment that
            # guarantees the contract.
            for _ in range(3):
                assign_np = np.asarray(assign)
                counts = np.bincount(assign_np, minlength=self.k)
                if not (counts == 0).any():
                    break
                centers_np = np.asarray(centers)
                d = np.asarray(_pairwise(x, jnp.asarray(centers_np),
                                         self.distance))
                far = np.argsort(-d.min(-1))
                empties = np.flatnonzero(counts == 0)
                for j, ci in enumerate(empties):
                    centers_np[ci] = x_np[far[j]]
                centers, assign, _ = _lloyd(
                    x, jnp.asarray(centers_np), self.k, self.distance,
                    self.max_iter, self.min_var)
            assign_np = np.asarray(assign)
            counts = np.bincount(assign_np, minlength=self.k)
            if (counts == 0).any():
                # forced repair: hand each empty cluster the point that is
                # farthest from its current center, taken from a cluster
                # that can spare one; centers become those points
                centers_np = np.asarray(centers)
                d = np.asarray(_pairwise(x, jnp.asarray(centers_np),
                                         self.distance))
                for ci in np.flatnonzero(counts == 0):
                    own = d[np.arange(len(assign_np)),
                            assign_np]            # dist to assigned center
                    donors = counts[assign_np] > 1
                    pick = int(np.argmax(np.where(donors, own, -np.inf)))
                    counts[assign_np[pick]] -= 1
                    assign_np[pick] = ci
                    counts[ci] = 1
                    centers_np[ci] = x_np[pick]
                centers, assign = jnp.asarray(centers_np), assign_np
        centers_np = np.asarray(centers)
        assign_np = np.asarray(assign)
        clusters = [Cluster(i, centers_np[i]) for i in range(self.k)]
        if pts is None:
            pts = Point.toPoints(x_np)
        for p, a in zip(pts, assign_np):
            clusters[int(a)].addPoint(p)
        return ClusterSet(clusters, self.distance)
