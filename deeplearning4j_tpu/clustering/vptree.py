"""Vantage-point tree kNN (≡ deeplearning4j-nearestneighbors ::
org.deeplearning4j.clustering.vptree.VPTree + sptree.DataPoint).

Reference shape: ``new VPTree(items, "euclidean", invert)`` builds a
metric tree on the JVM; ``search(target, k, results, distances)`` fills
result lists by branch-and-bound traversal.

Two paths here:

- ``VPTree`` — API-parity host-side tree (numpy): median-split
  vantage-point construction, triangle-inequality pruned search. Useful
  when single queries trickle in on the host.
- ``knn(queries, k)`` — the TPU-first path: ONE (Q, N) distance GEMM on
  the MXU + ``lax.top_k``. At reference-era corpus sizes (≤ a few
  million vectors) a single fused matmul+top-k beats pointer-chasing
  tree traversal by orders of magnitude, and it batches over queries —
  this is what ``NearestNeighborsServer``-style serving should use.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from deeplearning4j_tpu.clustering.kmeans import _pairwise

__all__ = ["DataPoint", "VPTree", "knn"]


class DataPoint:
    """≡ clustering.sptree.DataPoint (index + vector)."""

    def __init__(self, index, point):
        self.index = int(index)
        self.point = np.asarray(point, np.float32).reshape(-1)

    def getIndex(self):
        return self.index

    def getPoint(self):
        return self.point


def _dist_np(x, items, fn):
    if fn == "euclidean":
        return np.sqrt(np.maximum(((items - x) ** 2).sum(-1), 0.0))
    if fn == "manhattan":
        return np.abs(items - x).sum(-1)
    if fn == "cosinesimilarity":
        xn = x / max(np.linalg.norm(x), 1e-12)
        it = items / np.maximum(
            np.linalg.norm(items, axis=-1, keepdims=True), 1e-12)
        return 1.0 - it @ xn
    if fn == "dot":
        return -(items @ x)
    raise ValueError(f"unknown similarity function: {fn!r}")


@functools.partial(jax.jit, static_argnames=("k", "fn"))
def _knn_device(queries, items, k, fn):
    d = _pairwise(queries, items, fn)   # shared with kmeans — one impl
    neg, idx = jax.lax.top_k(-d, k)
    return idx, -neg


def knn(queries, items, k, similarity_function="euclidean"):
    """Batched exact kNN on device: returns (indices (Q,k), distances
    (Q,k)). One MXU GEMM + top-k; no tree needed."""
    q = jnp.asarray(np.asarray(queries, np.float32))
    if q.ndim == 1:
        q = q[None, :]
    it = jnp.asarray(np.asarray(items, np.float32))
    k = min(int(k), it.shape[0])
    idx, d = _knn_device(q, it, k, str(similarity_function).lower())
    return np.asarray(idx), np.asarray(d)


class _Node:
    __slots__ = ("index", "threshold", "inside", "outside", "bucket")

    def __init__(self, index, threshold=0.0, inside=None, outside=None,
                 bucket=None):
        self.index = index
        self.threshold = threshold
        self.inside = inside
        self.outside = outside
        self.bucket = bucket  # leaf: indices scanned linearly at query


class VPTree:
    """≡ vptree.VPTree(items, similarityFunction, invert). ``invert``
    mirrors the reference flag for similarity (vs distance) functions —
    cosine/dot are already converted to distances internally, so invert
    only validates intent."""

    def __init__(self, items, similarity_function="euclidean", invert=False,
                 seed=123):
        if isinstance(items, (list, tuple)) and items and \
                isinstance(items[0], DataPoint):
            self.items = np.stack([p.point for p in items])
        else:
            self.items = np.asarray(items, np.float32)
        self.fn = str(similarity_function).lower()
        if invert and self.fn not in ("cosinesimilarity", "dot"):
            raise ValueError("invert=True expects a similarity function")
        # Tree search is only EXACT for true metrics — the branch-and-bound
        # pruning rule IS the triangle inequality (ADVICE r4). 'dot' has no
        # metric form: refuse it here (knn() below is the exact batched
        # path for it). 'cosinesimilarity' (1-cos) is not a metric either,
        # but chord distance ||x̂-ŷ|| on the unit sphere is, and it ranks
        # identically (chord² = 2·(1-cos)): the tree internally uses
        # euclidean over normalized vectors and converts reported
        # distances back to the 1-cos form.
        if self.fn == "dot":
            raise ValueError(
                "VPTree: 'dot' is not a metric, so tree pruning would "
                "return inexact neighbors — use clustering.vptree.knn() "
                "(exact batched GEMM + top-k) for dot-product similarity")
        if self.fn == "cosinesimilarity":
            self._tree_items = self.items / np.maximum(
                np.linalg.norm(self.items, axis=-1, keepdims=True), 1e-12)
            self._tree_fn = "euclidean"
        else:
            self._tree_items = self.items
            self._tree_fn = self.fn
        self._rng = np.random.RandomState(seed)
        self._root = self._build(list(range(self.items.shape[0])))

    def _build(self, idxs):
        if not idxs:
            return None
        if len(idxs) == 1:
            return _Node(idxs[0])
        vp = idxs[self._rng.randint(len(idxs))]
        rest = [i for i in idxs if i != vp]
        d = _dist_np(self._tree_items[vp], self._tree_items[rest],
                     self._tree_fn)
        med = float(np.median(d))
        inside = [rest[i] for i in range(len(rest)) if d[i] < med]
        outside = [rest[i] for i in range(len(rest)) if d[i] >= med]
        if not inside or not outside:
            # degenerate split (all points on the median, e.g. duplicates):
            # recursing with only the vp removed would be O(N)-deep, so
            # store the rest as a flat leaf bucket scanned at query time
            return _Node(vp, bucket=rest)
        return _Node(vp, med, self._build(inside), self._build(outside))

    def search(self, target, k, results=None, distances=None):
        """≡ VPTree.search: fills `results` (DataPoint) and `distances`
        lists, nearest first; also returns (results, distances)."""
        target = np.asarray(target, np.float32).reshape(-1)
        if self.fn == "cosinesimilarity":   # search in the metric space
            target = target / max(np.linalg.norm(target), 1e-12)
        k = min(int(k), self.items.shape[0])
        # best-first branch-and-bound with a simple max-heap of size k
        import heapq
        heap = []  # (-distance, index)

        def consider(idx, d):
            if len(heap) < k:
                heapq.heappush(heap, (-d, idx))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, idx))

        def visit(node):
            if node is None:
                return
            d = float(_dist_np(target, self._tree_items[node.index][None, :],
                               self._tree_fn)[0])
            consider(node.index, d)
            if node.bucket is not None:  # degenerate leaf: vectorized scan
                ds = _dist_np(target, self._tree_items[node.bucket],
                              self._tree_fn)
                for i, bd in zip(node.bucket, ds):
                    consider(i, float(bd))
                return
            tau = -heap[0][0] if len(heap) == k else np.inf
            if node.inside is None and node.outside is None:
                return
            if d < node.threshold:
                visit(node.inside)
                if d + tau >= node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau <= node.threshold:
                    visit(node.inside)

        visit(self._root)
        order = sorted(((-nd, i) for nd, i in heap))
        if results is None:
            results = []
        if distances is None:
            distances = []
        for d, i in order:
            results.append(DataPoint(i, self.items[i]))
            # report in the caller's distance form: chord² = 2·(1-cos)
            distances.append(float(d * d / 2.0)
                             if self.fn == "cosinesimilarity" else float(d))
        return results, distances
