"""ParallelWrapper (≡ deeplearning4j-parallel-wrapper ::
parallelism.ParallelWrapper) — synchronous data-parallel training.

The reference clones the model per GPU, runs workers on threads, and merges
gradients through EncodedGradientsAccumulator over Aeron/NCCL. TPU-native
inversion: ONE SPMD program — parameters replicated over the `dp` mesh
axis, batch sharded on dim 0, and the gradient all-reduce is inserted by
XLA as an ICI psum inside the SAME fused step (no accumulator thread, no
encoding; see compression.py for the optional threshold-encoding parity).

Usage parity:
    pw = (ParallelWrapper.Builder(net)
          .workers(8).prefetchBuffer(4).averagingFrequency(1).build())
    pw.fit(iterator)
"""
from __future__ import annotations

import numpy as np

import jax

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import profiler as _prof
from deeplearning4j_tpu.parallel.mesh import DeviceMesh
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import guardian as _guardian
from deeplearning4j_tpu.resilience import watchdog as _watchdog
from deeplearning4j_tpu.runtime import pipeline as _pipeline


class _StagedShards:
    """One batch already padded + dp-sharded onto the mesh by the
    prefetch worker — _fit_dataset consumes it without any host work."""

    __slots__ = ("x", "y", "fmask", "lmask")

    def __init__(self, x, y, fmask, lmask):
        self.x = x
        self.y = y
        self.fmask = fmask
        self.lmask = lmask


class ParallelWrapper:
    def __init__(self, model, workers=None, prefetch_buffer=2,
                 averaging_frequency=1, report_score=True, devices=None,
                 shard_optimizer_state=False, gradient_accumulation=None):
        self.model = model
        devs = list(devices if devices is not None else jax.devices())
        n = workers or len(devs)
        self.mesh = DeviceMesh(devs[:n], dp=n)
        self.prefetch_buffer = prefetch_buffer
        self.averaging_frequency = averaging_frequency  # sync SPMD ⇒ always 1
        self.report_score = report_score
        self.shard_optimizer_state = shard_optimizer_state  # ZeRO-1
        # G; None = inherit the model conf's gradientAccumulation —
        # an EXPLICIT 1 overrides the conf back to per-batch steps
        self.gradient_accumulation = (None if gradient_accumulation
                                      is None else
                                      int(gradient_accumulation))
        if self.gradient_accumulation is not None \
                and self.gradient_accumulation < 1:
            raise ValueError("gradient_accumulation must be >= 1")

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def workers(self, n):
            self._kw["workers"] = int(n)
            return self

        def prefetchBuffer(self, n):
            self._kw["prefetch_buffer"] = int(n)
            return self

        def averagingFrequency(self, n):
            self._kw["averaging_frequency"] = int(n)
            return self

        def reportScoreAfterAveraging(self, flag):
            self._kw["report_score"] = bool(flag)
            return self

        def shardOptimizerState(self, flag=True):
            """ZeRO-1: shard updater state over dp (parallel/zero.py)."""
            self._kw["shard_optimizer_state"] = bool(flag)
            return self

        def gradientAccumulation(self, n):
            """In-step microbatch accumulation: every G consecutive
            same-shape batches run as ONE dp-sharded jitted optimizer
            step (scan sums grads on device, single update) — one
            dispatch per optimizer step regardless of G, effective
            batch G× the per-dispatch footprint. Composes with the
            guardian (one verdict per real update) and takes
            precedence over stepsPerDispatch. When not set here it is
            inherited from the conf DSL's `.gradientAccumulation(G)`;
            an explicit `gradientAccumulation(1)` OVERRIDES the conf
            back to plain per-batch dp steps."""
            self._kw["gradient_accumulation"] = int(n)
            return self

        def workspaceMode(self, *_):
            return self  # XLA buffer reuse; accepted for parity

        def trainingMode(self, *_):
            return self  # always synchronous averaging (SPMD)

        def build(self):
            return ParallelWrapper(self._model, **self._kw)

    # -- device placement ------------------------------------------------
    def _shard_model(self):
        m = self.model
        m._params = self.mesh.replicate(m._params)
        if self.shard_optimizer_state:
            from deeplearning4j_tpu.parallel.zero import \
                shard_optimizer_state
            m._opt_state = shard_optimizer_state(m._opt_state, self.mesh)
        else:
            m._opt_state = self.mesh.replicate(m._opt_state)
        if m._state:
            m._state = self.mesh.replicate(m._state)

    @staticmethod
    def _pad_rows(arr, pad):
        """Append `pad` copies of the last row (row CONTENT is irrelevant —
        padded rows are zero-weighted in the loss; repeating keeps dtypes
        and value ranges valid, e.g. int label ids)."""
        return np.concatenate([arr, np.repeat(arr[-1:], pad, axis=0)])

    def _graph_model(self):
        """Resolved ONCE per wrapper: is the wrapped model a (validated)
        single-input/single-output ComputationGraph?"""
        cached = getattr(self, "_is_graph", None)
        if cached is not None:
            return cached
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        self._is_graph = isinstance(self.model, ComputationGraph)
        if self._is_graph and (len(self.model.conf.input_names) != 1
                               or len(self.model.conf.output_names) != 1):
            raise ValueError(
                "ParallelWrapper needs a single-input/single-output "
                "ComputationGraph (got "
                f"{len(self.model.conf.input_names)} inputs, "
                f"{len(self.model.conf.output_names)} outputs); use "
                "ShardedTrainer for general graphs")
        return self._is_graph

    def _host_prep(self, ds):
        """Host side of one batch: unwrap (Multi)DataSet, pad a ragged
        final batch to a dp multiple with zero-weighted rows. Returns
        numpy (feats, labs, fmask, lmask). Runs on the caller's thread
        in the synchronous path, on the prefetch worker when staging."""
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        if isinstance(ds, MultiDataSet):
            # single-array MultiDataSet (the usual graph pairing) maps
            # onto the same flat path; genuinely-multi needs ShardedTrainer
            if len(ds.features) != 1 or len(ds.labels) != 1:
                raise ValueError(
                    "ParallelWrapper.fit got a MultiDataSet with "
                    f"{len(ds.features)} feature / {len(ds.labels)} label "
                    "arrays; only single-input/single-output data is "
                    "supported — use ShardedTrainer for general graphs")
            fms = ds.featuresMasks
            lms = ds.labelsMasks
            feats = np.asarray(ds.features[0])
            labs = np.asarray(ds.labels[0])
            fm = None if not fms or fms[0] is None else np.asarray(fms[0])
            lm = None if not lms or lms[0] is None else np.asarray(lms[0])
        else:
            feats = np.asarray(ds.features)
            labs = np.asarray(ds.labels)
            lm = None if ds.labelsMask is None \
                else np.asarray(ds.labelsMask)
            fm = None if ds.featuresMask is None \
                else np.asarray(ds.featuresMask)
        pad = (-feats.shape[0]) % self.mesh.size
        if pad:
            # Ragged final batch: pad rows to a multiple of the dp
            # axis, and ZERO-WEIGHT them via the labels mask so the
            # masked-mean loss (losses._apply_mask_mean) excludes
            # them exactly — repeat-padding without a mask silently
            # biased last-batch gradients (round-1 VERDICT).
            b = feats.shape[0]
            feats = self._pad_rows(feats, pad)
            labs = self._pad_rows(labs, pad)
            if lm is None:
                mshape = labs.shape[:-1] if labs.ndim >= 2 \
                    else labs.shape
                lm = np.ones(mshape, np.float32)
            else:
                lm = self._pad_rows(lm, pad)
            lm = lm.copy()
            lm[b:] = 0.0
            if fm is not None:
                fm = self._pad_rows(fm, pad)
        if _mon.enabled():
            _mon.record_transfer(feats.nbytes + labs.nbytes
                                 + (0 if lm is None else lm.nbytes)
                                 + (0 if fm is None else fm.nbytes))
        return feats, labs, fm, lm

    def _stage(self, ds):
        """Prefetch-worker staging: host prep + dp-sharded device_put
        through XLA-owned copies (donation-safe; overlaps the NEXT
        batch's H2D transfer with the current step's compute)."""
        feats, labs, fm, lm = self._host_prep(ds)
        sh = self.mesh.sharding("dp")
        own = _pipeline.xla_owned_copy
        return _StagedShards(
            own(feats, sh), own(labs, sh),
            None if fm is None else own(fm, sh),
            None if lm is None else own(lm, sh))

    def _fit_dataset(self, ds):
        """One dp-sharded train step on a DataSet (the shared inner loop —
        also driven by EarlyStoppingParallelTrainer). Accepts either a
        raw (Multi)DataSet or a _StagedShards from the prefetcher."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"parallel_wrapper@{id(self):x}")
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_start()
        is_graph = self._graph_model()
        with _mon.span("train.stage"):
            if isinstance(ds, _StagedShards):
                x, y, fmask, lmask = ds.x, ds.y, ds.fmask, ds.lmask
            else:
                feats, labs, fm, lm = self._host_prep(ds)
                x = jax.device_put(feats, self.mesh.sharding("dp"))
                y = jax.device_put(labs, self.mesh.sharding("dp"))
                lmask = None if lm is None \
                    else jax.device_put(lm, self.mesh.sharding("dp"))
                fmask = None if fm is None \
                    else jax.device_put(fm, self.mesh.sharding("dp"))
            m = self.model
            m._rng_key, sub = jax.random.split(m._rng_key)
        _g = _guardian.ACTIVE
        with _mon.span("parallel.dispatch"):
            if is_graph:
                # the reference's ParallelWrapper wraps ComputationGraph
                # too; packing convention lives in
                # ComputationGraph._pack_single
                ins, labels, fmasks, lmasks = m._pack_single(x, y, fmask,
                                                             lmask)
                if _g is not None:
                    (m._params, m._opt_state, m._state, loss, gnorm,
                     ok) = m._train_step_guarded(
                        m._params, m._opt_state, m._state, ins, labels,
                        fmasks, lmasks, sub, _g.lr_scale, _g.max_gnorm)
                else:
                    m._params, m._opt_state, m._state, loss = \
                        m._train_step(m._params, m._opt_state, m._state,
                                      ins, labels, fmasks, lmasks, sub)
            else:
                ins = None
                if _g is not None:
                    (m._params, m._opt_state, m._state, loss, gnorm,
                     ok) = m._train_step_guarded(
                        m._params, m._opt_state, m._state, x, y, fmask,
                        lmask, sub, _g.lr_scale, _g.max_gnorm)
                else:
                    m._params, m._opt_state, m._state, loss = \
                        m._train_step(m._params, m._opt_state, m._state,
                                      x, y, fmask, lmask, sub)
            m._score = loss    # device scalar; score() floats on demand
        if _g is not None:
            _g.on_step(loss, gnorm, ok)   # device scalars; no sync here
        m._iteration += 1
        # StatsListener contract (ADVICE r5): the model-side fit paths set
        # both of these per real update — the wrapper's step must too, or
        # ratio/histogram collection freezes on a stale version
        m._last_features = ins if is_graph else x
        m._params_version = getattr(m, "_params_version", 0) + 1
        with _mon.span("train.listeners"):
            for listener in m._listeners:
                listener.iterationDone(m, m._iteration, m._epoch)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()
        return m._score

    # -- scanned dispatch (round-5): k same-shape batches in ONE sharded
    # dispatch, reusing the model's _train_scan — the dp-path answer to
    # the per-dispatch tunnel cost the r4 stepsPerDispatch A/B measured.
    # Same rng key stream and math as the sequential loop: dense models
    # come out bit-identical; conv models can differ by fp-reassociation
    # noise (~1e-6) because XLA fuses the scanned conv body differently
    @staticmethod
    def _scan_sig(ds):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        if isinstance(ds, MultiDataSet):
            return None   # multi data routes through the single path
        if ds.features is None:
            return None   # no features → non-scannable, not a TypeError
        def sh(a):
            return None if a is None else tuple(np.shape(a))
        return (sh(ds.features), sh(ds.labels), sh(ds.featuresMask),
                sh(ds.labelsMask))

    def _fit_group_scanned(self, group):
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"parallel_wrapper@{id(self):x}")
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_start()
        m = self.model
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh2 = NamedSharding(self.mesh.mesh, P(None, "dp"))  # (k, B, ...)
        def stack_put(field):
            arrs = [getattr(ds, field) for ds in group]
            if arrs[0] is None:
                return None
            stacked = np.stack([np.asarray(a) for a in arrs])
            _mon.record_transfer(stacked.nbytes)
            return jax.device_put(stacked, sh2)

        with _mon.span("train.stage"):
            subs = []
            for _ in group:   # identical key stream to the seq path
                m._rng_key, sub = jax.random.split(m._rng_key)
                subs.append(sub)
            xs, ys = stack_put("features"), stack_put("labels")
            fms, lms = stack_put("featuresMask"), stack_put("labelsMask")
        import jax.numpy as jnp
        with _mon.span("parallel.scan_dispatch"):
            if self._graph_model():
                ins, labels, fmasks, lmasks = m._pack_single(xs, ys, fms,
                                                             lms)
                (m._params, m._opt_state, m._state,
                 losses) = m._train_scan(m._params, m._opt_state, m._state,
                                         ins, labels, fmasks, lmasks,
                                         jnp.stack(subs))
                # last batch of the scanned stack, unpacked like the
                # model-side scanned path (graph.py:487)
                m._last_features = jax.tree_util.tree_map(
                    lambda a: a[-1], ins)
            else:
                (m._params, m._opt_state, m._state,
                 losses) = m._train_scan(m._params, m._opt_state, m._state,
                                         xs, ys, fms, lms, jnp.stack(subs))
                m._last_features = xs[-1]
        # ONE real param update for the whole scanned group: bump the
        # version once so StatsListener's dedup treats the k-1 inner
        # iterationDone calls as param-stale (ADVICE r5, wrapper.py:200)
        m._params_version = getattr(m, "_params_version", 0) + 1
        with _mon.span("train.listeners"):
            if m._listeners:
                for i in range(len(group)):
                    m._score = losses[i]   # device slice; lazy float
                    m._iteration += 1
                    for listener in m._listeners:
                        listener.iterationDone(m, m._iteration, m._epoch)
            else:
                m._score = losses[len(group) - 1]
                m._iteration += len(group)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()

    def _fit_group_accum(self, group):
        """One ACCUMULATED dp-sharded optimizer step over G stacked
        batches — the model's `_train_accum`/`_train_step_accum` with
        input sharding (k, B, ...) = (replicated, dp): the scan sums
        per-microbatch gradients (each microbatch's psum rides the same
        program) and applies ONE update. One real update: iteration and
        listeners advance once; under a guardian the accumulated step's
        single verdict gates it (per-microbatch NaN still caught via
        the poisoned loss)."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"parallel_wrapper@{id(self):x}")
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_start()
        m = self.model
        from jax.sharding import NamedSharding, PartitionSpec as P
        sh2 = NamedSharding(self.mesh.mesh, P(None, "dp"))  # (G, B, ...)

        def stack_put(field):
            arrs = [getattr(ds, field) for ds in group]
            if arrs[0] is None:
                return None
            stacked = np.stack([np.asarray(a) for a in arrs])
            _mon.record_transfer(stacked.nbytes)
            return jax.device_put(stacked, sh2)

        with _mon.span("train.stage"):
            subs = []
            for _ in group:   # one key split per microbatch
                m._rng_key, sub = jax.random.split(m._rng_key)
                subs.append(sub)
            xs, ys = stack_put("features"), stack_put("labels")
            fms, lms = stack_put("featuresMask"), stack_put("labelsMask")
        import jax.numpy as jnp
        _g = _guardian.ACTIVE
        with _mon.span("parallel.accum_dispatch"):
            if self._graph_model():
                ins, labels, fmasks, lmasks = m._pack_single(xs, ys, fms,
                                                             lms)
                if _g is not None:
                    (m._params, m._opt_state, m._state, loss, gnorm,
                     ok) = m._train_accum_guarded(
                        m._params, m._opt_state, m._state, ins, labels,
                        fmasks, lmasks, jnp.stack(subs), _g.lr_scale,
                        _g.max_gnorm)
                else:
                    (m._params, m._opt_state, m._state,
                     loss) = m._train_accum(
                        m._params, m._opt_state, m._state, ins, labels,
                        fmasks, lmasks, jnp.stack(subs))
                m._last_features = jax.tree_util.tree_map(
                    lambda a: a[-1], ins)
            else:
                if _g is not None:
                    (m._params, m._opt_state, m._state, loss, gnorm,
                     ok) = m._train_step_accum_guarded(
                        m._params, m._opt_state, m._state, xs, ys, fms,
                        lms, jnp.stack(subs), _g.lr_scale, _g.max_gnorm)
                else:
                    (m._params, m._opt_state, m._state,
                     loss) = m._train_step_accum(
                        m._params, m._opt_state, m._state, xs, ys, fms,
                        lms, jnp.stack(subs))
                m._last_features = xs[-1]
            m._score = loss    # device scalar; score() floats on demand
        if _g is not None:
            _g.on_step(loss, gnorm, ok)   # one verdict per real update
        m._iteration += 1
        m._params_version = getattr(m, "_params_version", 0) + 1
        with _mon.span("train.listeners"):
            for listener in m._listeners:
                listener.iterationDone(m, m._iteration, m._epoch)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()

    def fit(self, iterator, epochs=1, stepsPerDispatch=1):
        """Data-parallel fit: same jitted train step as the wrapped model —
        input sharding makes it SPMD over the dp axis. stepsPerDispatch=k
        scans k same-shape batches inside ONE dispatch (ragged/odd batches
        fall back to the per-batch step; same key stream and math — dense
        models bit-identical, conv models within fp-reassociation noise).

        gradientAccumulation=G (builder knob, or inherited from the
        model conf): every G same-shape batches run as ONE accumulated
        optimizer step instead — one dispatch AND one update per group;
        takes precedence over stepsPerDispatch and stays on under a
        guardian (the accumulated step carries its own verdict)."""
        if self.model._params is None:
            self.model.init()
        self._shard_model()
        it, pf = iterator, None
        accum = self.gradient_accumulation
        if accum is None:   # unset → inherit; explicit 1 stays 1
            accum = int(self.model.conf.defaults.get(
                "gradientAccumulation", 1) or 1)
        k = max(1, int(stepsPerDispatch))
        if accum > 1:
            k = accum   # accumulation owns the grouping
        elif _guardian.ACTIVE is not None:
            k = 1    # per-step health verdicts (see model fit loops)
        if self.prefetch_buffer and hasattr(iterator, "asyncSupported") \
                and iterator.asyncSupported():
            # k == 1: stage all the way onto the mesh (pad + dp-sharded
            # device_put) in the background. k > 1: the scanned path
            # stacks host arrays per group itself, so prefetch only the
            # host pull (stage=None) and leave staging to the group.
            it = pf = _pipeline.PrefetchIterator(
                iterator, depth=self.prefetch_buffer,
                stage=self._stage if k == 1 else None)
        try:
            for _ in range(int(epochs)):
                with _mon.span("fit.epoch"):
                    if hasattr(it, "reset"):
                        it.reset()
                    if k == 1:
                        for ds in _mon.traced_iter(it):
                            self._fit_dataset(ds)
                    else:
                        group, sig = [], None

                        def flush():
                            nonlocal group
                            for g in group:   # sub-k groups run singly
                                self._fit_dataset(g)
                            group = []

                        for ds in _mon.traced_iter(it):
                            s = self._scan_sig(ds)
                            scannable = (s is not None and len(s[0]) > 0
                                         and s[0][0] % self.mesh.size == 0)
                            if not scannable:
                                flush()
                                sig = None
                                self._fit_dataset(ds)
                                continue
                            if s != sig:
                                flush()
                                sig = s
                            group.append(ds)
                            if len(group) == k:
                                if accum > 1:
                                    self._fit_group_accum(group)
                                else:
                                    self._fit_group_scanned(group)
                                group = []
                        flush()
                    self.model._epoch += 1
        finally:
            # fit over: this trainer's heartbeat is no longer stall
            # evidence (see multilayer.fit)
            if _watchdog.ACTIVE is not None:
                _watchdog.ACTIVE.retire(f"parallel_wrapper@{id(self):x}")
            if pf is not None:
                pf.close()
        return self.model

    def shutdown(self):
        pass  # no worker threads to stop: one SPMD program
