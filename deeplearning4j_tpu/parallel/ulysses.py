"""All-to-all (Ulysses-style) sequence parallelism over the `sp` axis.

The complement to ring attention (parallel/ring_attention.py) for long
sequences: instead of rotating K/V blocks around the ICI ring (n-1 hops,
O(T/n) memory per device), TWO all-to-all collectives re-shard

    (B, H, T/n, D)  --all_to_all-->  (B, H/n, T, D)

so each device holds the FULL sequence for its head group, runs ordinary
attention locally (causal works unchanged, padding masks ride one
all_gather — no cross-device softmax bookkeeping), and a final
all-to-all restores sequence sharding. Trade-offs, per the scaling-book
recipe:

- Ulysses: four all-to-alls per call (q/k/v gathers + output scatter),
  full-T attention per device — wins when heads >= sp and T fits one
  device's HBM after the head split.
- Ring: n-1 ppermute hops overlapped with compute, O(T/n) activation
  memory — wins when even T x D per head group is too big, or H < sp.

Both return shard_map-ready fns with the same signature, so models swap
strategies with one argument (models/bert.py attn_impl).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu.parallel.mesh import shard_map
from deeplearning4j_tpu.parallel.ring_attention import (blockwise_attention,
                                                        dense_attention)

__all__ = ["make_ulysses_attention", "ulysses_attention_sharded"]


def make_ulysses_attention(mesh, axis_name="sp", causal=False,
                           attn_fn=None, block_size=512):
    """Build f(q_local, k_local, v_local, mask_local=None) for use INSIDE
    shard_map over `mesh`: q/k/v locals are (B, H, T/n, D) sharded on
    time, the optional padding mask (B, T/n); output is sharded like q.
    Requires H % n == 0 (heads split across the axis while attention
    runs). attn_fn overrides the local attention (defaults to the
    flash-style blockwise scan, which stays O(T) memory for masked
    batches too; signature f(q, k, v, causal=..., kv_mask=None) — a
    custom attn_fn without a kv_mask parameter fails loudly on masked
    batches rather than silently attending to padding). The full (B, T)
    mask is all_gathered once."""
    custom_attn = attn_fn is not None
    if attn_fn is None:
        def attn_fn(q, k, v, causal=False, kv_mask=None):
            return blockwise_attention(q, k, v, block_size=block_size,
                                       causal=causal, kv_mask=kv_mask)

    def ulysses(q, k, v, mask=None):
        n = lax.psum(1, axis_name)
        h = q.shape[1]
        if h % n:
            raise ValueError(
                f"ulysses attention needs heads ({h}) divisible by the "
                f"{axis_name!r} axis size ({n}) — use ring attention for "
                "head counts below the mesh axis")

        def gather_seq(x):   # (B, H, T/n, D) -> (B, H/n, T, D)
            return lax.all_to_all(x, axis_name, split_axis=1,
                                  concat_axis=2, tiled=True)

        def scatter_seq(x):  # (B, H/n, T, D) -> (B, H, T/n, D)
            return lax.all_to_all(x, axis_name, split_axis=2,
                                  concat_axis=1, tiled=True)

        qg, kg, vg = gather_seq(q), gather_seq(k), gather_seq(v)
        if mask is not None:
            full = lax.all_gather(mask, axis_name, axis=1, tiled=True)
            if custom_attn:
                # kv_mask is passed BY KEYWORD, so the impl must name it
                # as a keyword-reachable parameter — a bare **kwargs
                # catch-all (or a positional-only param that happens to
                # share the name) would swallow it silently (ADVICE r5;
                # same guard as models/bert.py). The bind() then checks
                # the REST of the call (e.g. a missing causal param), so
                # a convention mismatch still surfaces as this curated
                # error, not a bare TypeError from inside shard_map.
                import inspect

                from deeplearning4j_tpu.util.introspect import \
                    explicit_mask_param
                ok = explicit_mask_param(attn_fn,
                                         names=("kv_mask",)) is not None
                if ok:
                    try:
                        inspect.signature(attn_fn).bind(
                            qg, kg, vg, causal=causal, kv_mask=full)
                    except TypeError:
                        ok = False
                if not ok:
                    raise ValueError(
                        "masked batch but the custom attn_fn does not "
                        "explicitly declare a kv_mask parameter (bare "
                        "**kwargs does not count) or cannot be called "
                        "with (q, k, v, causal=..., kv_mask=...) — "
                        "silent padding attention is not an option; "
                        "accept attn_fn(q, k, v, causal=..., "
                        "kv_mask=None)")
            out = attn_fn(qg, kg, vg, causal=causal, kv_mask=full)
        else:
            out = attn_fn(qg, kg, vg, causal=causal)
        return scatter_seq(out.astype(q.dtype))

    return ulysses


def ulysses_attention_sharded(mesh, q, k, v, mask=None, axis_name="sp",
                              causal=False, attn_fn=None):
    """Convenience wrapper: q/k/v are GLOBAL (B, H, T, D) arrays (mask
    (B, T)); shards the time axis over `axis_name`, runs the all-to-all
    attention, and returns the global result. (Models embed
    make_ulysses_attention in their own shard_map instead — this is the
    standalone surface.)"""
    from jax.sharding import PartitionSpec as P

    spec = P(None, None, axis_name, None)
    fn = make_ulysses_attention(mesh, axis_name, causal=causal,
                                attn_fn=attn_fn)
    if mask is None:
        sharded = shard_map(
            lambda a, b, c: fn(a, b, c), mesh=mesh,
            in_specs=(spec, spec, spec), out_specs=spec, check_vma=False)
        return sharded(q, k, v)
    sharded = shard_map(
        fn, mesh=mesh, in_specs=(spec, spec, spec, P(None, axis_name)),
        out_specs=spec, check_vma=False)
    return sharded(q, k, v, mask)
