"""Byte-balanced gradient buckets for the overlapped exchange
(≡ the reference's EncodedGradientsAccumulator shipping gradient
*chunks* over Aeron as they become ready, rather than one monolithic
message after the whole backward).

PR 7's `MultiHostTrainer` all-reduced the entire gradient tree as one
logical exchange at the end of the step, so the full cross-host latency
sat exposed on the critical path. This module splits the tree into N
byte-balanced buckets; the trainer then encodes and all-reduces each
bucket as an INDEPENDENT collective, issued in program order
(encode b0 → exchange b0 → encode b1 → exchange b1 → ...), so bucket
k's collective has no data dependency on bucket k+1's encode and XLA's
latency-hiding scheduler can run them concurrently (async
all-reduce-start on TPU/GPU; verified structurally on the HLO text on
CPU, where collectives lower synchronously — see
`check_overlap_structure`).

Everything here is trace-time planning over leaf SHAPES: the plan is
computed once on the host from tree metadata (no device values touched
— lint-enforced by scripts/check_fastpath.py's training-exchange sync
rule) and then drives pure jnp concat/split inside the jitted step.

Each bucket rides ONE collective: the bucket's leaves are raveled and
concatenated into a single flat vector (same dtype per bucket — the
planner never mixes dtypes), all-reduced, then split + reshaped back.
This is also what makes the per-bucket threshold-encoder state natural:
one flat residual vector and one adaptive threshold scalar per bucket.
"""
from __future__ import annotations

import re

import numpy as np

import jax
import jax.numpy as jnp

__all__ = ["BucketPlan", "plan_buckets", "check_overlap_structure",
           "DEFAULT_NUM_BUCKETS", "ENCODE_SCOPE", "EXCHANGE_SCOPE"]

#: default bucket count when neither `num_buckets` nor `bucket_bytes`
#: is given: enough splits for the scheduler to overlap, few enough
#: that per-collective latency still amortizes
DEFAULT_NUM_BUCKETS = 4

#: named-scope stamps the trainer wraps per-bucket ops in — the HLO
#: structural check keys off these (they survive into op metadata)
ENCODE_SCOPE = "dl4j_bucket{b}_encode"
EXCHANGE_SCOPE = "dl4j_bucket{b}_exchange"


class BucketPlan:
    """Host-side plan: which flattened-tree leaf goes to which bucket.

    Attributes
    ----------
    num_buckets: int
    buckets: tuple of tuples of leaf indices (tree_flatten order inside
        each bucket — deterministic, so checkpointed per-bucket encoder
        state always lines up with the same elements).
    bucket_bytes: per-bucket payload bytes (the balance the planner
        optimized).
    """

    def __init__(self, treedef, shapes, dtypes, buckets):
        self.treedef = treedef
        self.shapes = tuple(tuple(s) for s in shapes)
        self.dtypes = tuple(np.dtype(d) for d in dtypes)
        self.buckets = tuple(tuple(b) for b in buckets)
        self.num_buckets = len(self.buckets)
        sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.leaf_sizes = tuple(sizes)
        self.bucket_elems = tuple(sum(sizes[i] for i in b)
                                  for b in self.buckets)
        self.bucket_bytes = tuple(
            sum(sizes[i] * self.dtypes[i].itemsize for i in b)
            for b in self.buckets)
        self.total_bytes = sum(self.bucket_bytes)

    def bucket_dtype(self, b):
        return self.dtypes[self.buckets[b][0]]

    # -- trace-time tensor plumbing (pure jnp; runs inside jit) ----------
    def concat(self, tree):
        """Tree -> [flat 1-D array per bucket] (ravel + concat in plan
        order). Single-leaf buckets skip the concat."""
        leaves = jax.tree_util.tree_leaves(tree)
        out = []
        for b in self.buckets:
            flats = [jnp.ravel(leaves[i]) for i in b]
            out.append(flats[0] if len(flats) == 1
                       else jnp.concatenate(flats))
        return out

    def split(self, flats):
        """[flat per bucket] -> tree (inverse of `concat`)."""
        leaves = [None] * len(self.shapes)
        for b, flat in zip(self.buckets, flats):
            off = 0
            for i in b:
                n = self.leaf_sizes[i]
                # static slice: offsets are plan constants, so XLA sees
                # plain slices (free to fuse), never dynamic-slice
                leaves[i] = flat[off:off + n].reshape(self.shapes[i])
                off += n
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    def describe(self):
        """Host-side summary for telemetry / GET /health."""
        return {"num_buckets": self.num_buckets,
                "bucket_bytes": list(self.bucket_bytes),
                "total_bytes": self.total_bytes,
                "leaves": len(self.shapes)}


def plan_buckets(tree, num_buckets=None, bucket_bytes=None):
    """Byte-balanced partition of `tree`'s leaves into buckets.

    num_buckets: requested bucket count (clamped to the leaf count);
        default DEFAULT_NUM_BUCKETS.
    bucket_bytes: alternatively, a target payload per bucket — the
        planner derives the count as ceil(total/target).

    Greedy LPT (largest leaf into the lightest bucket) per dtype group:
    a bucket never mixes dtypes (its payload is ONE flat vector), so
    leaves are first grouped by dtype, each group gets buckets
    proportional to its byte share (at least one), and LPT balances
    within the group. Deterministic for a given tree structure.
    """
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if not leaves:
        raise ValueError("plan_buckets: empty tree")
    shapes = [tuple(getattr(l, "shape", ())) for l in leaves]
    dtypes = [np.dtype(getattr(l, "dtype", np.float32)) for l in leaves]
    sizes = [int(np.prod(s)) if s else 1 for s in shapes]
    nbytes = [sizes[i] * dtypes[i].itemsize for i in range(len(leaves))]
    total = sum(nbytes)
    if bucket_bytes is not None:
        if num_buckets is not None:
            raise ValueError("pass num_buckets OR bucket_bytes, not both")
        num_buckets = max(1, -(-total // int(bucket_bytes)))
    elif num_buckets is None:
        num_buckets = DEFAULT_NUM_BUCKETS
    num_buckets = max(1, min(int(num_buckets), len(leaves)))

    # dtype groups, largest byte-share first (stable order via dtype str)
    groups = {}
    for i, dt in enumerate(dtypes):
        groups.setdefault(str(dt), []).append(i)
    ordered = sorted(groups.items(),
                     key=lambda kv: (-sum(nbytes[i] for i in kv[1]),
                                     kv[0]))
    # buckets per group: proportional to bytes, >=1 each, sum == requested
    # (when fewer buckets than groups, the request grows to one/group)
    counts = []
    remaining = max(num_buckets, len(ordered))
    for gi, (_, idxs) in enumerate(ordered):
        left = len(ordered) - gi - 1
        share = sum(nbytes[i] for i in idxs) / max(total, 1)
        want = max(1, min(len(idxs), round(share * num_buckets),
                          remaining - left))
        counts.append(want)
        remaining -= want

    buckets = []
    for (_, idxs), k in zip(ordered, counts):
        k = min(k, len(idxs))
        loads = [0] * k
        members = [[] for _ in range(k)]
        for i in sorted(idxs, key=lambda i: (-nbytes[i], i)):  # LPT
            b = min(range(k), key=lambda j: (loads[j], j))
            loads[b] += nbytes[i]
            members[b].append(i)
        # deterministic intra-bucket order: tree_flatten order
        buckets.extend(sorted(m) for m in members)
    # stable bucket order: by first leaf index, so bucket identity (and
    # its checkpointed encoder state) is a pure function of the tree
    buckets.sort(key=lambda b: b[0])
    return BucketPlan(treedef, shapes, dtypes, buckets)


# ===================== HLO structural overlap check =====================
# dense wire rides all-reduce; the sparse token wire rides all-gather —
# both count as "the bucket's collective" for the overlap structure
_COLLECTIVE_RE = re.compile(
    r"=\s+\S+\s+(all-reduce-start|all-reduce"
    r"|all-gather-start|all-gather)\(")


def _entry_lines(hlo_text):
    """The scheduled ENTRY computation's instruction lines, in order."""
    lines, inside = [], False
    for ln in hlo_text.splitlines():
        if ln.startswith("ENTRY "):
            inside = True
            continue
        if inside:
            if ln.startswith("}"):
                break
            lines.append(ln)
    return lines


def check_overlap_structure(hlo_text, num_buckets,
                            require_async=False):
    """Structural proof, on compiled/scheduled HLO text, that the
    bucketed exchange is overlappable AND actually scheduled overlapped:

    1. exactly `num_buckets` bucket collectives exist (the monolithic
       all-reduce really was split) — identified by the
       `dl4j_bucket{k}_exchange` named-scope stamp in op metadata;
    2. for every k >= 1, bucket k's ENCODE compute is scheduled AFTER
       bucket k-1's collective was issued (all-reduce-start on async
       backends; the sync all-reduce on CPU) — i.e. collective k-1 is
       in flight while encode k computes, never "all encodes first,
       then all collectives back-to-back".

    `require_async=True` additionally demands `all-reduce-start` ops
    (TPU/GPU latency-hiding); the CPU backend lowers collectives
    synchronously, so tier-1 asserts the schedule shape only.

    Returns a list of human-readable violations (empty == pass).
    """
    lines = _entry_lines(hlo_text)
    if not lines:
        return ["no ENTRY computation found in HLO text"]
    coll_pos = {}       # bucket -> line index of its collective
    enc_pos = {}        # bucket -> first line index of its encode ops
    for idx, ln in enumerate(lines):
        is_coll = _COLLECTIVE_RE.search(ln) is not None
        for b in range(num_buckets):
            if is_coll and b not in coll_pos \
                    and EXCHANGE_SCOPE.format(b=b) in ln:
                coll_pos[b] = idx
            if b not in enc_pos and ENCODE_SCOPE.format(b=b) in ln \
                    and not is_coll:
                enc_pos[b] = idx
    problems = []
    missing = [b for b in range(num_buckets) if b not in coll_pos]
    if missing:
        problems.append(
            f"expected one collective per bucket, none found for "
            f"buckets {missing} (split failed or scopes were fused "
            f"away)")
        return problems
    if require_async and "all-reduce-start" not in hlo_text:
        problems.append("no async all-reduce-start ops (backend lowered "
                        "collectives synchronously)")
    for b in range(1, num_buckets):
        if b not in enc_pos:
            # encode fused INTO the collective's operand producer: treat
            # the collective itself as the encode position
            enc_pos[b] = coll_pos[b]
        if enc_pos[b] <= coll_pos[b - 1]:
            problems.append(
                f"bucket {b}'s encode (line {enc_pos[b]}) is scheduled "
                f"before bucket {b - 1}'s collective (line "
                f"{coll_pos[b - 1]}) — the exchange is serialized after "
                f"all compute, nothing can overlap")
    return problems
