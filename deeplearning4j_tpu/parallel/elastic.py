"""Elastic / fault-tolerant distributed training (SURVEY §2: orbax
checkpoint + rejoin; ≡ the reference's SharedTrainingMaster fault
tolerance, where a restarted worker rejoins and resumes from the last
shared state).

TPU-native inversion: instead of Aeron-replicated parameter state, the
source of truth is an orbax sharded checkpoint in shared storage. Any
host that dies restarts, calls `resume_or_init`, and receives the latest
(step, params, opt_state) laid out for its mesh; training continues from
the last completed save. Async checkpointing keeps the save off the
training step's critical path.
"""
from __future__ import annotations

import os

import jax
import numpy as np


class ElasticCheckpointer:
    """Orbax-backed save/resume for (step, params, opt_state) pytrees."""

    def __init__(self, directory, max_to_keep=3, save_interval_steps=1):
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)
        self.manager = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps))

    def save(self, step, params, opt_state=None, wait=False):
        state = {"params": params}
        if opt_state is not None:
            state["opt_state"] = opt_state
        self.manager.save(int(step),
                          args=self._ocp.args.StandardSave(state))
        if wait:
            self.manager.wait_until_finished()
        return self

    def latest_step(self):
        return self.manager.latest_step()

    def restore(self, step=None, like=None):
        """Restore (step, state). `like` — a pytree of arrays with the
        target sharding/layout (orbax restores device-put to match)."""
        step = self.manager.latest_step() if step is None else int(step)
        if step is None:
            return None, None
        if like is not None:
            args = self._ocp.args.StandardRestore(like)
        else:
            args = self._ocp.args.StandardRestore()
        return step, self.manager.restore(step, args=args)

    def close(self):
        self.manager.wait_until_finished()
        self.manager.close()


class ElasticTrainer:
    """Wrap a ShardedTrainer-style step with periodic checkpoints and
    crash-resume (≡ fault-tolerant SharedTrainingMaster loop)."""

    def __init__(self, trainer, directory, save_every=50, max_to_keep=3):
        self.trainer = trainer
        self.ckpt = ElasticCheckpointer(directory, max_to_keep=max_to_keep,
                                        save_interval_steps=save_every)
        self.save_every = int(save_every)
        self.step_num = 0

    def resume_or_init(self, init_params):
        """Restore the latest checkpoint if one exists, else shard the
        given fresh params. Returns (params, opt_state)."""
        params, opt_state = self.trainer.init(init_params)
        latest = self.ckpt.latest_step()
        if latest is None:
            return params, opt_state
        like = {"params": params, "opt_state": opt_state}
        step, state = self.ckpt.restore(like=like)
        self.step_num = step
        # orbax restores each leaf committed to its `like` placement; a
        # fresh optimizer's scalars (e.g. Adam count) sit on one device,
        # which would clash with mesh-committed params inside jit —
        # re-place every restored leaf on a mesh-wide sharding
        from jax.sharding import NamedSharding, PartitionSpec

        def place(fresh, restored):
            sh = fresh.sharding if isinstance(
                getattr(fresh, "sharding", None), NamedSharding) \
                else NamedSharding(self.trainer.mesh, PartitionSpec())
            return jax.device_put(restored, sh)

        state = jax.tree_util.tree_map(place, like, state)
        return state["params"], state["opt_state"]

    def fit_batch(self, params, opt_state, batch, rng):
        params, opt_state, loss = self.trainer.fit_batch(
            params, opt_state, batch, rng)
        self.step_num += 1
        if self.step_num % self.save_every == 0:
            self.ckpt.save(self.step_num, params, opt_state)
        return params, opt_state, loss

    def finalize(self, params, opt_state):
        self.ckpt.save(self.step_num, params, opt_state, wait=True)
        self.ckpt.close()


def initialize_multihost(coordinator_address=None, num_processes=None,
                         process_id=None):
    """≡ the reference's cluster join for the elastic path; reads the
    JAX_COORDINATOR_ADDRESS env when no address is given and delegates to
    parallel.mesh.initialize_distributed (single implementation)."""
    from deeplearning4j_tpu.parallel.mesh import initialize_distributed
    if coordinator_address is None:
        coordinator_address = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return False
    return initialize_distributed(
        coordinator_address,
        num_processes or int(os.environ.get("JAX_NUM_PROCESSES", "1")),
        process_id if process_id is not None
        else int(os.environ.get("JAX_PROCESS_ID", "0")))
