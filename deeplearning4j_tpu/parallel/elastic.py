"""Elastic / fault-tolerant distributed training (SURVEY §2: orbax
checkpoint + rejoin; ≡ the reference's SharedTrainingMaster fault
tolerance, where a restarted worker rejoins and resumes from the last
shared state).

TPU-native inversion: instead of Aeron-replicated parameter state, the
source of truth is an orbax sharded checkpoint in shared storage. Any
host that dies restarts, calls `resume_or_init`, and receives the latest
(step, params, opt_state) laid out for its mesh; training continues from
the last completed save. Async checkpointing keeps the save off the
training step's critical path.
"""
from __future__ import annotations

import os

import jax
import numpy as np

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.errors import CheckpointIntegrityError


class ElasticCheckpointer:
    """Orbax-backed save/resume for (step, params, opt_state[, extra])
    pytrees. `extra` carries whatever the trainer needs for step-accurate
    resume (rng key, batch-norm state, iteration counters)."""

    def __init__(self, directory, max_to_keep=3, save_interval_steps=1,
                 sweep_orphans=True, primary_only=False, read_only=False):
        """sweep_orphans=False skips the startup debris sweep — REQUIRED
        when the directory is shared with another process that may have
        an async save in flight (the sweep would rmtree its in-progress
        orbax temp dir); the single-writer restart case keeps the
        default.

        Multi-host modes: with `jax.process_count() > 1`, orbax's save
        path runs `sync_global_processes` — a GLOBAL barrier that hangs
        forever if only one process saves (root-caused against the
        two-process runner: process 0's save stalled inside
        `create_temporary_path` waiting for peers that never call save).
        `primary_only=True` scopes every orbax barrier to THIS process
        (`MultiprocessingOptions(active_processes={me})` — the barrier
        rides the coordination service restricted to one process id),
        so the single-writer pattern works; `read_only=True` is the
        peers' flavor: restore/inspect with no save machinery at all."""
        import orbax.checkpoint as ocp

        from deeplearning4j_tpu.resilience import integrity as _integrity
        self._ocp = ocp
        self.directory = os.path.abspath(str(directory))
        os.makedirs(self.directory, exist_ok=True)
        # a kill mid-save leaves orbax tmp dirs / partial steps / stale
        # manifests behind; sweep them BEFORE the manager scans the
        # directory (startup only — no save from this process can be in
        # flight yet). dl4j.resilience.ckpt_orphans_removed counts them.
        self.orphans_removed = (
            _integrity.sweep_orphans(self.directory)
            if sweep_orphans and not read_only else 0)
        self._closed = False
        opts = {"max_to_keep": max_to_keep,
                "save_interval_steps": save_interval_steps}
        if read_only:
            opts["read_only"] = True
        if primary_only or read_only:
            # scope EVERY orbax barrier to this process alone — both the
            # save-side atomicity syncs and the one at the end of
            # Checkpointer.restore (without this, a read-only peer's
            # restore dispatches a global device sync the single writer
            # never joins → a silent cross-host hang)
            me = jax.process_index()
            opts["multiprocessing_options"] = \
                ocp.options.MultiprocessingOptions(
                    primary_host=me, active_processes={me},
                    # two processes' single-process barriers share one
                    # coordination service: identical keys with
                    # different task sets are rejected as conflicting
                    barrier_sync_key_prefix=f"dl4j-p{me}")
            # orbax refuses create=True with active_processes; the root
            # directory already exists (makedirs above)
            opts["create"] = False
        self.manager = ocp.CheckpointManager(
            self.directory, options=ocp.CheckpointManagerOptions(**opts))

    def check_for_errors(self):
        """Surface a deferred ASYNC-save failure now. Orbax records
        exceptions from the background commit thread; without this check
        they would be swallowed until (or past) close — a training run
        could 'checkpoint' for hours while every save failed."""
        check = getattr(self.manager, "check_for_errors", None)
        if check is not None:
            check()

    def save(self, step, params, opt_state=None, extra=None, wait=False,
             verdict=None):
        """`verdict` is the guardian health verdict recorded in the
        integrity manifest ("verified" when the guardian vouched for
        this state; defaults to "unguarded")."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.CHECKPOINT_SAVE)
        self.check_for_errors()     # previous async save failed → raise
        state = {"params": params}
        if opt_state is not None:
            state["opt_state"] = opt_state
        if extra:
            state["extra"] = extra
        if not wait:
            # ASYNC save of buffers the caller's next train step will
            # DONATE is a use-after-free: XLA reuses the memory while
            # orbax's background thread still serializes it (on CPU the
            # device buffer even aliases host memory — np.asarray would
            # be a view, hence np.array's forced copy). Snapshot to
            # host copies first; wait=True saves need no copy.
            # Non-fully-addressable arrays (multi-host shards) CANNOT be
            # gathered here — they pass through to orbax's per-shard
            # writer exactly as before this fix.
            def _snap(a):
                if not hasattr(a, "shape") or isinstance(a, np.ndarray):
                    return a
                if getattr(a, "is_fully_addressable", True):
                    return np.array(a)
                return a

            state = jax.tree_util.tree_map(_snap, state)
        saved = self.manager.save(int(step),
                                  args=self._ocp.args.StandardSave(state))
        if saved:
            # integrity manifest from the SAME host snapshot orbax will
            # serialize (no extra sync; cannot race donated buffers) —
            # written atomically, so restore either sees a complete
            # manifest or none
            from deeplearning4j_tpu.resilience import \
                integrity as _integrity
            _integrity.write_manifest(self.directory, step, state,
                                      verdict=verdict)
            # reap sidecars whose generation max_to_keep GC just
            # removed — without this a long run accumulates one orphan
            # manifest per retired generation until the next restart.
            # The just-saved step is kept explicitly: an async save may
            # not appear in all_steps() yet
            _integrity.prune_manifests(
                self.directory,
                keep=list(self.manager.all_steps()) + [int(step)])
        if saved and _mon.enabled():
            _mon.get_registry().counter(
                _mon.RESILIENCE_CHECKPOINT_SAVES,
                help="checkpoint saves issued (async unless wait)").inc()
        if wait:
            self.manager.wait_until_finished()
            self.check_for_errors()
        return self

    def latest_step(self):
        return self.manager.latest_step()

    def all_steps(self):
        """Every on-disk checkpoint generation, ascending."""
        return sorted(int(s) for s in self.manager.all_steps())

    def restore(self, step=None, like=None):
        """Restore (step, state). `like` fixes the TREE STRUCTURE of the
        result (optax NamedTuples survive). Leaves whose `like`
        counterpart carries a NamedSharding come back device-put to that
        sharding (mesh reshape across save/restore works, as before);
        everything else comes back as HOST numpy arrays — callers
        re-place on device themselves (`replace_on_mesh`, the trainers'
        resume paths).

        Deliberately restores WITHOUT a target and grafts the raw
        leaves into `like`'s treedef: orbax's targeted-restore path
        (StandardRestore(like)) hands back numpy arrays whose backing
        memory is not soundly owned — reading them after the restore
        call intermittently yields garbage or segfaults (observed
        ~half of resume runs on this orbax/jax CPU combo; the untargeted
        path has never misread). Shapes are validated leaf-by-leaf so a
        structure mismatch fails loudly instead of silently
        transposing state."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.CHECKPOINT_RESTORE)
        step = self.manager.latest_step() if step is None else int(step)
        if step is None:
            return None, None
        if like is not None and any(
                getattr(a, "is_fully_addressable", True) is False
                for a in jax.tree_util.tree_leaves(like)):
            # multi-host target: keep orbax's per-shard targeted restore
            # (the untargeted path below reads every leaf fully on every
            # host, and the graft's device_put cannot place shards this
            # process does not own)
            return step, self.manager.restore(
                step, args=self._ocp.args.StandardRestore(like))
        import logging

        class _DropTargetWarning(logging.Filter):
            def filter(self, record):
                return "expects a target tree" not in record.getMessage()

        # the untargeted restore is deliberate (see above) — drop orbax's
        # per-restore warning about it, nothing else
        absl_logger = logging.getLogger("absl")
        f = _DropTargetWarning()
        absl_logger.addFilter(f)
        try:
            raw = self.manager.restore(
                step, args=self._ocp.args.StandardRestore())
        finally:
            absl_logger.removeFilter(f)
        if like is None:
            return step, raw
        like_leaves, treedef = jax.tree_util.tree_flatten(like)
        raw_leaves = jax.tree_util.tree_leaves(raw)
        if len(raw_leaves) != len(like_leaves):
            raise ValueError(
                f"checkpoint step {step} has {len(raw_leaves)} leaves "
                f"but the restore target has {len(like_leaves)} — "
                "saved and target structures do not match")
        from jax.sharding import NamedSharding

        grafted = []
        for want, got in zip(like_leaves, raw_leaves):
            ws = tuple(getattr(want, "shape", ()) or ())
            gs = tuple(getattr(got, "shape", ()) or ())
            if ws != gs:
                raise ValueError(
                    f"checkpoint step {step}: leaf shape {gs} does not "
                    f"match target shape {ws} — saved and target "
                    "structures do not match")
            dt = getattr(want, "dtype", None)
            host = np.asarray(got) if dt is None \
                else np.asarray(got, dtype=dt)
            sh = getattr(want, "sharding", None)
            if isinstance(sh, NamedSharding):
                grafted.append(place_global(host, sh))
            else:
                grafted.append(host)
        return step, jax.tree_util.tree_unflatten(treedef, grafted)

    def restore_verified(self, like=None, check_finite=True):
        """Restore the newest checkpoint generation that passes
        integrity verification (manifest checksums + finiteness — see
        resilience/integrity.py), FALLING BACK a generation on any
        restore or verification failure: a corrupted latest checkpoint
        costs one generation of progress instead of the whole run.
        Fallbacks land on `dl4j.resilience.ckpt_restore_fallbacks`.

        Returns (step, state) like restore(); (None, None) when no
        checkpoint exists at all; raises `CheckpointIntegrityError`
        when generations exist but none could be restored."""
        from deeplearning4j_tpu.resilience import integrity as _integrity
        steps = self.all_steps()
        if not steps:
            return None, None
        last_err = None
        for step in reversed(steps):
            try:
                s, state = self.restore(step=step, like=like)
                _integrity.verify_restored(self.directory, step, state,
                                           check_finite=check_finite)
                return s, state
            except Exception as e:  # noqa: BLE001 — any failure here
                # (orbax read error, injected restore fault, manifest
                # mismatch, shape mismatch) means THIS generation is
                # unusable; the one before it may not be
                last_err = e
                if _mon.enabled():
                    _mon.get_registry().counter(
                        _mon.RESILIENCE_CKPT_FALLBACKS,
                        labels={"reason": type(e).__name__},
                        help="checkpoint generations skipped on restore "
                             "(corrupt/unreadable)").inc()
        raise CheckpointIntegrityError(
            f"no restorable checkpoint generation in {self.directory} "
            f"({len(steps)} tried; newest failure: {last_err})") \
            from last_err

    def close(self):
        """Idempotent: wait for any in-flight async save (never tear
        down a half-written checkpoint), surface deferred errors, then
        close — the manager is closed even when the wait raises."""
        if self._closed:
            return
        self._closed = True
        try:
            self.manager.wait_until_finished()
            self.check_for_errors()
        finally:
            self.manager.close()


# canonical implementation moved to runtime/pipeline.py (the host
# pipeline stages EVERY batch through it, not just checkpoint restores);
# re-exported here so existing call/import sites keep working
from deeplearning4j_tpu.runtime.pipeline import (  # noqa: E402,F401
    as_unaliasable, xla_owned_copy)


def place_global(host, sharding):
    """Donation-safe placement of a host array onto ANY NamedSharding —
    including cross-process shardings no single process could
    `device_put` whole. Fully-addressable targets take the ordinary
    `xla_owned_copy`; multi-host targets materialize shard-by-shard via
    `make_array_from_callback`, each shard staged through the
    misaligned-copy trick so XLA owns every buffer (the same aliasing
    hazard class as whole-array staging — a donating step must never
    free numpy-owned memory)."""
    if getattr(sharding, "is_fully_addressable", True):
        return xla_owned_copy(host, sharding)
    host = np.asarray(host)
    return jax.make_array_from_callback(
        host.shape, sharding, lambda idx: as_unaliasable(host[idx]))


def replace_on_mesh(mesh, like, state):
    """Re-place every restored leaf on a mesh-wide sharding taken from
    its `like` counterpart. Orbax restores each leaf committed to its
    `like` placement; a fresh optimizer's scalars (e.g. Adam count) sit
    on one device, which would clash with mesh-committed params inside
    jit — so leaves whose `like` has no NamedSharding get the replicated
    mesh sharding instead. Cross-process shardings place shard-by-shard
    (`place_global`), so a multi-host resume re-creates exactly the
    shards this process owns."""
    from jax.sharding import NamedSharding, PartitionSpec

    def place(fresh, restored):
        sh = fresh.sharding if isinstance(
            getattr(fresh, "sharding", None), NamedSharding) \
            else NamedSharding(mesh, PartitionSpec())
        if not isinstance(restored, np.ndarray) \
                and getattr(restored, "sharding", None) == sh:
            return restored     # restore() already placed it (owned)
        return place_global(restored, sh)

    return jax.tree_util.tree_map(place, like, state)


class ElasticTrainer:
    """Wrap a ShardedTrainer-style step with periodic checkpoints and
    crash-resume (≡ fault-tolerant SharedTrainingMaster loop)."""

    def __init__(self, trainer, directory, save_every=50, max_to_keep=3,
                 sweep_orphans=True):
        self.trainer = trainer
        self.ckpt = ElasticCheckpointer(directory, max_to_keep=max_to_keep,
                                        save_interval_steps=save_every,
                                        sweep_orphans=sweep_orphans)
        self.save_every = int(save_every)
        self.step_num = 0

    def resume_or_init(self, init_params):
        """Restore the newest VERIFIED checkpoint if one exists (manifest
        checksums + finiteness, falling back a generation on corruption —
        the same integrity path FaultTolerantTrainer resumes through),
        else shard the given fresh params. Returns (params, opt_state)."""
        params, opt_state = self.trainer.init(init_params)
        like = {"params": params, "opt_state": opt_state}
        step, state = self.ckpt.restore_verified(like=like)
        if step is None:
            return params, opt_state
        self.step_num = step
        state = replace_on_mesh(self.trainer.mesh, like, state)
        return state["params"], state["opt_state"]

    def fit_batch(self, params, opt_state, batch, rng):
        params, opt_state, loss = self.trainer.fit_batch(
            params, opt_state, batch, rng)
        self.step_num += 1
        if self.step_num % self.save_every == 0:
            self.ckpt.save(self.step_num, params, opt_state)
        return params, opt_state, loss

    def finalize(self, params, opt_state):
        self.ckpt.save(self.step_num, params, opt_state, wait=True)
        self.ckpt.close()


def initialize_multihost(coordinator_address=None, num_processes=None,
                         process_id=None):
    """≡ the reference's cluster join for the elastic path; delegates to
    the hardened bootstrap (parallel/multihost.initialize — single
    implementation), which resolves the `DL4J_*` / `JAX_*` env config
    itself and returns False when no coordinator is configured."""
    from deeplearning4j_tpu.parallel.multihost import initialize
    return initialize(coordinator_address, num_processes, process_id)
