"""Expert parallelism helpers (`ep` mesh axis).

The reference has no MoE; this is a TPU-native addition (models.bert MoE
layers use it implicitly via sharding_rules: expert-major parameter tensors
shard their leading dim over ep, so each chip holds |E|/|ep| experts and
XLA turns the dense one-hot dispatch einsum into an all-to-all).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


def switch_router(x, router_w, num_experts):
    """Top-1 switch routing: returns (one_hot dispatch, gate, aux_loss).
    aux_loss is the standard load-balancing loss (mean_prob · mean_dispatch
    · E) keeping experts evenly used."""
    logits = x @ router_w.astype(x.dtype)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top = jnp.argmax(probs, axis=-1)
    onehot = jax.nn.one_hot(top, num_experts, dtype=x.dtype)
    gate = jnp.max(probs, axis=-1).astype(x.dtype)
    # load-balancing aux loss (Switch Transformer eq. 4)
    density = jnp.mean(onehot.astype(jnp.float32), axis=tuple(range(onehot.ndim - 1)))
    mean_prob = jnp.mean(probs, axis=tuple(range(probs.ndim - 1)))
    aux = num_experts * jnp.sum(density * mean_prob)
    return onehot, gate, aux
