from deeplearning4j_tpu.parallel.mesh import DeviceMesh, initialize_distributed
from deeplearning4j_tpu.parallel.wrapper import ParallelWrapper
from deeplearning4j_tpu.parallel.sharded_trainer import (ParameterAveragingTrainer,
                                                         ShardedTrainer)
from deeplearning4j_tpu.parallel.ulysses import (make_ulysses_attention,
                                                 ulysses_attention_sharded)
from deeplearning4j_tpu.parallel.ring_attention import (blockwise_attention,
                                                        dense_attention,
                                                        make_ring_attention,
                                                        ring_attention)
from deeplearning4j_tpu.parallel.buckets import (BucketPlan,
                                                 check_overlap_structure,
                                                 plan_buckets)
from deeplearning4j_tpu.parallel.compression import (encoded_updater,
                                                     threshold_encoding)
from deeplearning4j_tpu.parallel.elastic import (ElasticCheckpointer,
                                                  ElasticTrainer,
                                                  initialize_multihost)
from deeplearning4j_tpu.parallel.pipeline import (make_pipeline_fn,
                                                  make_pipelined_loss,
                                                  stack_stage_params)
from deeplearning4j_tpu.parallel.zero import (shard_optimizer_state,
                                              state_memory_bytes)
from deeplearning4j_tpu.parallel.inference import (InferenceMode,
                                                   ParallelInference)
from deeplearning4j_tpu.parallel.multihost import (CoordinatedGuardian,
                                                   MultiHostRunner,
                                                   MultiHostTrainer,
                                                   PeerCoordinator)

__all__ = ["DeviceMesh", "initialize_distributed", "ParallelWrapper",
           "ParameterAveragingTrainer", "ShardedTrainer",
           "blockwise_attention", "dense_attention", "make_ring_attention",
           "ring_attention", "encoded_updater", "threshold_encoding",
           "BucketPlan", "check_overlap_structure", "plan_buckets",
           "make_pipeline_fn", "make_pipelined_loss", "stack_stage_params",
           "ElasticCheckpointer", "ElasticTrainer", "initialize_multihost",
           "shard_optimizer_state", "state_memory_bytes",
           "InferenceMode", "ParallelInference",
           "CoordinatedGuardian", "MultiHostRunner", "MultiHostTrainer",
           "PeerCoordinator"]
