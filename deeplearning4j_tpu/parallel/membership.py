"""Elastic cluster membership over the coordination KV (≡ the
reference's SharedTrainingMaster dynamic worker registry: workers
announce themselves to the master, join the parameter-sharing group at
a step boundary, and leave without tearing the run down).

TPU-native inversion: there is no master process holding the roster.
Membership changes ride the same write-once heartbeat agreement the
preemption drain uses — a host announces a JOIN or LEAVE on the KV
store, every member folds the pending announcements into its next
heartbeat, and the UNION over the round's (write-once) heartbeat set is
the agreed membership delta: every member computes the identical REFORM
decision at the identical step, so the dp mesh re-forms at a
coordinated step boundary with no one-sided view possible.

Commit is leader-driven only for KV hygiene (the lowest surviving pid
writes the new roster epoch, admits joiners, deletes the announcement
keys, and reaps the departed hosts' KV state); the roster itself was
already agreed by the heartbeat union before commit runs — a leader
crash mid-commit leaves announcements behind, which simply re-surface
at the next sync point.

Key schema (under the coordinator's namespace):

    em/join/<pid>    announcement: <pid> wants in  (overwrite ok)
    em/leave/<pid>   announcement: <pid> drains out (overwrite ok)
    em/roster/<e>    committed member list for epoch <e> (write-once)
    em/admit/<pid>   joiner's admission ticket: {"epoch", "members"}

`restack_encoder` is the host-side state migration for the per-worker
threshold-encoder stacks when the dp width changes — the elastic
sibling of the runner's `_migrate_encoder` legacy path.
"""
from __future__ import annotations

import json
import time

import numpy as np

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import events as _events
from deeplearning4j_tpu.resilience.errors import MembershipChangeError

__all__ = ["ElasticMembership", "restack_encoder",
           "JOIN_PREFIX", "LEAVE_PREFIX", "ROSTER_PREFIX", "ADMIT_PREFIX"]

JOIN_PREFIX = "em/join/"
LEAVE_PREFIX = "em/leave/"
ROSTER_PREFIX = "em/roster/"
ADMIT_PREFIX = "em/admit/"

#: KV prefixes holding PER-HOST state that must not outlive the host —
#: reaped on leave/replace so /metrics, the /health peer table and the
#: straggler attribution stop showing the departed host as a live row
REAP_PREFIXES = ("metrics/", "steps/", "alive/")


class ElasticMembership:
    """Per-process membership endpoint, attached to a PeerCoordinator.

    The coordinator folds `pending()` into each heartbeat and reaches
    the REFORM decision; the driving runner then calls `commit()` on
    every member at the agreed boundary. Joining hosts use
    `announce_join()` + `await_admission()`."""

    def __init__(self, coordinator, members=None):
        self.c = coordinator
        self.members = sorted(members if members is not None
                              else range(coordinator.num_processes))
        self.epoch = 0
        coordinator.membership = self
        coordinator.members = list(self.members)

    # -- announcements ---------------------------------------------------
    def announce_join(self, pid=None):
        pid = self.c.process_id if pid is None else int(pid)
        self.c.publish(f"{JOIN_PREFIX}{pid}",
                       json.dumps({"pid": pid, "t": time.time()}),
                       overwrite=True)
        return pid

    def announce_leave(self, pid=None):
        pid = self.c.process_id if pid is None else int(pid)
        self.c.publish(f"{LEAVE_PREFIX}{pid}",
                       json.dumps({"pid": pid, "t": time.time()}),
                       overwrite=True)
        if _mon.enabled():
            _events.emit("parallel", _events.MEMBERSHIP_LEAVE,
                         attrs={"pid": pid},
                         correlation_id="membership")
        return pid

    def pending(self):
        """(joins, leaves) currently announced on the KV — this
        process's VIEW, which rides its next heartbeat; the agreed delta
        is the union over the round's heartbeat set, not this."""
        joins = sorted(int(k) for k, _ in self.c.fetch_dir(JOIN_PREFIX)
                       if int(k) not in self.members)
        leaves = sorted(int(k) for k, _ in self.c.fetch_dir(LEAVE_PREFIX)
                        if int(k) in self.members)
        return joins, leaves

    # -- the agreed transition -------------------------------------------
    def commit(self, joins, leaves, info=None):
        """Apply the AGREED delta. Every member calls this with the same
        (joins, leaves) — the union the coordinator computed from the
        round's write-once heartbeats. The leader (lowest surviving pid)
        additionally writes the roster epoch, admits joiners, clears the
        announcements and reaps departed-host KV state. `info` rides the
        joiners' admission tickets (warm-start pointers: drain-save
        step, old dp width, coordinator round counters). Returns the
        new member list."""
        joins = sorted(set(int(p) for p in joins) - set(self.members))
        leaves = sorted(set(int(p) for p in leaves) & set(self.members))
        new_members = sorted((set(self.members) - set(leaves))
                             | set(joins))
        if not new_members:
            raise MembershipChangeError(
                "membership change would leave zero members "
                f"(leaves={leaves})")
        survivors = sorted(set(self.members) - set(leaves))
        leader = min(survivors) if survivors else min(new_members)
        self.epoch += 1
        if self.c.process_id == leader:
            self.c.publish(f"{ROSTER_PREFIX}{self.epoch}",
                           json.dumps({"members": new_members,
                                       "epoch": self.epoch,
                                       "t": time.time()}))
            ticket = {"epoch": self.epoch, "members": new_members}
            if info:
                ticket.update(info)
            for pid in joins:
                self._delete(f"{JOIN_PREFIX}{pid}")
                self.c.publish(f"{ADMIT_PREFIX}{pid}",
                               json.dumps(ticket), overwrite=True)
            for pid in leaves:
                self._delete(f"{LEAVE_PREFIX}{pid}")
                self.reap_host(pid)
        self.members = new_members
        self.c.reform(new_members)
        if _mon.enabled():
            _events.emit("parallel", _events.MEMBERSHIP_EPOCH,
                         attrs={"epoch": self.epoch, "joins": joins,
                                "leaves": leaves,
                                "members": new_members},
                         correlation_id="membership")
        return new_members

    def abandon(self, joins=(), leaves=()):
        """Withdraw announcements after a FAILED transition (fault
        injected / joiner died mid-admission): the previous roster stays
        authoritative and the announcements stop re-surfacing. Safe on
        every member (deletes are idempotent)."""
        for pid in joins:
            self._delete(f"{JOIN_PREFIX}{int(pid)}")
        for pid in leaves:
            self._delete(f"{LEAVE_PREFIX}{int(pid)}")

    def await_admission(self, timeout=None):
        """JOINER side: block until the leader admits this process,
        then adopt the committed roster. Returns the admission ticket
        (epoch, members, plus whatever warm-start info the leader
        attached at commit). Raises the typed `MembershipChangeError`
        when nothing admits us in time (the cluster may have drained,
        or our announcement was abandoned)."""
        t = self.c.peer_timeout if timeout is None else float(timeout)
        try:
            raw = self.c.fetch(f"{ADMIT_PREFIX}{self.c.process_id}",
                               timeout=t)
        except Exception as e:  # noqa: BLE001 — timeout/transport alike
            raise MembershipChangeError(
                f"join announced but never admitted within {t:.1f} s "
                f"({e})") from e
        info = json.loads(raw)
        self.epoch = int(info["epoch"])
        self.members = sorted(int(p) for p in info["members"])
        self.c.reform(self.members)
        if _mon.enabled():
            _events.emit("parallel", _events.MEMBERSHIP_JOINED,
                         attrs={"pid": self.c.process_id,
                                "epoch": self.epoch,
                                "members": self.members},
                         correlation_id="membership")
        return info

    # -- departed-host KV hygiene ----------------------------------------
    def reap_host(self, pid):
        """Delete every KV key a departed host owned: its metrics /
        step-timeline / liveness records (the monitoring planes drop the
        stale row at their next gather) and any heartbeat keys it left
        behind."""
        for pfx in REAP_PREFIXES:
            self._delete(f"{pfx}{pid}")
        # heartbeat keys are round-keyed (hb/<rnd>/<pid>): enumerate and
        # delete the departed pid's leaves
        try:
            for k, _ in self.c.fetch_dir("hb/"):
                if k.endswith(f"/{pid}"):
                    self._delete(f"hb/{k}")
        except Exception:  # noqa: BLE001 — hygiene is best-effort
            pass

    def _delete(self, key):
        try:
            self.c._client.key_value_delete(self.c._key(key))
        except Exception:  # noqa: BLE001 — deletes are best-effort
            pass


def restack_encoder(enc, new_n):
    """Re-stack per-worker threshold-encoder state for a NEW dp width —
    host-side numpy, called at the reform boundary on gathered state
    (the elastic sibling of the runner's `_migrate_encoder`).

    Shrink folds row i into row i % new_n: residual mass is CONSERVED
    (the departed workers' un-sent gradient mass is inherited by the
    survivors instead of silently dropped). Grow keeps the surviving
    rows and appends zero residual for the new workers, with thresholds
    tiled cyclically from the existing rows (a joiner starts from a
    peer's adapted threshold, not the cold-start default). `nnz` is
    telemetry from the LAST step on the OLD width — zeroed either way.
    """
    thr = np.asarray(enc["threshold"])
    old_n = int(thr.shape[0])
    new_n = int(new_n)
    if new_n < 1:
        raise ValueError(f"restack_encoder: new width {new_n} < 1")
    if new_n == old_n:
        return enc

    def stack_rows(a):
        a = np.asarray(a)
        if new_n < old_n:
            out = a[:new_n].copy()
            for i in range(new_n, old_n):
                out[i % new_n] = out[i % new_n] + a[i]
            return out
        return np.concatenate(
            [a, np.zeros((new_n - old_n,) + a.shape[1:], a.dtype)])

    residual = {b: stack_rows(r) for b, r in enc["residual"].items()}
    if new_n < old_n:
        new_thr = thr[:new_n].copy()
    else:
        extra = np.stack([thr[i % old_n] for i in range(old_n, new_n)])
        new_thr = np.concatenate([thr, extra])
    nnz = np.zeros((new_n,) + np.asarray(enc["nnz"]).shape[1:],
                   np.asarray(enc["nnz"]).dtype)
    return {"residual": residual, "threshold": new_thr, "nnz": nnz}
