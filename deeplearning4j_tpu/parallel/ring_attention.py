"""Ring attention — sequence/context parallelism over the `sp` mesh axis.

The reference scales long sequences by truncated BPTT; TPU-native long
context instead shards the sequence across chips and rotates K/V blocks
around the ICI ring (Liu et al., Ring Attention) with an online-softmax
accumulator, overlapping each hop with the local attention block. Used by
models/bert.py + parallel tests; single-device callers get the same math
via `blockwise_attention` (flash-style lax.scan) or `dense_attention`.

Shapes: (B, H, T, D) throughout; softmax stats accumulate in float32.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import shard_map


def dense_attention(q, k, v, causal=False, mask=None, scale=None):
    """Reference O(T²) attention (numerics oracle for the sharded paths)."""
    d = q.shape[-1]
    scale = scale or (1.0 / jnp.sqrt(d).astype(q.dtype))
    logits = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        causal_mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(causal_mask, logits, -jnp.inf)
    if mask is not None:
        logits = jnp.where(mask, logits, -jnp.inf)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", probs, v)


def _block_accumulate(carry, q, k, v, logits_mask, scale):
    """Online-softmax accumulation of one K/V block into (o, l, m)."""
    o, l, m = carry
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k).astype(jnp.float32) * scale
    if logits_mask is not None:
        s = jnp.where(logits_mask, s, -1e30)
    m_new = jnp.maximum(m, jnp.max(s, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(s - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum(
        "bhqk,bhkd->bhqd", p.astype(v.dtype), v).astype(jnp.float32)
    return o_new, l_new, m_new


def blockwise_attention(q, k, v, block_size=512, causal=False,
                        kv_mask=None):
    """Single-device flash-style attention: lax.scan over K/V blocks with
    online softmax — O(T) memory. kv_mask (B, T): padding-key validity
    (invalid keys never receive probability), still O(T) memory."""
    b, h, t, d = q.shape
    scale = 1.0 / jnp.sqrt(d)
    nblk = -(-t // block_size)
    pad = nblk * block_size - t
    if pad:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad), (0, 0)))
    kb = k.reshape(b, h, nblk, -1, d).transpose(2, 0, 1, 3, 4)
    vb = v.reshape(b, h, nblk, -1, d).transpose(2, 0, 1, 3, 4)
    q_pos = jnp.arange(t)
    if kv_mask is not None and pad:
        kv_mask = jnp.pad(kv_mask, ((0, 0), (0, pad)))

    def step(carry, inp):
        kv_idx, kblk, vblk = inp
        k_pos = kv_idx * block_size + jnp.arange(block_size)
        lm = (k_pos[None, :] < t)
        if causal:
            lm = lm & (q_pos[:, None] >= k_pos[None, :])
        lm = lm[None, None]
        if kv_mask is not None:
            blk = lax.dynamic_slice_in_dim(kv_mask, kv_idx * block_size,
                                           block_size, 1)
            lm = lm & (blk > 0)[:, None, None, :]
        return _block_accumulate(carry, q, kblk, vblk, lm, scale), None

    o0 = jnp.zeros((b, h, t, d), jnp.float32)
    l0 = jnp.zeros((b, h, t), jnp.float32)
    m0 = jnp.full((b, h, t), -jnp.inf, jnp.float32)
    (o, l, m), _ = lax.scan(step, (o0, l0, m0),
                            (jnp.arange(nblk), kb, vb))
    return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)


def _make_ring_flash(axis_name, block_q=128, block_k=128, interpret=None,
                     causal=False):
    """Ring attention whose LOCAL block math is the Pallas flash kernel
    pair: forward calls the fused fwd kernel per held K/V block and merges
    the per-block (o, lse) partials with the associative logsumexp merge;
    backward is a second ring pass driving the Pallas dQ / dK-dV kernels
    with the GLOBAL lse (dk/dv partial sums ride around the ring with
    their K/V blocks and arrive home after the full cycle).

    Causal (round-4): at ring step i, shard `my` holds the K/V block of
    shard (my − i) mod n, so the block's GLOBAL position relative to the
    queries is fully determined by the step: i == 0 → the diagonal block
    (run the CAUSAL kernel), i ≤ my → strictly-past block (full kernel),
    i > my → strictly-future block (skipped: lse = −inf in the merge,
    zero grads in backward). lax.cond picks the kernel per step, so each
    step still runs exactly one Pallas program."""
    from deeplearning4j_tpu.kernels.flash_attention import (
        _flash_backward, _flash_forward, _zero_mask_cotangent)

    def _block_fwd(q, kblk, vblk, mblk, i, my):
        """One local flash block, causal- and mask-aware; lse is
        (B*H, tq_padded). mblk is None (static) or the held K/V block's
        key-validity slice."""
        if not causal:
            return _flash_forward(q, kblk, vblk, None, mblk, False,
                                  block_q, block_k, interpret)

        def diag(q, kb, vb, mb):
            return _flash_forward(q, kb, vb, None, mb, True,
                                  block_q, block_k, interpret)

        def past(q, kb, vb, mb):
            return _flash_forward(q, kb, vb, None, mb, False,
                                  block_q, block_k, interpret)

        def future(q, kb, vb, mb):
            # strictly-future block: SKIP the kernel — -inf lse zeroes
            # its weight in the associative merge. Shapes must mirror
            # _flash_forward's returns: out (B,H,T,D), lse (B*H, tq_pad).
            b, h, t_local, d = q.shape
            bq = min(block_q, max(t_local, 8))
            tq_pad = -(-t_local // bq) * bq
            return (jnp.zeros((b, h, t_local, d), q.dtype),
                    jnp.full((b * h, tq_pad), -jnp.inf, jnp.float32))

        if mblk is None:
            return lax.cond(
                i == 0, lambda q, kb, vb: diag(q, kb, vb, None),
                lambda q, kb, vb: lax.cond(
                    i <= my, lambda q2, kb2, vb2: past(q2, kb2, vb2, None),
                    lambda q2, kb2, vb2: future(q2, kb2, vb2, None),
                    q, kb, vb),
                q, kblk, vblk)
        return lax.cond(
            i == 0, diag,
            lambda q, kb, vb, mb: lax.cond(i <= my, past, future,
                                           q, kb, vb, mb),
            q, kblk, vblk, mblk)

    def _fwd_pass(q, k, v, kv_mask):
        """Shared forward ring (kv_mask None or the local mask slice):
        per-block (o, lse) partials merged -inf-safely — a block whose
        kernel saw NO valid key returns the +1e30 invalid-row sentinel,
        which means "contributes nothing" (-inf) in the merge."""
        n = lax.psum(1, axis_name)
        my = lax.axis_index(axis_name)
        b, h, t_local, d = q.shape
        perm = [(j, (j + 1) % n) for j in range(n)]

        def step(carry, i):
            o, lse, kblk, vblk, mblk = carry
            ob, lse_b = _block_fwd(q, kblk, vblk, mblk, i, my)
            lse_b = lse_b[:, :t_local].reshape(b, h, t_local)
            # +1e30 = kernel sentinel (no valid key for the row);
            # <= -1e29 = causal+masked starvation (l ~ 0 at m = -1e30).
            # Both mean "no contribution from this block".
            lse_b = jnp.where((lse_b >= 1e29) | (lse_b <= -1e29),
                              -jnp.inf, lse_b)
            m = jnp.maximum(lse, lse_b)
            m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
            w1 = jnp.where(jnp.isfinite(lse), jnp.exp(lse - m_safe), 0.0)
            w2 = jnp.where(jnp.isfinite(lse_b),
                           jnp.exp(lse_b - m_safe), 0.0)
            s = jnp.maximum(w1 + w2, 1e-30)
            o = (o * w1[..., None]
                 + ob.astype(jnp.float32) * w2[..., None]) / s[..., None]
            lse = m + jnp.log(s)
            kblk = lax.ppermute(kblk, axis_name, perm)
            vblk = lax.ppermute(vblk, axis_name, perm)
            if mblk is not None:
                mblk = lax.ppermute(mblk, axis_name, perm)
            return (o, lse, kblk, vblk, mblk), None

        o0 = jnp.zeros(q.shape, jnp.float32)
        lse0 = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
        (o, lse, _, _, _), _ = lax.scan(step, (o0, lse0, k, v, kv_mask),
                                        jnp.arange(n))
        return o, lse

    def _bwd_pass(q, k, v, kv_mask, o, lse, g):
        n = lax.psum(1, axis_name)
        my = lax.axis_index(axis_name)
        b, h, t_local, d = q.shape
        # rows that saw NO valid key anywhere merged to lse = -inf; the
        # backward recompute needs the kernels' +1e30 sentinel form so
        # p = exp(finite - 1e30) == 0 (never exp(+inf))
        lse = jnp.where(jnp.isfinite(lse), lse, 1e30)
        lse2 = lse.reshape(b * h, t_local)
        perm = [(j, (j + 1) % n) for j in range(n)]

        def _block_bwd(i, kblk, vblk, mblk):
            if not causal:
                return _flash_backward(q, kblk, vblk, None, mblk, o, lse2,
                                       g, False, block_q, block_k,
                                       interpret)

            def diag(kb, vb, mb):
                return _flash_backward(q, kb, vb, None, mb, o, lse2, g,
                                       True, block_q, block_k, interpret)

            def past(kb, vb, mb):
                return _flash_backward(q, kb, vb, None, mb, o, lse2, g,
                                       False, block_q, block_k, interpret)

            def future(kb, vb, mb):
                # the global-lse recompute would give NONZERO p for
                # future blocks (they never entered the softmax) — their
                # gradients are identically zero and must be skipped
                return (jnp.zeros(q.shape, q.dtype),
                        jnp.zeros(kb.shape, kb.dtype),
                        jnp.zeros(vb.shape, vb.dtype))

            if mblk is None:
                return lax.cond(
                    i == 0, lambda kb, vb: diag(kb, vb, None),
                    lambda kb, vb: lax.cond(
                        i <= my, lambda kb2, vb2: past(kb2, vb2, None),
                        lambda kb2, vb2: future(kb2, vb2, None),
                        kb, vb),
                    kblk, vblk)
            return lax.cond(
                i == 0, diag,
                lambda kb, vb, mb: lax.cond(i <= my, past, future,
                                            kb, vb, mb),
                kblk, vblk, mblk)

        def step(carry, i):
            dq, kblk, vblk, mblk, dkblk, dvblk = carry
            dq_i, dk_i, dv_i = _block_bwd(i, kblk, vblk, mblk)
            dq = dq + dq_i.astype(jnp.float32)
            dkblk = dkblk + dk_i.astype(jnp.float32)
            dvblk = dvblk + dv_i.astype(jnp.float32)
            # dk/dv partials travel WITH their K/V blocks; after the full
            # cycle every block (and its gradient sum) is home again
            kblk = lax.ppermute(kblk, axis_name, perm)
            vblk = lax.ppermute(vblk, axis_name, perm)
            if mblk is not None:
                mblk = lax.ppermute(mblk, axis_name, perm)
            dkblk = lax.ppermute(dkblk, axis_name, perm)
            dvblk = lax.ppermute(dvblk, axis_name, perm)
            return (dq, kblk, vblk, mblk, dkblk, dvblk), None

        z = jnp.zeros(q.shape, jnp.float32)
        (dq, _, _, _, dk, dv), _ = lax.scan(
            step, (z, k, v, kv_mask, z, z), jnp.arange(n))
        return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)

    @jax.custom_vjp
    def ring_flash(q, k, v):
        o, _ = _fwd_pass(q, k, v, None)
        return o.astype(q.dtype)

    def fwd(q, k, v):
        o, lse = _fwd_pass(q, k, v, None)
        out = o.astype(q.dtype)
        return out, (q, k, v, out, lse)

    def bwd(res, g):
        q, k, v, o, lse = res
        return _bwd_pass(q, k, v, None, o, lse, g)

    ring_flash.defvjp(fwd, bwd)

    @jax.custom_vjp
    def ring_flash_masked(q, k, v, kv_mask):
        o, _ = _fwd_pass(q, k, v, kv_mask)
        return o.astype(q.dtype)

    def fwd_m(q, k, v, kv_mask):
        o, lse = _fwd_pass(q, k, v, kv_mask)
        out = o.astype(q.dtype)
        return out, (q, k, v, kv_mask, out, lse)

    def bwd_m(res, g):
        q, k, v, kv_mask, o, lse = res
        dq, dk, dv = _bwd_pass(q, k, v, kv_mask, o, lse, g)
        return dq, dk, dv, _zero_mask_cotangent(kv_mask)

    ring_flash_masked.defvjp(fwd_m, bwd_m)

    def ring_flash_entry(q, k, v, kv_mask=None):
        if kv_mask is None:
            return ring_flash(q, k, v)
        return ring_flash_masked(q, k, v, kv_mask)

    return ring_flash_entry


def make_ring_attention(mesh, axis_name="sp", causal=False, use_flash=None,
                        block_q=128, block_k=128, interpret=None):
    """Build a ring-attention fn for q,k,v sharded over `axis_name` on the
    time dim. Returns f(q_local, k_local, v_local) usable INSIDE shard_map
    over `mesh` — each of the n devices holds (B, H, T/n, D) and K/V blocks
    ppermute around the ring, one ICI hop per step.

    use_flash (default: auto — on TPU, noncausal): local block math runs
    the Pallas flash kernels (fwd + bwd) composed with the ring, so the
    sp path gets the fused-kernel HBM profile instead of the lax.scan
    accumulator. Causal can ride the same kernels (round-4: diagonal ring
    step → causal kernel, past steps → full kernel, future steps skipped)
    but stays OPT-IN (use_flash=True) until it has an on-chip smoke run —
    interpret-mode tests don't validate Mosaic lowering (BENCH.md
    round-3 lesson).

    Padded batches: BOTH paths take kv_mask (a local (B, T/n) slice
    that rotates with its K/V block). The masked FLASH ring (round-5)
    feeds each held block's slice into the kernels' own kv_mask path
    (fwd + bwd) with -inf-safe partial merging; like causal, it stays
    OPT-IN (use_flash=True) until an on-chip smoke —
    ring_attention() auto-selects the lax ring for masked batches."""
    if use_flash is None:
        use_flash = jax.default_backend() == "tpu" and not causal
    if use_flash:
        return _make_ring_flash(axis_name, block_q, block_k, interpret,
                                causal=causal)

    def ring_attn(q, k, v, kv_mask=None):
        """kv_mask (round-5): local (B, T/n) key-validity slice — it
        rotates around the ring WITH its K/V block, so padded keys never
        receive probability from any device's queries (O(T/n) memory,
        no full-mask gather)."""
        n = lax.psum(1, axis_name)
        my = lax.axis_index(axis_name)
        b, h, t_local, d = q.shape
        scale = 1.0 / jnp.sqrt(d)
        q_pos = my * t_local + jnp.arange(t_local)

        def step(carry, i):
            o, l, m, kblk, vblk, mblk = carry
            src_idx = (my - i) % n  # whose K/V block we currently hold
            lm = None
            if causal:
                k_pos = src_idx * t_local + jnp.arange(t_local)
                lm = (q_pos[:, None] >= k_pos[None, :])[None, None]
            if mblk is not None:
                km = (mblk > 0)[:, None, None, :]   # (B,1,1,T/n)
                lm = km if lm is None else (lm & km)
            o, l, m = _block_accumulate((o, l, m), q, kblk, vblk, lm, scale)
            # rotate K/V (+ their mask slice) one hop around the ring
            # (overlaps with next block on TPU: XLA schedules the
            # collective-permute async)
            perm = [(j, (j + 1) % n) for j in range(n)]
            kblk = lax.ppermute(kblk, axis_name, perm)
            vblk = lax.ppermute(vblk, axis_name, perm)
            if mblk is not None:
                mblk = lax.ppermute(mblk, axis_name, perm)
            return (o, l, m, kblk, vblk, mblk), None

        o0 = jnp.zeros((b, h, t_local, d), jnp.float32)
        l0 = jnp.zeros((b, h, t_local), jnp.float32)
        m0 = jnp.full((b, h, t_local), -jnp.inf, jnp.float32)
        (o, l, m, _, _, _), _ = lax.scan(step, (o0, l0, m0, k, v, kv_mask),
                                         jnp.arange(n))
        return (o / jnp.maximum(l, 1e-30)[..., None]).astype(q.dtype)

    return ring_attn


def ring_attention(q, k, v, mesh, axis_name="sp", causal=False,
                   kv_mask=None):
    """Convenience wrapper: shard (B,H,T,D) over T, run the ring, gather.
    kv_mask: global (B, T) key-validity mask for padded batches — NOTE
    masked batches auto-select the lax ring (the masked flash ring
    exists but is opt-in via make_ring_attention(use_flash=True) until
    it has an on-chip smoke run)."""
    fn = make_ring_attention(mesh, axis_name, causal,
                             use_flash=False if kv_mask is not None
                             else None)
    spec = P(None, None, axis_name, None)
    args, specs = [q, k, v], [spec, spec, spec]
    if kv_mask is not None:
        args.append(kv_mask)
        specs.append(P(None, axis_name))
    shmapped = shard_map(fn, mesh=mesh, in_specs=tuple(specs),
                             out_specs=spec, check_vma=False)
    return shmapped(*args)
