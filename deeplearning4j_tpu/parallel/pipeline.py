"""Pipeline parallelism over the `pp` mesh axis (GPipe-style microbatching).

The reference has no pipeline parallelism (its scale-out is data-parallel
only) — this is a TPU-native addition required for models deeper than one
chip's HBM. Design: the layer stack is split into S = |pp| equal stages;
stage s's params live on pp-shard s (leading stage axis sharded over pp).
Inside shard_map, microbatches stream through the classic GPipe schedule:
S + M - 1 ticks, activations hop stage→stage via ppermute each tick.
Backward is just jax.grad through the shard_map (ppermute transposes to the
reverse hop), so the whole pipeline — forward, bubble, backward — is ONE
XLA program.

Usage: stage_fn(stage_params, x) -> y applies ONE stage's chunk of layers.
All stages must share one stage_fn/param-structure (equal chunks).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.mesh import shard_map


def stack_stage_params(per_stage_params):
    """[stage0_tree, stage1_tree, ...] -> one tree with leading stage dim."""
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs),
                                  *per_stage_params)


def make_pipeline_fn(stage_fn, mesh, n_microbatches, axis_name="pp"):
    """Returns f(stacked_stage_params, x) -> y running the GPipe schedule.

    x: (B, ...) global batch; split into n_microbatches along dim 0.
    stacked_stage_params: leading dim = n_stages, sharded over `axis_name`.
    """

    def pipeline(stage_params, x):
        # inside shard_map: stage_params has leading dim 1 (this shard's)
        params = jax.tree_util.tree_map(lambda a: a[0], stage_params)
        n_stages = jax.lax.psum(1, axis_name)
        stage = jax.lax.axis_index(axis_name)
        mb = x.reshape((n_microbatches, -1) + x.shape[1:])
        n_ticks = n_microbatches + n_stages - 1
        state = jnp.zeros_like(mb[0])
        outputs = jnp.zeros_like(mb)
        perm = [(j, (j + 1) % n_stages) for j in range(n_stages)]

        def tick(carry, t):
            state, outputs = carry
            # stage 0 ingests microbatch t (while t < M); others use the
            # activation that just arrived from the previous stage
            mb_idx = jnp.clip(t, 0, n_microbatches - 1)
            inp = jnp.where(stage == 0, mb[mb_idx], state)
            out = stage_fn(params, inp)
            # last stage emits microbatch (t - (S-1)) when valid
            out_idx = jnp.clip(t - (n_stages - 1), 0, n_microbatches - 1)
            valid = (t >= n_stages - 1)
            outputs = jax.lax.cond(
                valid & (stage == n_stages - 1),
                lambda o: o.at[out_idx].set(out),
                lambda o: o, outputs)
            # hop activations forward one stage
            state = jax.lax.ppermute(out, axis_name, perm)
            return (state, outputs), None

        (state, outputs), _ = jax.lax.scan(tick, (state, outputs),
                                           jnp.arange(n_ticks))
        # only the last stage holds real outputs; broadcast to all shards
        outputs = jax.lax.psum(
            jnp.where(stage == n_stages - 1, outputs, 0.0), axis_name)
        return outputs.reshape((-1,) + x.shape[1:])

    def wrapped(stacked_params, x):
        in_specs = (jax.tree_util.tree_map(lambda _: P(axis_name),
                                           stacked_params), P())
        return shard_map(pipeline, mesh=mesh,
                             in_specs=in_specs, out_specs=P(),
                             check_vma=False)(stacked_params, x)

    return wrapped


def make_pipelined_loss(stage_fn, loss_head, mesh, n_microbatches,
                        axis_name="pp"):
    """loss(stacked_params, head_params, x, y) with the pipeline inside —
    differentiable end-to-end (grads flow back through the reversed ring)."""
    pipe = make_pipeline_fn(stage_fn, mesh, n_microbatches, axis_name)

    def loss(stacked_params, head_params, x, y):
        h = pipe(stacked_params, x)
        return loss_head(head_params, h, y)

    return loss
