"""Threshold-encoded gradient sharing (≡ nd4j-parameter-server /
EncodedGradientsAccumulator + the 1.5-style threshold encoding used by
SharedTrainingMaster).

Reference behavior: each worker quantizes its gradient to {−t, 0, +t}
(elements |g| ≥ threshold), ships only those, and keeps the un-sent
remainder in a residual buffer that is added back next step; the threshold
adapts to keep message sparsity in a target band.

On TPU the all-reduce rides ICI and needs no compression — so this is an
OPTIONAL optax transform (off by default, documented) providing functional
parity: updates are thresholded with residual accumulation; everything
stays inside the jitted step (no host round-trip).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


#: the encoder's starting threshold — shared with the multi-host
#: trainer's stacked per-worker state init so the two can never drift
DEFAULT_INITIAL_THRESHOLD = 1e-3


def threshold_encoding(initial_threshold=DEFAULT_INITIAL_THRESHOLD,
                       min_threshold=1e-5,
                       decay=0.95, boost=1.2, target_sparsity=1e-3):
    """optax transform: g -> quantized {−t,0,+t} with residual feedback.

    The adaptive rule mirrors the reference: if fewer than
    `target_sparsity` of elements clear the threshold, the threshold decays
    (send more next step); if vastly more clear it, it boosts.
    """

    def init_fn(params):
        residual = jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"residual": residual,
                "threshold": jnp.asarray(initial_threshold, jnp.float32),
                # elements shipped last step (the wire-cost telemetry the
                # multi-host trainer surfaces as dl4j.dist.encoded_bytes);
                # device scalar so the update stays sync-free
                "nnz": jnp.asarray(0, jnp.int32)}

    def update_fn(updates, state, params=None):
        del params
        thr = state["threshold"]

        def encode(g, r):
            acc = g + r
            mask = jnp.abs(acc) >= thr
            sent = jnp.where(mask, jnp.sign(acc) * thr, 0.0).astype(g.dtype)
            new_r = acc - sent
            return sent, new_r

        flat_updates, treedef = jax.tree_util.tree_flatten(updates)
        flat_res = jax.tree_util.tree_leaves(state["residual"])
        enc = [encode(g, r) for g, r in zip(flat_updates, flat_res)]
        sent = jax.tree_util.tree_unflatten(treedef, [e[0] for e in enc])
        residual = jax.tree_util.tree_unflatten(treedef, [e[1] for e in enc])
        total = sum(g.size for g in flat_updates)
        nonzero = sum(jnp.sum(jnp.abs(e[0]) > 0) for e in enc)
        frac = nonzero / total
        new_thr = jnp.where(frac < target_sparsity, thr * decay,
                            jnp.where(frac > 50 * target_sparsity,
                                      thr * boost, thr))
        new_thr = jnp.maximum(new_thr, min_threshold)
        return sent, {"residual": residual, "threshold": new_thr,
                      "nnz": jnp.asarray(nonzero, jnp.int32)}

    return optax.GradientTransformation(init_fn, update_fn)


# -- sparse ragged wire format (ISSUE 17) -----------------------------------
#
# The dense exchange pmean's a {−t,0,+t} tensor the size of the bucket; the
# reference stack (EncodedGradientsAccumulator over Aeron) ships messages
# whose size tracks nnz instead. Wire layout per worker per bucket, one
# int32 vector of `capacity + 2` elements:
#
#   [ count | threshold_bits | tok_0 ... tok_{K-1} ]
#
#   count          shipped element count (<= capacity)
#   threshold_bits the f32 threshold bit-cast to int32 (receiver needs t
#                  to reconstruct ±t values)
#   tok            (index+1) * sign for each shipped element; 0 = empty
#                  slot. The +1 bias keeps index 0 representable with a
#                  sign.
#
# Size-prefixed in the header, fixed capacity on the wire so the allgather
# stays a static-shape collective (jit-compatible ragged-ness: the payload
# is ragged in *meaning* — trailing zero slots — not in shape). Decode
# scatters with mode='drop', so a corrupt out-of-range token can never
# write out of bounds; structural corruption (bad count, nonsense
# threshold, out-of-range index) poisons the delivered gradient to NaN so
# the guardian gates the step — never a silent wrong-gradient.

#: header slots in front of the token array: [count, threshold_bits]
WIRE_HEADER = 2


def wire_capacity(elems, frac):
    """Per-bucket token capacity: `frac` of the bucket's elements, at least
    1, never more than the bucket itself. Host-side, static per plan."""
    return max(1, min(int(elems), int(-(-elems * frac // 1))))


def wire_payload_bytes(capacity):
    """Per-worker wire bytes for one bucket at the given capacity."""
    return (int(capacity) + WIRE_HEADER) * 4


def sparse_encode(flat, state, capacity, min_threshold=1e-5,
                  decay=0.95, boost=1.2, target_sparsity=1e-3):
    """Encode one worker's bucket gradient into (payload, new_state).

    The residual/threshold math is the dense encoder's
    (`threshold_encoding`), op for op: as long as nnz <= capacity the
    shipped set equals the dense mask, the residual update is identical,
    and the adaptive-threshold rule keys off the TRUE mask count — so
    dense and sparse state trajectories match bit-exactly whenever
    nothing overflows. On overflow the first `capacity` above-threshold
    elements ship and the rest stay in the residual (shipped next step
    after the threshold boosts), so the wire never lies about what was
    delivered."""
    elems = flat.size
    thr = state["threshold"]
    acc = flat + state["residual"]
    mask = jnp.abs(acc) >= thr
    dense_sent = jnp.where(mask, jnp.sign(acc) * thr, 0.0).astype(flat.dtype)
    nnz = jnp.sum(jnp.abs(dense_sent) > 0)

    idx = jnp.nonzero(mask, size=capacity, fill_value=elems)[0]
    idx = idx.astype(jnp.int32)
    vals = jnp.take(dense_sent, idx, mode="fill", fill_value=0)
    # what actually ships (== dense_sent unless capacity overflowed)
    sent = jnp.zeros_like(flat).at[idx].add(vals, mode="drop")
    new_r = acc - sent

    frac = nnz / elems
    new_thr = jnp.where(frac < target_sparsity, thr * decay,
                        jnp.where(frac > 50 * target_sparsity,
                                  thr * boost, thr))
    new_thr = jnp.maximum(new_thr, min_threshold)

    sgn = jnp.where(vals > 0, 1, jnp.where(vals < 0, -1, 0)).astype(jnp.int32)
    tok = (idx + 1) * sgn
    count = jnp.sum(sgn != 0).astype(jnp.int32)
    thr_bits = jax.lax.bitcast_convert_type(
        thr.astype(jnp.float32), jnp.int32)
    payload = jnp.concatenate([count[None], thr_bits[None], tok])
    return payload, {"residual": new_r, "threshold": new_thr,
                     "nnz": nnz.astype(jnp.int32)}


def _decode_row(row, elems, dtype):
    """One worker's payload -> its dense {−t,0,+t} contribution (bit-equal
    to what that worker's dense encoder would have produced), NaN-poisoned
    if the message is structurally corrupt."""
    count, thr_bits, tok = row[0], row[1], row[WIRE_HEADER:]
    thr = jax.lax.bitcast_convert_type(thr_bits, jnp.float32)
    valid = tok != 0
    idx = jnp.where(valid, jnp.abs(tok) - 1, elems)
    sgn = jnp.sign(tok).astype(jnp.float32)
    vals = jnp.where(valid, (sgn * thr).astype(dtype), 0).astype(dtype)
    out = jnp.zeros((elems,), dtype).at[idx].add(vals, mode="drop")
    ok = ((count == jnp.sum(valid))
          & (count <= tok.shape[0])
          & jnp.isfinite(thr) & (thr > 0)
          & jnp.all(jnp.where(valid, idx < elems, True)))
    return jnp.where(ok, out, jnp.full((), jnp.nan, dtype))


def sparse_decode(gathered, elems, dtype):
    """Decode-and-accumulate the allgathered payloads (num_workers,
    capacity+2) into the mean delivered gradient.

    The accumulation is an explicit linear chain in worker order — on this
    backend that reproduces `jax.lax.pmean`'s reduction order bit-for-bit
    (asserted by the tier-1 wire tests), which is what keeps the sparse
    exchange bit-identical to the dense one at fixed membership.
    """
    n = gathered.shape[0]
    acc = _decode_row(gathered[0], elems, dtype)
    for w in range(1, n):
        acc = acc + _decode_row(gathered[w], elems, dtype)
    return acc / n


def check_payload(payload, elems, capacity=None):
    """Host-side structural validation of one wire message; raises the
    typed `WireFormatError` naming the violation. Used by the recovery /
    chaos paths — the hot decode stays in-jit and poisons instead."""
    import numpy as np

    from deeplearning4j_tpu.resilience.errors import WireFormatError

    p = np.asarray(payload)
    if p.ndim != 1 or p.size < WIRE_HEADER:
        raise WireFormatError(
            f"truncated wire message: {p.size} slots < header {WIRE_HEADER}")
    if capacity is not None and p.size != capacity + WIRE_HEADER:
        raise WireFormatError(
            f"wire message size {p.size} != capacity {capacity} + header")
    count = int(p[0])
    thr = float(np.frombuffer(
        np.asarray(p[1], np.int32).tobytes(), np.float32)[0])
    tok = p[WIRE_HEADER:]
    nz = int(np.count_nonzero(tok))
    if count != nz:
        raise WireFormatError(
            f"wire count field {count} != {nz} non-empty tokens")
    if not np.isfinite(thr) or thr <= 0:
        raise WireFormatError(f"wire threshold {thr!r} not a positive float")
    idx = np.abs(tok[tok != 0]) - 1
    if idx.size and int(idx.max()) >= elems:
        raise WireFormatError(
            f"wire token index {int(idx.max())} out of range for "
            f"{elems}-element bucket")
    return count, thr


def encoder_stats(enc_state):
    """Device-scalar wire telemetry for a (possibly per-worker-stacked)
    threshold-encoding state: mean adaptive threshold, total elements
    shipped last step, and the un-sent residual mass. Pure jax — the
    multi-host sync point jits this once and materializes the three
    scalars together at flush cadence (never per step)."""
    return {"threshold": jnp.mean(enc_state["threshold"]),
            "nnz": jnp.sum(enc_state["nnz"]),
            "residual_norm": optax.global_norm(enc_state["residual"])}


def encoded_updater(updater, **kw):
    """Chain threshold encoding in front of any framework updater:
    functional parity with EncodedGradientsAccumulator-wrapped workers."""
    from deeplearning4j_tpu.nn.updaters import Updater
    tx = updater.to_optax() if isinstance(updater, Updater) else updater
    return optax.chain(threshold_encoding(**kw), tx)
