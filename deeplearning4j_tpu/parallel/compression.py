"""Threshold-encoded gradient sharing (≡ nd4j-parameter-server /
EncodedGradientsAccumulator + the 1.5-style threshold encoding used by
SharedTrainingMaster).

Reference behavior: each worker quantizes its gradient to {−t, 0, +t}
(elements |g| ≥ threshold), ships only those, and keeps the un-sent
remainder in a residual buffer that is added back next step; the threshold
adapts to keep message sparsity in a target band.

On TPU the all-reduce rides ICI and needs no compression — so this is an
OPTIONAL optax transform (off by default, documented) providing functional
parity: updates are thresholded with residual accumulation; everything
stays inside the jitted step (no host round-trip).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


#: the encoder's starting threshold — shared with the multi-host
#: trainer's stacked per-worker state init so the two can never drift
DEFAULT_INITIAL_THRESHOLD = 1e-3


def threshold_encoding(initial_threshold=DEFAULT_INITIAL_THRESHOLD,
                       min_threshold=1e-5,
                       decay=0.95, boost=1.2, target_sparsity=1e-3):
    """optax transform: g -> quantized {−t,0,+t} with residual feedback.

    The adaptive rule mirrors the reference: if fewer than
    `target_sparsity` of elements clear the threshold, the threshold decays
    (send more next step); if vastly more clear it, it boosts.
    """

    def init_fn(params):
        residual = jax.tree_util.tree_map(jnp.zeros_like, params)
        return {"residual": residual,
                "threshold": jnp.asarray(initial_threshold, jnp.float32),
                # elements shipped last step (the wire-cost telemetry the
                # multi-host trainer surfaces as dl4j.dist.encoded_bytes);
                # device scalar so the update stays sync-free
                "nnz": jnp.asarray(0, jnp.int32)}

    def update_fn(updates, state, params=None):
        del params
        thr = state["threshold"]

        def encode(g, r):
            acc = g + r
            mask = jnp.abs(acc) >= thr
            sent = jnp.where(mask, jnp.sign(acc) * thr, 0.0).astype(g.dtype)
            new_r = acc - sent
            return sent, new_r

        flat_updates, treedef = jax.tree_util.tree_flatten(updates)
        flat_res = jax.tree_util.tree_leaves(state["residual"])
        enc = [encode(g, r) for g, r in zip(flat_updates, flat_res)]
        sent = jax.tree_util.tree_unflatten(treedef, [e[0] for e in enc])
        residual = jax.tree_util.tree_unflatten(treedef, [e[1] for e in enc])
        total = sum(g.size for g in flat_updates)
        nonzero = sum(jnp.sum(jnp.abs(e[0]) > 0) for e in enc)
        frac = nonzero / total
        new_thr = jnp.where(frac < target_sparsity, thr * decay,
                            jnp.where(frac > 50 * target_sparsity,
                                      thr * boost, thr))
        new_thr = jnp.maximum(new_thr, min_threshold)
        return sent, {"residual": residual, "threshold": new_thr,
                      "nnz": jnp.asarray(nonzero, jnp.int32)}

    return optax.GradientTransformation(init_fn, update_fn)


def encoder_stats(enc_state):
    """Device-scalar wire telemetry for a (possibly per-worker-stacked)
    threshold-encoding state: mean adaptive threshold, total elements
    shipped last step, and the un-sent residual mass. Pure jax — the
    multi-host sync point jits this once and materializes the three
    scalars together at flush cadence (never per step)."""
    return {"threshold": jnp.mean(enc_state["threshold"]),
            "nnz": jnp.sum(enc_state["nnz"]),
            "residual_norm": optax.global_norm(enc_state["residual"])}


def encoded_updater(updater, **kw):
    """Chain threshold encoding in front of any framework updater:
    functional parity with EncodedGradientsAccumulator-wrapped workers."""
    from deeplearning4j_tpu.nn.updaters import Updater
    tx = updater.to_optax() if isinstance(updater, Updater) else updater
    return optax.chain(threshold_encoding(**kw), tx)
