"""ShardedTrainer + ParameterAveragingTrainer (≡ dl4j-spark ::
SharedTrainingMaster / ParameterAveragingTrainingMaster).

The reference's distributed story: Spark workers compute gradients, share
them via threshold-encoded Aeron messages (SharedTrainingMaster) or
periodically average full parameters (ParameterAveragingTrainingMaster).

TPU-native inversion: ONE jitted SPMD step over a (dp, tp, ...) mesh.
- ShardedTrainer: sync gradient all-reduce every step — the psum rides ICI
  (intra-host) / DCN (multi-host via jax.distributed); mathematically the
  averagingFrequency=1 case of the reference, with none of its staleness.
- ParameterAveragingTrainer: the reference's semantics faithfully — N local
  steps on each dp shard with NO gradient sync, then a pmean of params
  every N iterations (useful for comparisons; sync SPMD is the fast path).

Works with any loss_fn(params, batch, rng) -> scalar; param shardings come
from a PartitionSpec tree (e.g. models.bert.sharding_rules).
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import profiler as _prof
from deeplearning4j_tpu.nn import accum as _accum
from deeplearning4j_tpu.nn.updaters import Updater
from deeplearning4j_tpu.parallel import coordination as _dist
from deeplearning4j_tpu.parallel.mesh import shard_map
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import guardian as _guardian
from deeplearning4j_tpu.resilience import watchdog as _watchdog


def _as_tx(updater):
    return updater.to_optax() if isinstance(updater, Updater) else updater


def accumulate_grads(loss_fn, params, batch, rng, n_micro):
    """The trainer-facing accumulation entry (ShardedTrainer,
    MultiHostTrainer's local worker): lax.scan over `n_micro`
    microbatches (batch leaves carry a leading (G, ...) axis), summing
    gradients and loss ON DEVICE — one dispatch and one optimizer step
    regardless of G. The scan body is `nn/accum.accum_scan`, the ONE
    shared core all five accumulated step builders drive (the nn/
    model steps call it directly with their bn/vertex state threaded).

    Returns (mean_grads, mean_loss, micro_ok) where micro_ok is the AND
    of per-microbatch loss finiteness: a NaN/inf in ANY microbatch
    survives into the verdict even though only the accumulated gradient
    is inspected downstream (non-finite values also propagate through
    the on-device sum, so the accumulated gnorm catches them — micro_ok
    makes the per-microbatch contract explicit and covers a NaN loss
    with finite grads). `n_micro == 1` is byte-for-byte the plain step:
    no scan, no rng fold — existing key streams stay bit-identical.

    The microbatch rng is fold_in(rng, i), so the scanned stream equals
    an explicit sequential loop folding the same indices."""
    if n_micro <= 1:
        loss, grads = jax.value_and_grad(loss_fn)(params, batch, rng)
        return grads, loss, jnp.isfinite(loss)

    def grad_fn(p, s, inp):
        i, mb = inp
        loss, grads = jax.value_and_grad(loss_fn)(
            p, mb, jax.random.fold_in(rng, i))
        return (loss, s), grads

    grads, loss, ok, _ = _accum.accum_scan(
        grad_fn, params, jnp.float32(0.0),   # stateless: dummy carry
        (jnp.arange(n_micro), batch))
    return grads, loss, ok


class ShardedTrainer:
    """Sync-SPMD trainer over an explicit mesh.

    loss_fn(params, batch, rng) -> scalar; batch dim-0 shards over `dp`;
    params shard per `param_specs` (replicated where None).

    accumulation=G > 1 turns each fit_batch into ONE jitted optimizer
    step over a staged SUPER-batch whose leaves carry a leading
    microbatch axis (G, B, ...): the step lax.scans the G backward
    passes, accumulates gradients on device, and applies a single
    update — one dispatch and one host fetch per optimizer step
    regardless of G (the naive loop costs G dispatches + G updates),
    so effective batch sizes scale past what HBM can hold at once.
    The per-dp-shard batch dim is the SECOND axis; `shard_batch`
    handles the placement.
    """

    def __init__(self, loss_fn, updater, mesh, param_specs=None,
                 batch_axis="dp", donate=True, accumulation=1):
        self.loss_fn = loss_fn
        self.tx = _as_tx(updater)
        self.mesh = mesh
        self.param_specs = param_specs
        self.batch_axis = batch_axis
        self._donate = donate
        self.accumulation = int(accumulation)
        if self.accumulation < 1:
            raise ValueError("accumulation must be >= 1")
        self._step = None
        if self.accumulation > 1 and _mon.enabled():
            _mon.get_registry().gauge(
                _mon.DIST_ACCUM_MICROBATCHES,
                help="microbatches accumulated per optimizer step") \
                .set(self.accumulation)

    # -- placement -------------------------------------------------------
    def shard_params(self, params):
        """Host leaves go through xla_owned_copy, NOT a bare device_put:
        the step donates params, and device_put of a suitably-aligned
        numpy array can zero-copy ALIAS it on this backend — the donating
        step would then free memory numpy owns (heap corruption that
        surfaced as nondeterministic garbage losses; same root cause as
        the runtime/pipeline.py staging hazard)."""
        from deeplearning4j_tpu.runtime.pipeline import xla_owned_copy

        def put(a, s):
            sh = s if isinstance(s, NamedSharding) \
                else NamedSharding(self.mesh, s)
            if isinstance(a, jax.Array):
                return jax.device_put(a, sh)
            return xla_owned_copy(a, sh)

        if self.param_specs is None:
            rep = P()
            return jax.tree_util.tree_map(lambda a: put(a, rep), params)
        return jax.tree_util.tree_map(put, params, self.param_specs)

    def shard_batch(self, batch, owned=False):
        """dp-shard one batch pytree. owned=True stages host leaves
        through XLA-owned copies (runtime/pipeline.xla_owned_copy) — the
        background prefetch path uses it so staged buffers can never
        alias loader-owned numpy memory.

        With accumulation > 1 the batch is a SUPER-batch: leading axis =
        microbatch index (replicated), dim 1 = per-microbatch batch dim
        (dp-sharded) — the PR 3 prefetch stages whole super-batches the
        same way, so the host pipeline rides unchanged."""
        spec = (P(None, self.batch_axis) if self.accumulation > 1
                else P(self.batch_axis))
        sh = NamedSharding(self.mesh, spec)

        def put(a):
            _mon.record_transfer(getattr(a, "nbytes", 0))
            if owned and not isinstance(a, jax.Array):
                from deeplearning4j_tpu.runtime.pipeline import \
                    xla_owned_copy
                return xla_owned_copy(a, sh)
            return jax.device_put(a, sh)

        return jax.tree_util.tree_map(put, batch)

    def prefetch_batches(self, batches, depth=2):
        """The host-pipeline wiring for this functional trainer: returns
        an iterator whose background worker pulls `batches` (any
        iterable or DataSetIterator-protocol source of batch pytrees)
        and dp-shards batch N+1 onto the mesh while the caller's step N
        computes.

            it = trainer.prefetch_batches(loader, depth=2)
            for staged in it:
                params, opt_state, loss = trainer.fit_batch(
                    params, opt_state, staged, rng)

        Call .close() (or exhaust it) to stop the worker."""
        from deeplearning4j_tpu.runtime.pipeline import PrefetchIterator
        return PrefetchIterator(
            batches, depth=depth,
            stage=lambda b: self.shard_batch(b, owned=True))

    def init(self, params):
        params = self.shard_params(params)
        opt_state = self.tx.init(params)
        return params, opt_state

    # -- the one step ----------------------------------------------------
    def make_step(self):
        if self._step is not None:
            return self._step
        tx = self.tx
        loss_fn = self.loss_fn
        n_micro = self.accumulation

        donate = (0, 1) if self._donate else ()

        @functools.partial(jax.jit, donate_argnums=donate)
        def step(params, opt_state, batch, rng):
            grads, loss, _ = accumulate_grads(loss_fn, params, batch,
                                              rng, n_micro)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        self._step = step
        return step

    def make_guarded_step(self):
        """Guardian variant of `make_step` (see
        nn/multilayer._train_step_guarded): same update + device health
        verdict (finite loss, finite global grad norm under the
        guardian's threshold), applied only when healthy — a NaN
        gradient never reaches the sharded params. The psum'd gnorm is
        replicated, so every shard takes the same branch."""
        cached = getattr(self, "_guarded_step", None)
        if cached is not None:
            return cached
        tx = self.tx
        loss_fn = self.loss_fn
        n_micro = self.accumulation
        donate = (0, 1) if self._donate else ()

        @functools.partial(jax.jit, donate_argnums=donate)
        def step(params, opt_state, batch, rng, lr_scale, max_gnorm):
            grads, loss, micro_ok = accumulate_grads(
                loss_fn, params, batch, rng, n_micro)
            # the verdict gates the ACCUMULATED update, but a NaN in any
            # single microbatch still fails it: poison the loss the
            # verdict inspects (non-finite grads also propagate through
            # the accumulated gnorm)
            vloss = jnp.where(micro_ok, loss, jnp.float32(jnp.nan))
            params, opt_state, _, gnorm, ok = _guardian.guarded_apply(
                tx, grads, vloss, params, opt_state, lr_scale, max_gnorm)
            return params, opt_state, loss, gnorm, ok

        self._guarded_step = step
        return step

    def fit_batch(self, params, opt_state, batch, rng):
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"sharded_trainer@{id(self):x}")
        if _dist.ACTIVE is not None:
            # multi-host sync point every sync_every steps: heartbeat +
            # step agreement + preemption decision (one int increment
            # and a modulo off the sync cadence); `self` lets a bound
            # coordinator ignore host-local auxiliary trainers
            _dist.ACTIVE.on_step(self)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_start()
        _g = _guardian.ACTIVE
        with _mon.span("sharded.dispatch"):
            if _g is not None:
                params, opt_state, loss, gnorm, ok = \
                    self.make_guarded_step()(params, opt_state, batch,
                                             rng, _g.lr_scale,
                                             _g.max_gnorm)
                out = (params, opt_state, loss)
            else:
                out = self.make_step()(params, opt_state, batch, rng)
        if _g is not None:
            # device scalars; no sync here. `source` lets a bound
            # (coordinated) guardian ignore auxiliary local trainers
            _g.on_step(loss, gnorm, ok, source=self)
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()
        return out


class ParameterAveragingTrainer:
    """≡ ParameterAveragingTrainingMaster: independent local steps per dp
    shard, parameters pmean-ed every `averaging_frequency` iterations.
    Implemented with shard_map so each dp slice REALLY trains independently
    between averages (gradient psum intentionally absent)."""

    def __init__(self, loss_fn, updater, mesh, averaging_frequency=5,
                 batch_axis="dp"):
        self.loss_fn = loss_fn
        self.tx = _as_tx(updater)
        self.mesh = mesh
        self.freq = int(averaging_frequency)
        self.batch_axis = batch_axis
        self._step = None

    @property
    def _n(self):
        return self.mesh.shape[self.batch_axis]

    def init(self, params):
        """Replicate params N times with a leading per-worker axis sharded
        over dp — each worker REALLY owns a divergent copy between
        averages, like the reference's Spark workers."""
        n = self._n
        stacked = jax.tree_util.tree_map(
            lambda a: jnp.broadcast_to(a[None], (n,) + a.shape), params)
        sh = NamedSharding(self.mesh, P(self.batch_axis))
        stacked = jax.device_put(stacked, sh)
        opt_stacked = jax.jit(jax.vmap(self.tx.init))(stacked)
        return stacked, opt_stacked

    def average(self, stacked_params):
        """Mean over the worker axis -> one replicated param tree."""
        return jax.tree_util.tree_map(lambda a: jnp.mean(a, axis=0),
                                      stacked_params)

    def make_step(self):
        if self._step is not None:
            return self._step
        tx, loss_fn, axis, freq = self.tx, self.loss_fn, self.batch_axis, self.freq
        mesh = self.mesh
        wspec = P(axis)   # leading worker axis
        bspec = P(axis)

        def local_steps(params, opt_state, batch, rng, iteration):
            # strip the local leading worker axis (size 1 per shard)
            p = jax.tree_util.tree_map(lambda a: a[0], params)
            s = jax.tree_util.tree_map(lambda a: a[0], opt_state)
            my = jax.lax.axis_index(axis)
            loss, grads = jax.value_and_grad(loss_fn)(
                p, batch, jax.random.fold_in(rng, my))
            updates, s = tx.update(grads, s, p)
            p = optax.apply_updates(p, updates)
            do_avg = (iteration % freq) == (freq - 1)
            p = jax.lax.cond(
                do_avg,
                lambda q: jax.tree_util.tree_map(
                    lambda a: jax.lax.pmean(a, axis), q),
                lambda q: q, p)
            restack = lambda t: jax.tree_util.tree_map(lambda a: a[None], t)
            return restack(p), restack(s), jax.lax.pmean(loss, axis)

        shmapped = shard_map(
            local_steps, mesh=mesh,
            in_specs=(wspec, wspec, bspec, P(), P()),
            out_specs=(wspec, wspec, P()), check_vma=False)
        self._step = jax.jit(shmapped, donate_argnums=(0, 1))
        return self._step

    def fit_batch(self, params, opt_state, batch, rng, iteration):
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.TRAIN_DISPATCH)
        if _watchdog.ACTIVE is not None:
            _watchdog.ACTIVE.beat(f"param_averaging@{id(self):x}")
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_start()
        with _mon.span("sharded.dispatch"):
            out = self.make_step()(params, opt_state, batch,
                                   rng, jnp.asarray(iteration))
        _ps = _prof.ACTIVE
        if _ps is not None:
            _ps.step_end()
        return out
