"""Device mesh helpers (the TPU-native replacement for the reference's
device-affinity machinery in ParallelWrapper / Aeron transport config).

Axis-name conventions used across the framework:
  dp — data parallel        tp — tensor (model) parallel
  pp — pipeline parallel    sp — sequence/context parallel
  ep — expert parallel

Collectives ride ICI within a host's chips and DCN across hosts; XLA
chooses — we only annotate shardings (scaling-book recipe: pick a mesh,
annotate, let the compiler insert collectives).
"""
from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def shard_map(f, mesh=None, in_specs=None, out_specs=None, check_vma=None,
              **kw):
    """Version-compat `shard_map`: newer jax exposes `jax.shard_map`
    (with `check_vma=`), older releases only ship
    `jax.experimental.shard_map.shard_map` (whose equivalent kwarg is
    `check_rep=`). Every call site in this repo (and the tests) routes
    through here so a jax upgrade/downgrade is a one-line change."""
    native = getattr(jax, "shard_map", None)
    if native is not None:
        if check_vma is not None:
            kw["check_vma"] = check_vma
        return native(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kw)
    from jax.experimental.shard_map import shard_map as _shard_map
    if check_vma is not None:
        kw.setdefault("check_rep", check_vma)
    mapped = _shard_map(f, mesh=mesh, in_specs=in_specs,
                        out_specs=out_specs, **kw)

    # the experimental wrapper only takes positional args, but callers
    # that introspect `f` (models/bert.py mask guards) legitimately call
    # by keyword — rebind keywords to f's positional order
    import functools
    import inspect

    @functools.wraps(f)
    def call(*args, **kwargs):
        if kwargs:
            ba = inspect.signature(f).bind(*args, **kwargs)
            # fill defaulted gaps so a keyword after one (f(q, k, v,
            # causal=False, mask=None) called with mask=...) becomes
            # positional instead of silently staying in ba.kwargs and
            # being DROPPED — an arg-count mismatch with in_specs then
            # fails loudly inside shard_map, never silently
            ba.apply_defaults()
            if ba.kwargs:
                raise TypeError(
                    f"shard_map compat wrapper cannot pass keyword-only "
                    f"args {sorted(ba.kwargs)} positionally to the "
                    f"experimental shard_map; make them positional-or-"
                    f"keyword on {getattr(f, '__name__', f)!r}")
            return mapped(*ba.args)
        return mapped(*args)

    return call


class DeviceMesh:
    """Thin wrapper: build a named jax Mesh from the available devices.

    DeviceMesh(dp=2, tp=2, sp=2) → 8-device mesh with those axes.
    Any axis set to -1 absorbs the remaining devices.
    """

    def __init__(self, devices=None, **axes):
        devices = list(devices if devices is not None else jax.devices())
        if not axes:
            axes = {"dp": len(devices)}
        names = list(axes.keys())
        sizes = [int(v) for v in axes.values()]
        if -1 in sizes:
            known = int(np.prod([s for s in sizes if s != -1]))
            sizes[sizes.index(-1)] = len(devices) // known
        total = int(np.prod(sizes))
        if total > len(devices):
            raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} "
                             f"devices, have {len(devices)}")
        arr = np.array(devices[:total]).reshape(sizes)
        self.mesh = Mesh(arr, tuple(names))
        self.axis_names = tuple(names)
        self.shape = dict(zip(names, sizes))

    def __enter__(self):
        return self.mesh.__enter__()

    def __exit__(self, *a):
        return self.mesh.__exit__(*a)

    def sharding(self, *spec):
        """NamedSharding from axis names; None entries replicate."""
        return NamedSharding(self.mesh, P(*spec))

    def replicated(self):
        return NamedSharding(self.mesh, P())

    def shard_batch(self, tree, axis="dp"):
        """Place arrays with dim-0 sharded over `axis`."""
        sh = self.sharding(axis)
        return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)

    def replicate(self, tree):
        sh = self.replicated()
        return jax.tree_util.tree_map(lambda a: jax.device_put(a, sh), tree)

    @property
    def size(self):
        return int(np.prod(list(self.shape.values())))

    def axis_size(self, name):
        return self.shape[name]


def initialize_distributed(coordinator_address=None, num_processes=None,
                           process_id=None, **kw):
    """Multi-host bring-up (≡ SharedTrainingMaster's cluster bootstrap, but
    over jax.distributed instead of Aeron UDP). Gated: single-process
    environments (no coordinator configured anywhere) skip silently.

    Delegates to the HARDENED bootstrap in `parallel/multihost.py`:
    env-driven config (`DL4J_COORDINATOR` / `DL4J_NUM_PROCESSES` /
    `DL4J_PROCESS_ID`), connect retry/backoff under a deadline, CPU
    gloo collectives, and a post-init cross-process sanity barrier —
    failures raise typed `DistributedInitError`, never hang."""
    from deeplearning4j_tpu.parallel.multihost import initialize
    return initialize(coordinator_address, num_processes, process_id,
                      **kw)
