"""Cross-process coordination plane for multi-host training (≡ the
reference's SharedTrainingMaster control channel: worker liveness,
preemption drain, and lockstep agreement — but over jax's coordination
service KV store + barriers instead of Aeron UDP).

The design axiom: the TRAIN step is pure SPMD (collectives inside one
jitted program), and every control decision happens at a bounded-timeout
SYNC POINT every `sync_every` steps — piggybacked on the guardian's
verdict-flush cadence, so the control plane adds zero host syncs of its
own. At each sync point every process:

1. publishes a heartbeat (step number, wall time, preempt flag) to the
   KV store under a per-round key;
2. gathers every peer's heartbeat for the SAME round with a bounded
   timeout — a peer that never writes it was killed/wedged and surfaces
   as `PeerLostError` (plus a full forensics dump with the peer table)
   within `peer_timeout`, never an indefinite hang in a collective;
3. checks STEP AGREEMENT: all peers must report the same step — a
   desynced peer (one skipped a batch the others trained) is
   `PeerDesyncError`, because continuing would silently corrupt the
   replicated model;
4. reaches the PREEMPTION decision: the round's heartbeat set is
   write-once, so every process reads the same flags and reaches the
   same drain-or-continue decision at the same step.

The hot hook in `ShardedTrainer.fit_batch` is the usual one-pointer
compare (`if _coord.ACTIVE is not None: _coord.ACTIVE.on_step()`);
everything above happens only on the sync-point steps.

A `PeerMonitor` daemon thread (optional) additionally heartbeats a
wall-clock liveness key and watches the peers' — defense in depth for
the window BETWEEN sync points, and the data source for the `/health`
peer table and post-mortem autopsies of collective failures.

Single-process use (tests, degraded local runs) needs no jax
coordination service: `LocalKV` implements the same KV/barrier surface
in-process, so the whole control plane is unit-testable by running two
coordinators against one shared LocalKV from two threads.
"""
from __future__ import annotations

import json
import os
import signal
import threading
import time

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import cluster as _cluster
from deeplearning4j_tpu.monitoring import events as _events
from deeplearning4j_tpu.monitoring import stragglers as _stragglers
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.errors import (PeerDesyncError,
                                                  PeerLostError,
                                                  PreemptionSignal)

__all__ = ["ACTIVE", "LocalKV", "PeerCoordinator", "PeerMonitor",
           "PREEMPT", "REFORM", "clear_coordinator",
           "default_peer_timeout", "install_preemption_handler"]

#: THE switch the trainer hot hooks check (faults.py pattern). None →
#: coordination off (the permanent state in single-host runs).
ACTIVE = None

#: decision constants a driving runner consumes via `take_decision()`
PREEMPT = "preempt"
#: membership change agreed (join/leave announcements in the round's
#: heartbeat union): the driving runner re-forms the dp mesh at this
#: step boundary. PREEMPT takes precedence when both arise in one round.
REFORM = "reform"


def default_peer_timeout():
    try:
        return float(os.environ.get("DL4J_PEER_TIMEOUT", "60"))
    except ValueError:
        return 60.0


def default_sync_every():
    try:
        return int(os.environ.get("DL4J_SYNC_EVERY", "10"))
    except ValueError:
        return 10


class LocalKV:
    """In-process stand-in for the jax coordination-service client: the
    same `key_value_set` / `blocking_key_value_get` / `key_value_dir_get`
    / `wait_at_barrier` surface, backed by a dict + condition variable.

    Two uses: single-process runs get a working control plane without a
    coordinator, and the chaos tests drive two `PeerCoordinator`s from
    two threads against ONE shared LocalKV — every agreement/containment
    path exercised in tier-1 without subprocess spawn cost."""

    def __init__(self):
        self._data = {}
        self._cv = threading.Condition()
        self._barriers = {}        # barrier_id -> arrival count

    def key_value_set(self, key, value, allow_overwrite=False):
        with self._cv:
            if not allow_overwrite and key in self._data:
                raise RuntimeError(f"ALREADY_EXISTS: {key}")
            self._data[key] = value
            self._cv.notify_all()

    def blocking_key_value_get(self, key, timeout_in_ms):
        deadline = time.monotonic() + timeout_in_ms / 1000.0
        with self._cv:
            while key not in self._data:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=left):
                    if key in self._data:
                        break
                    raise TimeoutError(
                        f"DEADLINE_EXCEEDED: key {key!r} not set within "
                        f"{timeout_in_ms} ms")
            return self._data[key]

    def key_value_dir_get(self, key):
        with self._cv:
            return [(k, v) for k, v in sorted(self._data.items())
                    if k.startswith(key)]

    def key_value_delete(self, key):
        with self._cv:
            for k in [k for k in self._data if k.startswith(key)]:
                self._data.pop(k, None)

    def wait_at_barrier(self, barrier_id, timeout_in_ms, process_ids=None,
                        expected=1):
        deadline = time.monotonic() + timeout_in_ms / 1000.0
        with self._cv:
            n = self._barriers.get(barrier_id, 0) + 1
            self._barriers[barrier_id] = n
            self._cv.notify_all()
            while self._barriers[barrier_id] < expected:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(timeout=left):
                    if self._barriers[barrier_id] >= expected:
                        break
                    raise TimeoutError(
                        f"DEADLINE_EXCEEDED: barrier {barrier_id!r} "
                        f"({self._barriers[barrier_id]}/{expected}) "
                        f"within {timeout_in_ms} ms")


def _distributed_client():
    """The live jax coordination-service client, or None outside a
    distributed run. Internal-API access kept in ONE place."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:  # noqa: BLE001 — no client is a normal state
        return None


class PeerCoordinator:
    """The per-process control-plane endpoint. One per training loop.

    Parameters
    ----------
    sync_every: steps between sync points (heartbeat + agreement); align
        with the guardian's `check_every` so the flush and the heartbeat
        share one host-bound moment.
    peer_timeout: seconds a peer may lag a sync point / stay silent
        before it is declared lost (env `DL4J_PEER_TIMEOUT`).
    barrier_timeout: seconds for explicit named barriers (checkpoint
        fences); defaults to 2× peer_timeout.
    client / process_id / num_processes: default to the live
        jax.distributed state; tests pass a shared `LocalKV` + explicit
        ids to simulate a cluster in-process.
    dump_dir: where peer-loss forensic reports go (cwd default).
    """

    def __init__(self, sync_every=None, peer_timeout=None,
                 barrier_timeout=None, client=None, process_id=None,
                 num_processes=None, namespace="dl4j", dump_dir=None,
                 clock=time.monotonic):
        import jax
        self.sync_every = int(sync_every if sync_every is not None
                              else default_sync_every())
        if self.sync_every < 1:
            raise ValueError("sync_every must be >= 1")
        self.peer_timeout = float(peer_timeout if peer_timeout is not None
                                  else default_peer_timeout())
        self.barrier_timeout = float(
            barrier_timeout if barrier_timeout is not None
            else 2.0 * self.peer_timeout)
        self._client = client if client is not None \
            else (_distributed_client() or LocalKV())
        self.process_id = int(process_id if process_id is not None
                              else jax.process_index())
        self.num_processes = int(num_processes if num_processes is not None
                                 else jax.process_count())
        #: the ACTIVE roster — every gather/agreement/autopsy loop walks
        #: this, never `range(num_processes)`. Fixed-membership runs
        #: keep the full range; an attached `ElasticMembership` rewrites
        #: it through `reform()` at agreed boundaries.
        self.members = list(range(self.num_processes))
        self.membership = None     # ElasticMembership attaches itself
        self._pending_reform = None  # (joins, leaves) behind a REFORM
        self.ns = namespace
        self.dump_dir = dump_dir
        self._clock = clock

        self.step = 0              # trainer steps observed (on_step calls)
        self.rounds = 0            # sync points completed
        #: when bound to a specific trainer, on_step calls from OTHER
        #: trainers are ignored — a host-local auxiliary fit (probe,
        #: validation) must not desync the step-agreement check (the
        #: same confusion class PR 5 solved with per-instance
        #: watchdog heartbeats)
        self._bound = None
        #: a driving runner consumes take_decision() after each batch —
        #: without one, a preempt decision raises PreemptionSignal
        #: directly from the sync point (nothing else could act on it)
        self.driver_attached = False
        self._decision = None
        self._preempt_requested = False
        self._preempt_reason = None
        self.preempted = False     # a drain decision was reached
        self._peers = {}           # last gathered peer table
        self._lost = {}            # pid -> info for peers declared lost
        #: pid -> (last published beat value, LOCAL monotonic time we
        #: first observed it) — staleness always compares the local
        #: observation clock, never a peer's wall clock (cross-host
        #: clock skew would otherwise stretch/shrink the peer timeout
        #: and corrupt the post-failure proof-of-life check)
        self._beat_obs = {}
        self.last_report_path = None
        self.on_sync = None        # callback(self) after each sync point
        self._monitor = None
        self._prev_active = None
        #: extra per-host stats riding the heartbeat + metrics snapshot
        #: (a driving runner drops e.g. exchange_bytes in here at sync
        #: cadence; the peer table and the cluster plane surface them)
        self.stats_extra = {}
        self._last_sync = None     # (step, clock) of the previous sync

    # -- install / clear (faults.py pattern) -----------------------------
    def install(self):
        global ACTIVE
        if ACTIVE is not self:
            self._prev_active = ACTIVE
            ACTIVE = self
        return self

    def uninstall(self):
        global ACTIVE
        if ACTIVE is self:
            ACTIVE = self._prev_active
            self._prev_active = None
        return self

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        if self._monitor is not None:
            self._monitor.stop()
        return False

    # -- KV helpers ------------------------------------------------------
    def _key(self, suffix):
        return f"{self.ns}/{suffix}"

    def publish(self, key, value, overwrite=False):
        self._client.key_value_set(self._key(key), value,
                                   allow_overwrite=overwrite)

    def fetch(self, key, timeout=None):
        """Blocking KV read with a bounded timeout (seconds)."""
        t = self.peer_timeout if timeout is None else float(timeout)
        return self._client.blocking_key_value_get(
            self._key(key), int(t * 1000))

    def fetch_dir(self, key):
        pfx = self._key(key)
        return [(k[len(pfx):], v)
                for k, v in self._client.key_value_dir_get(pfx)]

    def publish_json(self, key, doc):
        """Overwrite-publish one JSON document under `key` — the
        directory-registry primitive: each process re-publishes its own
        `<prefix>/<pid>` entry and `fetch_json_dir` merges the cross-host
        view (the fleet replica registry rides this)."""
        self.publish(key, json.dumps(doc), overwrite=True)

    def fetch_json_dir(self, prefix):
        """Read every JSON document under `prefix` → {suffix: doc},
        skipping entries that fail to parse (a publisher mid-write or a
        foreign key must not poison the merged registry view)."""
        out = {}
        for suffix, raw in self.fetch_dir(prefix):
            try:
                out[suffix] = json.loads(raw)
            except (TypeError, ValueError):
                continue
        return out

    def barrier(self, name, timeout=None):
        """Named cross-process fence with a bounded timeout → a timeout
        is a LOST/WEDGED peer (dump + `PeerLostError`), never a silent
        gRPC hang. The `comm.barrier` fault site fires first so chaos
        plans can break fences on schedule."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.COMM_BARRIER)
        t = self.barrier_timeout if timeout is None else float(timeout)
        kw = {}
        if isinstance(self._client, LocalKV):
            kw["expected"] = len(self.members)
        elif set(self.members) != set(range(self.num_processes)):
            # elastic roster: scope the fence to the ACTIVE members so a
            # departed host can never be waited on (the service default
            # would expect every launched process)
            kw["process_ids"] = list(self.members)
        try:
            self._client.wait_at_barrier(self._key(f"barrier/{name}"),
                                         int(t * 1000), **kw)
        except Exception as e:  # noqa: BLE001 — timeout/transport alike
            if _mon.enabled():
                _mon.get_registry().counter(
                    _mon.DIST_BARRIER_TIMEOUTS,
                    help="cross-process barriers that timed out").inc()
            raise self._peer_lost_error(
                f"barrier {name!r} not reached by all "
                f"{len(self.members)} members within {t:.1f} s",
                cause=e) from e

    # -- preemption ------------------------------------------------------
    def request_preemption(self, reason="signal"):
        """Mark THIS process as preempted; the flag rides the next
        heartbeat, and every process (including this one) reaches the
        same drain decision at the same sync point. Signal-handler safe:
        one bool store."""
        self._preempt_requested = True
        self._preempt_reason = reason

    @property
    def preempt_requested(self):
        return self._preempt_requested

    def take_decision(self):
        """Return-and-clear the pending control decision (PREEMPT /
        None). The driving runner consumes this after each batch —
        mirror of TrainingGuardian.take_action()."""
        d, self._decision = self._decision, None
        return d

    def take_reform(self):
        """Return-and-clear the (joins, leaves) delta behind the last
        REFORM decision — the runner consumes this right after
        `take_decision()` returned REFORM."""
        r, self._pending_reform = self._pending_reform, None
        return r

    def reform(self, members):
        """Adopt a NEW member roster at an agreed boundary: every
        subsequent gather / barrier / autopsy walks the new list, and
        stale per-peer bookkeeping for departed pids is dropped so a
        replaced host re-joining under the same pid starts clean."""
        members = sorted(int(p) for p in members)
        if not members:
            raise ValueError("reform: empty member roster")
        self.members = members
        keep = set(members)
        self._lost = {p: v for p, v in self._lost.items() if p in keep}
        self._peers = {p: v for p, v in self._peers.items() if p in keep}
        self._beat_obs = {p: v for p, v in self._beat_obs.items()
                          if p in keep}
        if self._monitor is not None:
            self._monitor._tripped &= keep
        if _mon.enabled():
            _mon.get_registry().gauge(
                _mon.DIST_PEERS,
                help="peer processes seen at the last sync point") \
                .set(len(members))
        return self

    def bind(self, trainer):
        """Scope step counting to `trainer`: while bound, ONLY calls
        whose `source` is that trainer advance the lockstep step
        counter — source-less calls are dropped too (any extra count
        desyncs step agreement across hosts). None unbinds (every call
        counts, the default)."""
        self._bound = trainer
        return self

    # -- the hot hook ----------------------------------------------------
    def on_step(self, source=None):
        """Called once per trainer step (the `fit_batch` hook). Cheap
        off the sync cadence: an int increment and a modulo. `source`
        is the calling trainer; when this coordinator is bound to a
        specific one, every other source (including None) is ignored."""
        if self._bound is not None and source is not self._bound:
            return
        self.step += 1
        if self._lost:
            # the monitor already counted + dumped when it tripped
            raise self._peer_lost_error(
                "peer(s) declared lost by the monitor thread",
                write_report=False, count=False)
        if self.step % self.sync_every:
            return
        self._sync_point()

    def _sync_point(self):
        rnd = self.rounds
        self.rounds += 1
        if _faults.ACTIVE is not None:
            # host.preempt: a PreemptionSignal injected here simulates
            # SIGTERM delivery at an exact step — it requests the drain
            # instead of propagating. Any other injected exception (or
            # a factory that kills the process outright) propagates the
            # chaos as designed.
            try:
                _faults.ACTIVE.fire(_faults.HOST_PREEMPT)
            except PreemptionSignal as e:
                self.request_preemption(f"injected: {e}")
        now = self._clock()
        rate = None
        if self._last_sync is not None and now > self._last_sync[1]:
            rate = round((self.step - self._last_sync[0])
                         / (now - self._last_sync[1]), 3)
        self._last_sync = (self.step, now)
        hb = {"step": self.step, "t": time.time(),
              "preempt": bool(self._preempt_requested),
              "reason": self._preempt_reason,
              "steps_per_s": rate}
        if self.membership is not None:
            # this process's VIEW of pending join/leave announcements —
            # the agreed delta is the UNION over the round's write-once
            # heartbeat set, so every member reaches the same REFORM
            # decision even when announcements land mid-round
            mj, ml = self.membership.pending()
            if mj:
                hb["mjoin"] = mj
            if ml:
                hb["mleave"] = ml
        if self.stats_extra:
            hb.update(self.stats_extra)
        self.publish(f"hb/{rnd}/{self.process_id}", json.dumps(hb))
        peers = {self.process_id: hb}
        for pid in self.members:
            if pid == self.process_id:
                continue
            try:
                peers[pid] = json.loads(
                    self.fetch(f"hb/{rnd}/{pid}"))
            except Exception as e:  # noqa: BLE001 — silence IS the signal
                self._lost[pid] = {"round": rnd, "error": str(e)}
                raise self._peer_lost_error(
                    f"process {pid} never published its round-{rnd} "
                    f"heartbeat within {self.peer_timeout:.1f} s "
                    f"(step {self.step})", cause=e) from e
        self._peers = peers
        steps = {pid: info.get("step") for pid, info in peers.items()}
        if len(set(steps.values())) > 1:
            raise self._desync_error(steps)
        if any(info.get("preempt") for info in peers.values()):
            self.preempted = True
            self._decision = PREEMPT
            if _mon.enabled():
                _mon.get_registry().counter(
                    _mon.DIST_PREEMPTIONS,
                    help="coordinated preemption drains agreed").inc()
        elif self.membership is not None:
            joins, leaves = set(), set()
            for info in peers.values():
                joins.update(int(p) for p in info.get("mjoin") or ())
                leaves.update(int(p) for p in info.get("mleave") or ())
            joins -= set(self.members)
            leaves &= set(self.members)
            if joins or leaves:
                if self.driver_attached:
                    self._decision = REFORM
                    self._pending_reform = (sorted(joins), sorted(leaves))
                    if _mon.enabled():
                        _mon.get_registry().counter(
                            _mon.DIST_REFORMS_AGREED,
                            help="membership changes agreed at sync "
                                 "points").inc()
                # undriven: nothing can execute a mesh re-form — the
                # announcements stay pending and harmless
        if _mon.enabled():
            _mon.get_registry().gauge(
                _mon.DIST_PEERS,
                help="peer processes seen at the last sync point") \
                .set(len(peers))
        # reap the round-before-last's heartbeat keys (everyone is
        # provably past them — this round's gather completed) so a
        # long run doesn't grow the coordination service's KV store
        # without bound; best effort, every process deletes its OWN key
        if rnd >= 2:
            try:
                self._client.key_value_delete(
                    self._key(f"hb/{rnd - 2}/{self.process_id}"))
            except Exception:  # noqa: BLE001
                pass
        if _mon.enabled():
            # cluster metrics plane: ONE overwritten `metrics/<pid>` KV
            # key per process at this (guardian-flush) cadence — no new
            # collectives, no new syncs, bounded keys by construction.
            # Best-effort: a full/failed KV write must not fail a step.
            try:
                extra = {"steps_per_s": rate, **self.stats_extra}
                _cluster.publish(self, extra=extra)
            except Exception:  # noqa: BLE001
                pass
            # per-host step timeline (straggler plane): ONE overwritten
            # `steps/<pid>` key per process at the same cadence — same
            # zero-cost contract, same best-effort posture.
            try:
                _stragglers.publish(self, extra={"steps_per_s": rate})
            except Exception:  # noqa: BLE001
                pass
        if self.on_sync is not None:
            self.on_sync(self)
        if self._decision == PREEMPT and not self.driver_attached:
            # nothing will consume the decision — unwind the fit loop
            # directly (the caller has no checkpointer to drain into)
            self._decision = None
            raise PreemptionSignal(
                f"preemption agreed at step {self.step} "
                f"({self._agreed_reason()})", step=self.step)

    def _agreed_reason(self):
        for pid, info in sorted(self._peers.items()):
            if info.get("preempt"):
                return f"requested by process {pid}: {info.get('reason')}"
        return self._preempt_reason or "requested"

    # -- containment -----------------------------------------------------
    def _peer_lost_error(self, message, cause=None, write_report=True,
                         count=True):
        """count=False when re-surfacing a loss the monitor already
        counted — one lost peer must land on `dl4j.dist.peer_lost`
        exactly once regardless of which path detected it."""
        if count and _mon.enabled():
            _mon.get_registry().counter(
                _mon.DIST_PEER_LOST,
                help="peers declared lost/wedged/desynced").inc()
        if _mon.enabled():
            # before the report, so its journal tail shows this loss
            _events.emit("parallel", _events.PEER_LOST,
                         attrs={"message": message},
                         correlation_id="peers-%d" % self.process_id)
        path = None
        if write_report:
            path = self._write_report(["PEER LOST: " + message]
                                      + ([f"cause: {cause}"] if cause
                                         else []))
        return PeerLostError(message, peers=self.peer_table(),
                             report_path=path or self.last_report_path)

    def desync_error(self, msg):
        """Build a `PeerDesyncError` the standard way — counted on
        `dl4j.dist.peer_lost` and with a forensics report — so every
        desync class (step disagreement here, verdict-window mismatch in
        CoordinatedGuardian) surfaces identically."""
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.DIST_PEER_LOST,
                help="peers declared lost/wedged/desynced").inc()
            _events.emit("parallel", _events.PEER_DESYNC,
                         attrs={"message": msg},
                         correlation_id="peers-%d" % self.process_id)
        path = self._write_report(["PEER DESYNC: " + msg])
        return PeerDesyncError(msg, peers=self.peer_table(),
                               report_path=path)

    def _desync_error(self, steps):
        return self.desync_error(
            f"step disagreement at sync round {self.rounds - 1}: "
            + ", ".join(f"p{pid}={s}"
                        for pid, s in sorted(steps.items())))

    def _write_report(self, headline):
        from deeplearning4j_tpu.resilience.watchdog import write_debug_report
        try:
            # count_dump=False: peer reports land on dl4j.dist.peer_lost,
            # not dl4j.watchdog.dumps — a stall-dump alert must not fire
            # for a peer loss on a healthy host
            self.last_report_path = write_debug_report(
                headline, dump_dir=self.dump_dir,
                prefix="dl4j-peer-report", count_dump=False)
        except Exception:  # noqa: BLE001 — the report must never mask
            self.last_report_path = None
        return self.last_report_path

    def autopsy(self, exc):
        """A collective/dispatch failure just surfaced: decide whether a
        dead peer caused it. Polls the monitor liveness keys until
        either every peer shows a fresh beat (→ re-raise the original
        error: the peers are fine, the failure is real) or a peer stays
        silent past `peer_timeout` (→ `PeerLostError` from the original
        error). Bounded by `peer_timeout` either way. With NO liveness
        keys at all (no `PeerMonitor` running anywhere) there is no
        evidence to adjudicate on — the original error re-raises
        immediately rather than being blamed on peers that may be
        perfectly healthy."""
        try:
            empty = not self.fetch_dir("alive/")
        except Exception as kv_err:  # noqa: BLE001 — service itself gone
            # the coordination service rides the coordinator process:
            # its death IS a peer loss, surfaced typed like any other
            raise self._peer_lost_error(
                f"coordination service unreachable while adjudicating a "
                f"collective failure — the coordinator process likely "
                f"died ({kv_err}); original error: {exc}",
                cause=exc) from exc
        if empty:
            raise exc
        started = time.monotonic()
        deadline = started + self.peer_timeout + 1.0
        while True:
            try:
                # one guarded pass: refresh local beat observations and
                # compute staleness from them
                stale = self._stale_peers()
            except Exception as kv_err:  # noqa: BLE001
                raise self._peer_lost_error(
                    f"coordination service unreachable mid-autopsy — "
                    f"the coordinator process likely died ({kv_err}); "
                    f"original error: {exc}", cause=exc) from exc
            # a beat OBSERVED after the failure is proof of life — once
            # every peer has produced one, the failure was not a peer
            # death (observation times are local-monotonic: clock skew
            # on the peers cannot fake or hide freshness)
            if all(self._beat_obs.get(pid, (None, -1.0))[1] >= started
                   for pid in self.members
                   if pid != self.process_id):
                raise exc
            if stale:
                # silence crossed peer_timeout: declared lost the moment
                # the age threshold trips, not a further timeout later
                err = self._peer_lost_error(
                    f"collective failed and peer(s) "
                    f"{sorted(stale)} stopped heartbeating: {exc}",
                    cause=exc)
                raise err from exc
            if time.monotonic() >= deadline:
                raise exc          # inconclusive: the real error wins
            time.sleep(min(0.2, self.peer_timeout / 10))

    def alive_info(self):
        """{pid: parsed liveness record} from the monitor 'alive/' keys
        — THE one parse of those keys (the monitor, the staleness
        checks, and the peer table all read through here). Also folds
        each NEW beat value into `_beat_obs` with the LOCAL monotonic
        observation time, which is what every staleness decision
        compares against (peer wall clocks are display-only)."""
        seen = {}
        now = time.monotonic()
        for k, v in self.fetch_dir("alive/"):
            try:
                pid, info = int(k), json.loads(v)
            except (ValueError, TypeError):
                continue
            seen[pid] = info
            prev = self._beat_obs.get(pid)
            if prev is None or prev[0] != info.get("t"):
                self._beat_obs[pid] = (info.get("t"), now)
        return seen

    def _stale_peers(self, grace_start=None):
        """Peers whose monitor liveness beat is older than peer_timeout
        (or missing entirely), measured on THIS process's monotonic
        clock from when each beat was first observed — immune to
        cross-host clock skew. `grace_start` (monotonic): a peer with
        NO key yet is only stale once peer_timeout has elapsed since
        that time (its monitor may not have beaten yet); None treats
        absence as staleness — correct for autopsies at death time.
        Requires monitors running on the peers."""
        seen = self.alive_info()
        now = time.monotonic()
        stale = set()
        for pid in self.members:
            if pid == self.process_id:
                continue
            if pid not in seen:
                if grace_start is None \
                        or now - grace_start > self.peer_timeout:
                    stale.add(pid)
                continue
            obs = self._beat_obs.get(pid)
            if obs is not None and now - obs[1] > self.peer_timeout:
                stale.add(pid)
        return stale

    # -- the /health + report surface ------------------------------------
    def peer_table(self):
        """pid -> last-known info (heartbeat step/time/preempt flag,
        monitor beat age, lost verdicts) — the `GET /health` peer table
        and the forensics-report section."""
        now = time.time()
        table = {}
        for pid, info in self._peers.items():
            entry = dict(info)
            if "t" in entry:
                entry["hb_age_s"] = round(now - entry.pop("t"), 3)
            table[pid] = entry
        try:
            seen = self.alive_info()
            mono = time.monotonic()
            for pid, info in seen.items():
                obs = self._beat_obs.get(pid)
                if obs is not None:
                    table.setdefault(pid, {})["alive_age_s"] = \
                        round(mono - obs[1], 3)
                table.setdefault(pid, {}).setdefault(
                    "step", info.get("step"))
        except Exception:  # noqa: BLE001 — table is best-effort
            pass
        for pid, info in self._lost.items():
            table.setdefault(pid, {})["lost"] = info
        # straggler columns: per-host attributed step time + the culprit
        # verdict on the slow host's row (best-effort, read-only KV)
        if _mon.enabled() and self.num_processes > 1:
            try:
                _stragglers.annotate_peer_table(self, table)
            except Exception:  # noqa: BLE001
                pass
        return table

    def snapshot(self):
        snap = {
            "process_id": self.process_id,
            "num_processes": self.num_processes,
            "members": list(self.members),
            "step": self.step,
            "rounds": self.rounds,
            "sync_every": self.sync_every,
            "peer_timeout_s": self.peer_timeout,
            "preempt_requested": self._preempt_requested,
            "preempted": self.preempted,
            "lost": {str(k): v for k, v in self._lost.items()},
            "peers": {str(k): v for k, v in self.peer_table().items()},
            "last_report": self.last_report_path,
        }
        # accumulation / bucketed-exchange knobs of the bound trainer
        # (GET /health "distributed" section): how many microbatches
        # each optimizer step accumulates and how the exchange is split
        t = self._bound
        if t is not None:
            snap["accum_microbatches"] = int(
                getattr(t, "accumulation", 1) or 1)
            plan = getattr(t, "bucket_plan", None)
            if plan is not None:
                snap["exchange_buckets"] = plan.num_buckets
                snap["bucket_bytes"] = list(plan.bucket_bytes)
        # cluster metrics plane (process 0 is the serving end): per-host
        # snapshot ages / steps/s / exchange bytes for GET /health —
        # best-effort and bounded (health must always answer fast)
        if self.process_id == 0 and self.num_processes > 1 \
                and _mon.enabled():
            cm = _cluster.health_meta(self)
            if cm is not None:
                snap["cluster"] = cm
            try:
                sg = _stragglers.attribution(self)
            except Exception:  # noqa: BLE001
                sg = None
            if sg is not None:
                snap["stragglers"] = sg
        return snap

    # -- monitor thread --------------------------------------------------
    def start_monitor(self, poll_interval=None, abort=None):
        if self._monitor is None:
            self._monitor = PeerMonitor(self, poll_interval=poll_interval,
                                        abort=abort).start()
        return self._monitor

    def stop_monitor(self):
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor = None


class PeerMonitor:
    """Daemon thread: writes this process's wall-clock liveness key
    every `poll_interval` (overwrite allowed) and watches the peers'.
    A peer silent past `peer_timeout` trips ONCE: forensics dump with
    the peer table, `dl4j.dist.peer_lost`, the coordinator's `_lost`
    table (the next `on_step` raises `PeerLostError` — bounded even
    between sync points), and the optional `abort` callable (e.g.
    `lambda: os._exit(134)` when the main thread may be wedged inside a
    native collective that no Python-level exception can reach)."""

    def __init__(self, coordinator, poll_interval=None, abort=None):
        self.c = coordinator
        self.poll_interval = (min(1.0, self.c.peer_timeout / 4.0)
                              if poll_interval is None
                              else float(poll_interval))
        self.abort = abort
        self._stop = threading.Event()
        self._thread = None
        self._tripped = set()
        self._started = None

    def start(self):
        if self._thread is None:
            self._started = time.monotonic()
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="dl4j-peer-monitor")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        return self

    def check_now(self):
        """One liveness beat + peer scan (exposed for tests). A peer
        that has never written a liveness key is only stale once the
        grace window (one peer_timeout from monitor start) has elapsed —
        its monitor may simply not have beaten yet."""
        c = self.c
        if self._started is None:
            self._started = time.monotonic()
        c.publish(f"alive/{c.process_id}",
                  json.dumps({"step": c.step, "t": time.time()}),
                  overwrite=True)
        stale = c._stale_peers(grace_start=self._started) - self._tripped
        for pid in stale:
            self._tripped.add(pid)
            c._lost[pid] = {"monitor": True, "t": time.time()}
            c._peer_lost_error(
                f"process {pid} silent for > {c.peer_timeout:.1f} s "
                f"(monitor thread)", write_report=True)
            if self.abort is not None:
                try:
                    self.abort()
                except Exception:  # noqa: BLE001
                    pass
        return stale

    def _run(self):
        # grace: peers need one poll to write their first liveness key;
        # don't scan until this process has beaten at least once
        while not self._stop.wait(self.poll_interval):
            try:
                self.check_now()
            except Exception:  # noqa: BLE001 — monitor must stay alive
                pass


def install_preemption_handler(coordinator, signals=(signal.SIGTERM,)):
    """SIGTERM → `coordinator.request_preemption()`: the in-flight step
    drains, the next sync point reaches the coordinated drain decision,
    and the runner writes the final verified checkpoint before a clean
    exit. Chains any existing handler. Main thread only (signal API);
    returns the previous handlers for restoration."""
    prev = {}

    def make(old):
        def handler(signum, frame):
            coordinator.request_preemption(
                f"signal {signal.Signals(signum).name}")
            if callable(old):
                old(signum, frame)
        return handler

    for s in signals:
        prev[s] = signal.getsignal(s)
        signal.signal(s, make(prev[s]))
    return prev


def clear_coordinator():
    """Force-reset the global switch — test teardown only."""
    global ACTIVE
    ACTIVE = None
