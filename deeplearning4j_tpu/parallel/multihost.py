"""Preemption-tolerant multi-host training (≡ the reference's
SharedTrainingMaster + EncodedGradientsAccumulator stack, PAPER.md §1:
multi-worker training that survives worker churn by shipping
threshold-encoded gradients and re-syncing stragglers — rebuilt over
jax.distributed, gRPC/DCN, and the PR 2/5 resilience layers).

Four pieces:

1. **Hardened bootstrap** — `initialize()`: env-driven config
   (`DL4J_COORDINATOR`, `DL4J_NUM_PROCESSES`, `DL4J_PROCESS_ID`),
   retry/backoff with a connect deadline around
   `jax.distributed.initialize` (a coordinator that is not up YET is
   retried, not crashed on), CPU gloo collectives enabled automatically
   (without them a cross-process CPU mesh fails with "Multiprocess
   computations aren't implemented" — the root cause of the seed's
   multihost test failure), and a post-init cross-process sanity
   barrier + device-count check with its own timeout. Every failure is
   a typed `DistributedInitError`; nothing here can hang silently.

2. **dp-over-DCN trainer** — `MultiHostTrainer`: `ShardedTrainer`
   composed across processes with in-step gradient accumulation and
   `compression.threshold_encoding` INSIDE the jitted step: the step
   scans G microbatches of a staged super-batch accumulating gradients
   on device (one dispatch + one update per OPTIMIZER step regardless
   of G), then each worker quantizes its accumulated local gradient to
   {−t, 0, +t} per byte-balanced BUCKET (`parallel/buckets.py`)
   against that bucket's own residual, and only the sparse quantized
   payloads ride the cross-host all-reduce — N independent collectives
   issued so bucket k exchanges while bucket k+1 encodes (the
   EncodedGradientsAccumulator exchange, chunked + overlapped). The
   per-bucket residual/threshold state is per-worker-stacked,
   checkpointed with the optimizer state, and restored bit-exactly on
   resume. Optional ZeRO-1 (`parallel/zero.py`) shards the base
   optimizer state over dp.

3. **Coordinated robustness** — `CoordinatedGuardian` reduces the
   device health verdicts across processes at every flush (elementwise
   AND of ok, max of grad-norm), so every host climbs the SAME
   escalation ladder rung on the SAME step; `MultiHostRunner` drives
   coordinated checkpoints (all processes gather-to-replicated and
   snapshot, process 0 writes, peers verify the integrity manifest
   against their own snapshot — a split brain fails the checksum
   compare), rollback lands all hosts on the same checksum-verified
   generation (process 0 picks it, publishes the step, peers restore
   and verify exactly that one), and the SIGTERM handler drains the
   in-flight step into a final verified checkpoint before a clean exit
   (`resume_or_init` then restarts bit-identically).

4. **Failure containment** — the sync-point heartbeats, step-agreement
   checks, bounded barrier/KV timeouts, `PeerLostError` + forensics
   dumps, and the `comm.allreduce` / `comm.barrier` / `host.preempt`
   fault-injection sites live in `parallel/coordination.py`; this
   module wires them through the trainer (collective failures get a
   peer autopsy before propagating).
"""
from __future__ import annotations

import functools
import os

import numpy as np

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import events as _events
from deeplearning4j_tpu.parallel import buckets as _buckets
from deeplearning4j_tpu.parallel import compression as _compression
from deeplearning4j_tpu.parallel import coordination as _coord
from deeplearning4j_tpu.parallel import zero as _zero
from deeplearning4j_tpu.parallel.mesh import shard_map
from deeplearning4j_tpu.parallel.sharded_trainer import (ShardedTrainer,
                                                         accumulate_grads)
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience import guardian as _guardian
from deeplearning4j_tpu.resilience.errors import (CheckpointIntegrityError,
                                                  DistributedInitError,
                                                  MembershipChangeError,
                                                  PeerDesyncError,
                                                  PeerLostError,
                                                  PreemptionSignal)
from deeplearning4j_tpu.resilience.policy import RetryPolicy

__all__ = [
    "CoordinatedGuardian", "ElasticMembership", "MultiHostRunner",
    "MultiHostTrainer",
    "global_batch", "initialize", "initialized", "process_id",
    "PeerCoordinator", "PeerMonitor", "LocalKV",
    "install_preemption_handler",
]

def _debug(*parts):
    """Bring-up tracing for multi-process runs (`DL4J_MH_DEBUG=1`):
    plain stderr prints with the process id, because the usual failure
    mode under debug here is a process that dies before flushing
    anything structured."""
    if os.environ.get("DL4J_MH_DEBUG"):
        import sys
        print(f"[mh p{jax.process_index() if initialized() else '?'}]",
              *parts, file=sys.stderr, flush=True)


# re-export the coordination plane under the one module name users (and
# the docs) reach for
PeerCoordinator = _coord.PeerCoordinator
PeerMonitor = _coord.PeerMonitor
LocalKV = _coord.LocalKV
install_preemption_handler = _coord.install_preemption_handler
from deeplearning4j_tpu.parallel.membership import (  # noqa: E402
    ElasticMembership)


def __getattr__(name):
    # `multihost.ACTIVE` always reflects the LIVE coordination switch
    # (rebinding a module-level alias at import time would freeze it)
    if name == "ACTIVE":
        return _coord.ACTIVE
    raise AttributeError(name)


# =========================== bootstrap ==================================
def _env(*names, default=None):
    for n in names:
        v = os.environ.get(n)
        if v:
            return v
    return default


def initialized():
    """True once `jax.distributed` is connected (by us or the caller)."""
    return _coord._distributed_client() is not None


def process_id():
    """This process's id in the cluster (0 in single-process runs)."""
    return jax.process_index() if initialized() else 0


def _enable_cpu_collectives():
    """Cross-process collectives on the CPU backend need the gloo
    implementation — the default ('none') makes ANY multi-process CPU
    computation fail with 'Multiprocess computations aren't implemented
    on the CPU backend' (the seed's two-process test failure). Must run
    before the backend exists; harmless for TPU/GPU platforms (the flag
    only affects `make_cpu_client`)."""
    from jax._src import xla_bridge
    if xla_bridge.backends_are_initialized():
        return False               # too late to change the client
    try:
        # the flag object, not jax.config attribute access — this jax
        # version only registers the latter lazily
        current = xla_bridge.CPU_COLLECTIVES_IMPLEMENTATION.value
        if current in (None, "none"):
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
            return True        # WE set it — failure paths may revert it
        return False           # user-configured (mpi/gloo): not ours to
        #                        touch, and never ours to revert
    except Exception:  # noqa: BLE001 — older/newer jax without the flag
        return False


def initialize(coordinator_address=None, num_processes=None,
               process_id=None, connect_deadline=None,
               barrier_timeout=None, retry_policy=None):
    """Hardened multi-host bring-up. Config falls back to env vars
    (`DL4J_COORDINATOR` / `DL4J_NUM_PROCESSES` / `DL4J_PROCESS_ID`,
    then the `JAX_*` equivalents); with no coordinator configured at
    all this is a silent single-process no-op (returns False) so the
    same entrypoint runs everywhere.

    Hardening over bare `jax.distributed.initialize`:
    - CPU gloo collectives enabled before the backend exists;
    - connect retry/backoff via `resilience.RetryPolicy` under a total
      `connect_deadline` (env `DL4J_CONNECT_DEADLINE`, default 120 s) —
      a coordinator that has not come up yet is retried, a partial
      connect is torn down (`jax.distributed.shutdown`) between
      attempts;
    - a post-init cross-process sanity barrier + device-count agreement
      check, each with its own timeout (env `DL4J_BARRIER_TIMEOUT`,
      default 60 s);
    - every failure mode raises typed `DistributedInitError` — never a
      silent gRPC hang, never a stack-specific transport error the
      supervisor can't classify.

    Also registers the process id with `resilience.faults` so
    `FaultPlan` seed derivation is process-aware."""
    coordinator_address = coordinator_address or _env(
        "DL4J_COORDINATOR", "JAX_COORDINATOR_ADDRESS")
    if coordinator_address is None:
        return False
    if initialized():
        # caller (or a launcher) initialized jax.distributed itself —
        # still honor the documented side effect so FaultPlan seed
        # derivation stays process-aware
        if _faults.PROCESS_ID is None:
            try:
                _faults.PROCESS_ID = jax.process_index()
            except Exception:  # noqa: BLE001
                pass
        return True
    # None stays None when neither arg nor env provides a value:
    # jax.distributed auto-detects cluster shape on TPU pods / managed
    # schedulers, and forcing 1/0 here would make every host join as
    # process 0 of 1
    if num_processes is None:
        v = _env("DL4J_NUM_PROCESSES", "JAX_NUM_PROCESSES")
        num_processes = int(v) if v is not None else None
    else:
        num_processes = int(num_processes)
    if process_id is None:
        v = _env("DL4J_PROCESS_ID", "JAX_PROCESS_ID")
        process_id = int(v) if v is not None else None
    else:
        process_id = int(process_id)
    try:
        connect_deadline = float(
            connect_deadline if connect_deadline is not None
            else os.environ.get("DL4J_CONNECT_DEADLINE", "120"))
    except ValueError:
        connect_deadline = 120.0
    try:
        barrier_timeout = float(
            barrier_timeout if barrier_timeout is not None
            else os.environ.get("DL4J_BARRIER_TIMEOUT", "60"))
    except ValueError:
        barrier_timeout = 60.0
    gloo_set = _enable_cpu_collectives()
    policy = retry_policy or RetryPolicy(
        max_attempts=8, initial_backoff=0.5, max_backoff=5.0,
        deadline=connect_deadline,
        seed=process_id if process_id is not None else 0)
    # per-attempt timeout: small enough that the RetryPolicy budget
    # actually drives the schedule, bounded below so one attempt can
    # still succeed on a slow link
    attempt_timeout = max(5, int(connect_deadline / policy.max_attempts))

    def attempt():
        try:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes, process_id=process_id,
                initialization_timeout=attempt_timeout)
        except Exception:
            try:       # tear down a half-connected client before retry
                jax.distributed.shutdown()
            except Exception:  # noqa: BLE001
                pass
            raise

    try:
        policy.call(attempt, label="distributed.init")
    except Exception as e:
        if gloo_set:
            # leave the process able to run single-host: a gloo CPU
            # client with no distributed connection refuses to build
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "none")
            except Exception:  # noqa: BLE001
                pass
        raise DistributedInitError(
            f"process {process_id}/{num_processes}: could not join "
            f"coordinator {coordinator_address} within "
            f"{connect_deadline:.0f} s: {e}") from e

    client = _coord._distributed_client()

    def post_init_failure(err):
        """A failed bring-up must not leave a half-formed cluster
        behind: a supervisor retry would then hit the
        already-initialized fast path, 'succeed', and hang in the
        first collective — the silent-hang class this bootstrap
        exists to eliminate. Tear the connection down (and the gloo
        flag, so single-host work still runs) before raising."""
        try:
            jax.distributed.shutdown()
        except Exception:  # noqa: BLE001
            pass
        if gloo_set:
            try:
                jax.config.update("jax_cpu_collectives_implementation",
                                  "none")
            except Exception:  # noqa: BLE001
                pass
        return err

    # resolve the ACTUAL cluster shape (auto-detected values included)
    # for the sanity checks and the fault-seed registration; an
    # explicitly-requested shape must match what jax actually formed
    requested = num_processes
    process_id = jax.process_index()
    num_processes = jax.process_count()
    if requested is not None and num_processes != requested:
        raise post_init_failure(DistributedInitError(
            f"cluster shape mismatch: requested {requested} processes "
            f"but jax.distributed formed {num_processes}"))
    # post-init sanity: every process must reach this barrier — a peer
    # that connected but wedged before here fails the WHOLE bring-up
    # loudly instead of hanging the first collective
    try:
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.COMM_BARRIER)
        client.wait_at_barrier("dl4j/init/sanity",
                               int(barrier_timeout * 1000))
    except Exception as e:
        raise post_init_failure(DistributedInitError(
            f"process {process_id}/{num_processes}: post-init sanity "
            f"barrier not reached by all processes within "
            f"{barrier_timeout:.0f} s: {e}")) from e
    # cluster-shape agreement: publish local device count, verify the
    # global view adds up on every process
    try:
        local = jax.local_device_count()
        client.key_value_set(f"dl4j/init/devices/{process_id}",
                             str(local))
        total = 0
        for p in range(num_processes):
            total += int(client.blocking_key_value_get(
                f"dl4j/init/devices/{p}", int(barrier_timeout * 1000)))
        if len(jax.devices()) != total:
            raise DistributedInitError(
                f"cluster shape mismatch: jax sees "
                f"{len(jax.devices())} devices, the {num_processes} "
                f"peers published {total} local devices in total")
    except DistributedInitError as e:
        raise post_init_failure(e)
    except Exception as e:
        raise post_init_failure(DistributedInitError(
            f"process {process_id}/{num_processes}: device-count "
            f"agreement check failed: {e}")) from e
    _faults.PROCESS_ID = process_id
    if _mon.enabled():
        _mon.get_registry().gauge(
            _mon.DIST_PEERS,
            help="peer processes seen at the last sync point") \
            .set(num_processes)
    return True


# ======================= dp-over-DCN trainer ============================
def global_batch(mesh, tree, axis="dp", accumulation=1):
    """Build globally-sharded batch arrays from per-host FULL copies
    (the SPMD-lockstep data recipe: every host generates the same batch
    deterministically, each materializes only its own shards). Staged
    donation-safe — the per-shard views go through the misaligned-copy
    trick so XLA owns every buffer.

    accumulation > 1: `tree` is a SUPER-batch whose leaves carry a
    leading microbatch axis (G, B, ...) — the microbatch axis stays
    replicated, dim 1 shards over dp (matches
    ShardedTrainer.shard_batch)."""
    from deeplearning4j_tpu.runtime.pipeline import as_unaliasable
    jmesh = getattr(mesh, "mesh", mesh)
    spec = P(None, axis) if int(accumulation) > 1 else P(axis)
    sh = NamedSharding(jmesh, spec)

    def put(a):
        a = np.asarray(a)
        return jax.make_array_from_callback(
            a.shape, sh, lambda idx: as_unaliasable(a[idx]))

    return jax.tree_util.tree_map(put, tree)


class MultiHostTrainer(ShardedTrainer):
    """`ShardedTrainer` with accumulated, bucketed, threshold-encoded
    gradient exchange: ONE jitted step per optimizer step that

    1. lax.scans `accumulation` microbatches of a staged super-batch,
       summing gradients on device (one dispatch regardless of G);
    2. splits the accumulated gradient tree into byte-balanced buckets
       (`parallel/buckets.py`), each a single flat vector;
    3. per bucket: threshold-encodes against that bucket's OWN residual
       + adaptive threshold (≡ EncodedGradientsAccumulator, now
       chunked), then all-reduces the sparse {−t, 0, +t} payload — N
       INDEPENDENT collectives issued in program order, so bucket k's
       exchange is in flight while bucket k+1 still encodes (XLA's
       latency-hiding scheduler overlaps them; structure asserted via
       `buckets.check_overlap_structure` on the HLO text).

    The per-bucket encoder state (flat residual vector + threshold +
    wire count per bucket, stacked per worker and dp-sharded) lives
    inside `opt_state["encoder"]`, so every checkpoint carries it and a
    resumed run continues each bucket's residual accumulation
    bit-exactly.

    `compress=False` without an explicit bucket request degrades to the
    plain ShardedTrainer step (GSPMD inserts the all-reduce); with
    `buckets=`/`bucket_bytes=` it runs the bucketed exchange on RAW
    gradients (split + overlapped, no encoding). `zero1=True` shards
    the BASE optimizer state over dp (`parallel/zero.py`); the update
    math stays outside the shard_map so GSPMD partitions it by the
    state sharding — and with accumulation it runs once per super-batch,
    not once per microbatch.
    """

    def __init__(self, loss_fn, updater, mesh=None, param_specs=None,
                 batch_axis="dp", donate=True, compress=True,
                 compression_kw=None, zero1=False, accumulation=1,
                 buckets=None, bucket_bytes=None, wire="dense",
                 wire_capacity=0.05):
        if mesh is None:
            mesh = Mesh(np.array(jax.devices()), (batch_axis,))
        super().__init__(loss_fn, updater, mesh, param_specs=param_specs,
                         batch_axis=batch_axis, donate=donate,
                         accumulation=accumulation)
        self.compress = bool(compress)
        self.zero1 = bool(zero1)
        self._compression_kw = dict(compression_kw or {})
        self._enc = (_compression.threshold_encoding(**self._compression_kw)
                     if self.compress else None)
        self._num_buckets = buckets
        self._bucket_bytes = bucket_bytes
        if wire not in ("dense", "sparse"):
            raise ValueError(f"wire must be 'dense' or 'sparse', got "
                             f"{wire!r}")
        if wire == "sparse" and not self.compress:
            raise ValueError("wire='sparse' ships threshold-encoded "
                             "tokens — it requires compress=True")
        #: "dense": pmean the {−t,0,+t} tensor (bucket-sized traffic);
        #: "sparse": size-prefixed (index, sign) token allgather whose
        #: wire bytes track nnz (compression.sparse_encode/_decode)
        self.wire = wire
        #: per-bucket token capacity: a float = fraction of the bucket's
        #: elements (size it ~2× the expected nnz band so the ≤2×-nnz
        #: wire bound holds with headroom), or an int = absolute slots
        self._wire_capacity = wire_capacity
        #: the explicit shard_map'd exchange runs whenever encoding OR
        #: bucketing is requested; otherwise GSPMD owns the all-reduce
        self._explicit = (self.compress or buckets is not None
                          or bucket_bytes is not None)
        self.bucket_plan = None

    def wire_caps(self):
        """Per-bucket wire token capacities (sparse wire only; static)."""
        plan = self.bucket_plan
        if self.wire != "sparse" or plan is None:
            return None
        if isinstance(self._wire_capacity, float):
            return [_compression.wire_capacity(plan.bucket_elems[b],
                                               self._wire_capacity)
                    for b in range(plan.num_buckets)]
        return [max(1, min(int(plan.bucket_elems[b]),
                           int(self._wire_capacity)))
                for b in range(plan.num_buckets)]

    def rebuild(self, mesh):
        """A fresh trainer with this one's configuration on a DIFFERENT
        mesh — the elastic re-form primitive (the dp width changed, so
        every jitted program and the bucket plan's sharding context must
        be rebuilt; the plan itself is pure tree structure and carries
        over unchanged)."""
        clone = type(self)(
            self.loss_fn, self.tx, mesh=mesh,
            param_specs=self.param_specs, batch_axis=self.batch_axis,
            donate=self._donate, compress=self.compress,
            compression_kw=self._compression_kw, zero1=self.zero1,
            accumulation=self.accumulation, buckets=self._num_buckets,
            bucket_bytes=self._bucket_bytes, wire=self.wire,
            wire_capacity=self._wire_capacity)
        clone.bucket_plan = self.bucket_plan
        return clone

    # -- bucket plan ------------------------------------------------------
    def _ensure_plan(self, tree):
        """Build (once) the byte-balanced bucket plan from the gradient
        tree's structure — host-side shape metadata only, never device
        values."""
        if self.bucket_plan is None:
            self.bucket_plan = _buckets.plan_buckets(
                tree, num_buckets=self._num_buckets,
                bucket_bytes=self._bucket_bytes)
            if _mon.enabled():
                reg = _mon.get_registry()
                reg.gauge(_mon.DIST_EXCHANGE_BUCKETS,
                          help="independent collectives the gradient "
                               "exchange is split into").set(
                    self.bucket_plan.num_buckets)
                reg.gauge(_mon.DIST_BUCKET_BYTES,
                          help="largest planned bucket payload (bytes) "
                               "— the byte-balance quality").set(
                    max(self.bucket_plan.bucket_bytes))
        return self.bucket_plan

    # -- state -----------------------------------------------------------
    def _init_encoder_state(self, params):
        """Per-worker-stacked, PER-BUCKET encoder state: each bucket
        owns a flat residual vector (bucket_elems,), an adaptive
        threshold and a wire count — leading axis = dp size, sharded
        over dp so each worker owns exactly its own residuals. Built
        from host values via per-shard callbacks (a multi-process mesh
        has no single process that could materialize the whole
        array)."""
        from deeplearning4j_tpu.runtime.pipeline import as_unaliasable
        plan = self._ensure_plan(params)
        n = dict(zip(self.mesh.axis_names,
                     self.mesh.devices.shape))[self.batch_axis]
        thr0 = np.float32(self._compression_kw.get(
            "initial_threshold", _compression.DEFAULT_INITIAL_THRESHOLD))
        sh = NamedSharding(self.mesh, P(self.batch_axis))

        def stacked(shape, dtype, fill):
            gshape = (n,) + tuple(shape)

            def shard(idx):
                # build only THIS shard's rows (1/n of the stack) —
                # materializing the full (n, ...) host array first
                # would cost dp× the model size in transient host
                # memory on every process
                shp = tuple(len(range(*sl.indices(gshape[d])))
                            for d, sl in enumerate(idx))
                return as_unaliasable(np.full(shp, fill, dtype))

            return jax.make_array_from_callback(gshape, sh, shard)

        residual = {str(b): stacked((plan.bucket_elems[b],),
                                    plan.bucket_dtype(b), 0)
                    for b in range(plan.num_buckets)}
        return {"residual": residual,
                "threshold": stacked((plan.num_buckets,), np.float32,
                                     thr0),
                "nnz": stacked((plan.num_buckets,), np.int32, 0)}

    def init(self, params):
        params = self.shard_params(params)
        base = self.tx.init(params)
        if self.zero1:
            base = _zero.shard_optimizer_state(base, self.mesh,
                                               axis=self.batch_axis)
        if self._explicit:
            self._ensure_plan(params)
        if not self.compress:
            return params, base
        return params, {"base": base,
                        "encoder": self._init_encoder_state(params)}

    # -- the bucketed exchange -------------------------------------------
    def _make_exchange(self):
        """shard_map'd accumulate-and-exchange: scan the super-batch's
        microbatches accumulating the LOCAL gradient, then per bucket:
        threshold-encode against this worker's bucket residual (when
        compressing) → pmean of the flat payload across dp (the only
        cross-host traffic) → decode. Collectives are issued bucket by
        bucket in program order, each independent of the next bucket's
        encode — the overlap structure the HLO check asserts.

        Returns (g, new_encoder_state, loss) when compressing, else
        (g, loss). The loss is NaN-poisoned when any microbatch loss or
        the accumulated local gradient is non-finite: a NaN fails every
        `>= threshold` compare, so encoding it would silently ship
        zeros while poisoning the residual — the poisoned (replicated)
        loss makes every host's guarded verdict fail instead, and the
        guarded step rolls the encoder state back."""
        enc, loss_fn, axis = self._enc, self.loss_fn, self.batch_axis
        plan = self.bucket_plan
        if plan is None:
            raise RuntimeError("bucket plan not built — call init() "
                               "before make_step()")
        n_micro = self.accumulation
        wspec, rep = P(axis), P()
        bspec = P(None, axis) if n_micro > 1 else wspec

        # Backends whose collectives lower synchronously (CPU) schedule
        # by a memory-minimizing list heuristic that is free to group
        # every encode before every all-reduce — legal, but it erases
        # the issue-order structure this exchange exists to establish
        # (an optimization_barrier doesn't survive: XLA's
        # optimization-barrier-expander strips it before scheduling).
        # There, pin bucket k+1's encode AFTER bucket k's collective
        # with a numerically-inert data dependency:
        # + 0.0 * sum(prev[:1])
        # is exactly zero (encoded payloads are finite; float
        # mul-by-zero is NOT foldable by XLA), costs a 1-element
        # reduce, and is wall-time neutral on a sync backend (the
        # collective blocks either way) — the HLO text then documents
        # the overlap schedule async backends actually run. On TPU/GPU
        # no pin is inserted: the latency-hiding scheduler must stay
        # free to hoist all-reduce-starts wherever it likes.
        pin_order = jax.default_backend() == "cpu"
        sparse = self.wire == "sparse"
        caps = self.wire_caps() if sparse else None
        # the adaptive-threshold hyperparameters, shared with the dense
        # encoder so the two wire formats run the SAME state trajectory
        adapt_kw = {k: v for k, v in self._compression_kw.items()
                    if k != "initial_threshold"}

        def exchange_buckets(flats, e):
            """[flat grads per bucket], per-worker encoder state ->
            ([replicated flat per bucket], new state or None)."""
            outs, res2, thr2, nnz2 = [], {}, [], []
            for b in range(plan.num_buckets):
                flat = flats[b]
                if pin_order and b > 0:
                    dep = 0.0 * jnp.sum(outs[b - 1][:1])
                    flat = flat + dep.astype(flat.dtype)
                with jax.named_scope(
                        _buckets.ENCODE_SCOPE.format(b=b)):
                    if enc is None:
                        sent = flat
                    else:
                        st = {"residual": e["residual"][str(b)],
                              "threshold": e["threshold"][b],
                              "nnz": e["nnz"][b]}
                        if sparse:
                            sent, st2 = _compression.sparse_encode(
                                flat, st, caps[b], **adapt_kw)
                        else:
                            sent, st2 = enc.update(flat, st)
                        res2[str(b)] = st2["residual"]
                        thr2.append(st2["threshold"])
                        nnz2.append(st2["nnz"])
                with jax.named_scope(
                        _buckets.EXCHANGE_SCOPE.format(b=b)):
                    if sparse:
                        # size-prefixed token payloads ride an
                        # allgather (wire bytes ∝ capacity, not bucket
                        # size); decode-and-accumulate reproduces the
                        # dense pmean bit-for-bit at fixed membership
                        gathered = jax.lax.all_gather(sent, axis)
                        outs.append(_compression.sparse_decode(
                            gathered, plan.bucket_elems[b],
                            plan.bucket_dtype(b)))
                    else:
                        outs.append(jax.lax.pmean(sent, axis))
            if enc is None:
                return outs, None
            return outs, {"residual": res2,
                          "threshold": jnp.stack(thr2),
                          "nnz": jnp.stack(nnz2)}

        def local_grads(params, batch, rng):
            my = jax.lax.axis_index(axis)
            grads, loss, micro_ok = accumulate_grads(
                loss_fn, params, batch, jax.random.fold_in(rng, my),
                n_micro)
            ok = micro_ok & jnp.isfinite(optax.global_norm(grads))
            return grads, jnp.where(ok, loss, jnp.float32(jnp.nan))

        if enc is not None:
            def local(params, enc_state, batch, rng):
                grads, loss = local_grads(params, batch, rng)
                e = jax.tree_util.tree_map(lambda a: a[0], enc_state)
                outs, e2 = exchange_buckets(plan.concat(grads), e)
                restack = jax.tree_util.tree_map(lambda a: a[None], e2)
                return (plan.split(outs), restack,
                        jax.lax.pmean(loss, axis))

            return shard_map(local, mesh=self.mesh,
                             in_specs=(rep, wspec, bspec, rep),
                             out_specs=(rep, wspec, rep),
                             check_vma=False)

        def local_raw(params, batch, rng):
            grads, loss = local_grads(params, batch, rng)
            outs, _ = exchange_buckets(plan.concat(grads), None)
            return plan.split(outs), jax.lax.pmean(loss, axis)

        return shard_map(local_raw, mesh=self.mesh,
                         in_specs=(rep, bspec, rep),
                         out_specs=(rep, rep), check_vma=False)

    def make_step(self):
        if not self._explicit:
            return super().make_step()
        if self._step is not None:
            return self._step
        tx = self.tx
        exchange = self._make_exchange()
        donate = (0, 1) if self._donate else ()

        if self.compress:
            @functools.partial(jax.jit, donate_argnums=donate)
            def step(params, opt_state, batch, rng):
                g, enc2, loss = exchange(params, opt_state["encoder"],
                                         batch, rng)
                updates, base2 = tx.update(g, opt_state["base"], params)
                params = optax.apply_updates(params, updates)
                return params, {"base": base2, "encoder": enc2}, loss
        else:
            @functools.partial(jax.jit, donate_argnums=donate)
            def step(params, opt_state, batch, rng):
                g, loss = exchange(params, batch, rng)
                updates, opt_state = tx.update(g, opt_state, params)
                params = optax.apply_updates(params, updates)
                return params, opt_state, loss

        self._step = step
        return step

    def make_guarded_step(self):
        if not self._explicit:
            return super().make_guarded_step()
        cached = getattr(self, "_guarded_step", None)
        if cached is not None:
            return cached
        tx = self.tx
        exchange = self._make_exchange()
        donate = (0, 1) if self._donate else ()

        if self.compress:
            @functools.partial(jax.jit, donate_argnums=donate)
            def step(params, opt_state, batch, rng, lr_scale,
                     max_gnorm):
                g, enc2, loss = exchange(params, opt_state["encoder"],
                                         batch, rng)
                # verdict on the EXCHANGED accumulated gradient —
                # replicated, so every host computes the identical
                # ok/gnorm (per-microbatch NaN arrives as the poisoned
                # loss); an unhealthy step rolls the per-bucket encoder
                # state back too (that step never happened, residuals
                # included)
                params, base, (enc_sel,), gnorm, ok = \
                    _guardian.guarded_apply(
                        tx, g, loss, params, opt_state["base"],
                        lr_scale, max_gnorm,
                        extra=((enc2, opt_state["encoder"]),))
                return params, {"base": base, "encoder": enc_sel}, \
                    loss, gnorm, ok
        else:
            @functools.partial(jax.jit, donate_argnums=donate)
            def step(params, opt_state, batch, rng, lr_scale,
                     max_gnorm):
                g, loss = exchange(params, batch, rng)
                params, opt_state, _, gnorm, ok = \
                    _guardian.guarded_apply(
                        tx, g, loss, params, opt_state, lr_scale,
                        max_gnorm)
                return params, opt_state, loss, gnorm, ok

        self._guarded_step = step
        return step

    def fit_batch(self, params, opt_state, batch, rng):
        if self._explicit and _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.COMM_ALLREDUCE)
            if self.wire == "sparse":
                _faults.ACTIVE.fire(_faults.WIRE_DECODE)
        try:
            return super().fit_batch(params, opt_state, batch, rng)
        except (PeerLostError, PreemptionSignal):
            raise
        except Exception as e:  # noqa: BLE001 — autopsy, then re-raise
            c = _coord.ACTIVE
            if c is not None and c.num_processes > 1:
                c.autopsy(e)   # raises PeerLostError or re-raises e
            raise

    # -- telemetry -------------------------------------------------------
    def _exchange_probe(self):
        """Jitted exchange-ONLY program (per-bucket encode → pmean on
        ZERO gradients): times the standalone cost of the collectives —
        the upper bound of what the overlapped schedule can hide
        (`dl4j.dist.exposed_exchange_ms`). Compiled once; dispatched
        only at stats cadence with monitoring enabled."""
        cached = getattr(self, "_probe_fn", None)
        if cached is not None:
            return cached
        plan, enc, axis = self.bucket_plan, self._enc, self.batch_axis

        def local(enc_state):
            e = jax.tree_util.tree_map(lambda a: a[0], enc_state)
            acc = jnp.float32(0.0)
            for b in range(plan.num_buckets):
                flat = jnp.zeros((plan.bucket_elems[b],),
                                 plan.bucket_dtype(b))
                st = {"residual": e["residual"][str(b)],
                      "threshold": e["threshold"][b],
                      "nnz": e["nnz"][b]}
                sent, _ = enc.update(flat, st)
                acc = acc + jnp.sum(jax.lax.pmean(sent, axis) ** 2)
            return acc

        fn = shard_map(local, mesh=self.mesh,
                       in_specs=(P(self.batch_axis),), out_specs=P(),
                       check_vma=False)
        self._probe_fn = jax.jit(fn)
        return self._probe_fn

    def encoder_stats(self, opt_state):
        """Materialize the compression wire telemetry (one small host
        read — call at sync cadence, not per step): mean adaptive
        threshold, total elements shipped last step, residual norm, and
        the per-bucket wire ledger (elements shipped per bucket, summed
        over workers)."""
        if not self.compress:
            return None
        fn = getattr(self, "_stats_fn", None)
        if fn is None:
            rep = NamedSharding(self.mesh, P())

            def stats(enc_state):
                out = _compression.encoder_stats(enc_state)
                nnz = enc_state["nnz"]           # (workers, buckets)
                out["bucket_nnz"] = jnp.sum(
                    nnz.reshape(-1, nnz.shape[-1]), axis=0)
                return out

            fn = jax.jit(stats,
                         out_shardings={"threshold": rep, "nnz": rep,
                                        "residual_norm": rep,
                                        "bucket_nnz": rep})
            self._stats_fn = fn
        dev = fn(opt_state["encoder"])

        def materialize(v):
            return np.asarray(v.addressable_shards[0].data) \
                if hasattr(v, "addressable_shards") else np.asarray(v)

        host = {k: materialize(v) for k, v in dev.items()}
        host["threshold"] = float(host["threshold"])
        host["residual_norm"] = float(host["residual_norm"])
        host["nnz"] = int(host["nnz"])
        # an encoded element ships as (index, sign) — call it 4 bytes on
        # the wire vs 4 bytes/element for a dense fp32 all-reduce
        host["encoded_bytes"] = host["nnz"] * 4
        host["bucket_nnz"] = [int(v) for v in host["bucket_nnz"]]
        host["bucket_encoded_bytes"] = [v * 4 for v in host["bucket_nnz"]]
        if self.wire == "sparse":
            # ACTUAL wire cost of the sparse format: every worker ships
            # (capacity + header) int32 slots per bucket each step —
            # static by construction, sized to track the nnz ledger
            caps = self.wire_caps()
            n_workers = int(np.prod(
                opt_state["encoder"]["nnz"].shape[:-1]))
            host["wire_capacity"] = list(caps)
            host["bucket_wire_bytes"] = [
                _compression.wire_payload_bytes(c) * n_workers
                for c in caps]
            host["wire_bytes"] = int(sum(host["bucket_wire_bytes"]))
            plan = self.bucket_plan
            host["dense_bytes"] = int(sum(
                plan.bucket_elems[b] * np.dtype(plan.bucket_dtype(b)).itemsize
                for b in range(plan.num_buckets)) * n_workers)
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.DIST_ENCODED_BYTES,
                        help="approximate bytes of threshold-encoded "
                             "gradient shipped cross-host").inc(
                host["encoded_bytes"])
            reg.gauge(_mon.DIST_RESIDUAL_NORM,
                      help="global norm of the un-sent gradient "
                           "residual").set(host["residual_norm"])
            if self.wire == "sparse":
                reg.gauge(_mon.DIST_WIRE_BYTES,
                          help="actual per-step bytes on the sparse "
                               "ragged wire (all workers, all buckets: "
                               "capacity + header slots)") \
                    .set(host["wire_bytes"])
            # exchange exposure, two regimes on one gauge:
            # - single-process: dispatch the exchange-only probe and
            #   time the blocked wait (first call warms the compile
            #   un-timed; we are already at a declared host-sync
            #   cadence, never per step) — a standalone UPPER bound.
            # - multi-process: the probe issues a collective, and
            #   monitoring.enabled() is host-LOCAL state — a subset of
            #   hosts with monitoring on would issue a pmean the others
            #   never join (hang, or worse: pair with a peer's next
            #   training collective). Instead DERIVE a lower bound from
            #   the published per-host step timelines: in a lockstep
            #   collective step the cross-host spread in dispatch-phase
            #   p50 is wall time the exchange exposed on the fast hosts
            #   (monitoring/stragglers.py, no collective issued).
            if jax.process_count() > 1:
                ms = self._derived_exchange_ms()
                if ms is not None:
                    host["exposed_exchange_ms_derived"] = ms
                    reg.gauge(_mon.DIST_EXPOSED_EXCHANGE_MS,
                              help="exposed cost of the bucketed "
                                   "exchange: probed standalone in "
                                   "single-process runs (upper bound); "
                                   "derived from cross-host dispatch-"
                                   "phase skew in multi-process runs "
                                   "(lower bound, no collective)"
                              ).set(ms)
                return host
            import time as _time
            probe = self._exchange_probe()
            if not getattr(self, "_probe_warm", False):
                jax.block_until_ready(probe(opt_state["encoder"]))
                self._probe_warm = True
            t0 = _time.perf_counter()
            jax.block_until_ready(probe(opt_state["encoder"]))
            ms = (_time.perf_counter() - t0) * 1e3
            host["exposed_exchange_ms"] = ms
            reg.gauge(_mon.DIST_EXPOSED_EXCHANGE_MS,
                      help="exposed cost of the bucketed exchange: "
                           "probed standalone in single-process runs "
                           "(upper bound); derived from cross-host "
                           "dispatch-phase skew in multi-process runs "
                           "(lower bound, no collective)").set(ms)
        return host

    @staticmethod
    def _derived_exchange_ms():
        """Multi-process exposed-exchange estimate off the straggler
        plane's published timelines — None without an active
        coordinator or below two reporting hosts."""
        coord = _coord.ACTIVE
        if coord is None:
            return None
        try:
            from deeplearning4j_tpu.monitoring import stragglers as _sg
            return _sg.derived_exchange_ms(coord)
        except Exception:  # noqa: BLE001
            return None


# ===================== coordinated robustness ===========================
class CoordinatedGuardian(_guardian.TrainingGuardian):
    """TrainingGuardian whose verdict flush is ALL-REDUCED across
    processes: each host publishes its materialized (gnorm, ok) window,
    gathers every peer's, and folds them (elementwise AND of ok, max of
    gnorm — NaN-poisoning preserved). Every host therefore feeds the
    IDENTICAL window into the deterministic escalation ladder and
    reaches the same skip / LR-backoff / rollback decision on the same
    step. A peer that never publishes its window within the peer
    timeout is a lost peer (`PeerLostError`), a window of a different
    length is a desynced one (`PeerDesyncError`)."""

    def __init__(self, coordinator, **kw):
        kw.setdefault("check_every", coordinator.sync_every)
        super().__init__(**kw)
        self.coordinator = coordinator
        self._flushes = 0

    def _materialize(self):
        import json
        gnorms, oks, retryables = super()._materialize()
        c = self.coordinator
        if c is None or len(c.members) <= 1:
            return gnorms, oks, retryables
        n = self._flushes
        self._flushes += 1
        c.publish(f"gv/{n}/{c.process_id}",
                  json.dumps({"g": [float(x) for x in gnorms],
                              "ok": [bool(x) for x in oks]}))
        gnorms = np.asarray(gnorms, np.float32)
        oks = np.asarray(oks, bool)
        for pid in c.members:
            if pid == c.process_id:
                continue
            try:
                peer = json.loads(c.fetch(f"gv/{n}/{pid}"))
            except Exception as e:  # noqa: BLE001
                raise c._peer_lost_error(
                    f"verdict flush {n}: no window from process {pid} "
                    f"within {c.peer_timeout:.1f} s", cause=e) from e
            if len(peer["ok"]) != len(oks):
                raise c.desync_error(
                    f"verdict flush {n}: process {pid} flushed "
                    f"{len(peer['ok'])} verdicts, this process "
                    f"{len(oks)} — the guarded-step cadence desynced")
            gnorms = np.maximum(gnorms,
                                np.asarray(peer["g"], np.float32))
            oks = np.logical_and(oks, np.asarray(peer["ok"], bool))
        # reap this process's flush-before-last window (everyone is
        # provably past it) so long runs don't grow the KV store
        if n >= 2:
            try:
                c._client.key_value_delete(
                    c._key(f"gv/{n - 2}/{c.process_id}"))
            except Exception:  # noqa: BLE001
                pass
        return gnorms, oks, retryables


class MultiHostRunner:
    """Coordinated driver for a `MultiHostTrainer` loop: periodic
    coordinated checkpoints (every process gathers + snapshots, process
    0 writes, peers verify the manifest against their own snapshot),
    guardian rollbacks that land every host on the same verified
    generation, and the preemption drain (agree at a sync point → final
    wait=True verified checkpoint → `PreemptionSignal` unwinds the fit
    loop for a clean exit).

    Functional style, like FaultTolerantTrainer's sharded mode:

        runner = MultiHostRunner(trainer, dir, coordinator,
                                 guardian=CoordinatedGuardian(coord))
        params, opt_state = runner.resume_or_init(init_params)
        while runner.step < total_steps:
            params, opt_state, loss = runner.fit_batch(
                params, opt_state, make_batch(runner.step))
    """

    def __init__(self, trainer, directory, coordinator, save_every=10,
                 guardian=None, verify_saves=True, max_to_keep=5,
                 rng_seed=0, monitor=True, sigterm=True,
                 elastic=False, mesh_factory=None, membership=None):
        from deeplearning4j_tpu.parallel.elastic import ElasticCheckpointer
        self.trainer = trainer
        self.coordinator = coordinator
        self.directory = str(directory)
        self.save_every = int(save_every)
        self.guardian = guardian
        self.verify_saves = bool(verify_saves)
        self.primary = coordinator.process_id == 0
        multi = coordinator.num_processes > 1
        # -- elastic membership: mid-run join/leave/replace ---------------
        self.elastic = bool(elastic)
        self.mesh_factory = mesh_factory
        self.membership = None
        self._replaces = 0         # replacement transitions executed
        if self.elastic:
            if mesh_factory is None:
                raise ValueError(
                    "elastic=True needs a mesh_factory(members) -> Mesh "
                    "so the dp mesh can re-form when membership changes")
            if getattr(trainer, "zero1", False):
                raise ValueError(
                    "elastic membership with zero1 optimizer-state "
                    "sharding is unsupported: re-forming would re-shard "
                    "the partitioned optimizer state mid-run")
            self.membership = membership if membership is not None \
                else ElasticMembership(coordinator)
        # single-writer pattern: process 0 owns the directory (orbax
        # barriers scoped to it alone — see ElasticCheckpointer), peers
        # open it read-only for restore + manifest verification; only
        # the writer sweeps startup debris
        self.ckpt = ElasticCheckpointer(
            directory, max_to_keep=max_to_keep, save_interval_steps=1,
            sweep_orphans=self.primary,
            primary_only=multi and self.primary,
            read_only=multi and not self.primary)
        self.step = 0
        self.resumed_step = None
        self._save_seq = 0         # barrier ids must be single-use; the
        #                            sequence increments identically on
        #                            every process (same call order)
        self.root_rng = jax.random.PRNGKey(int(rng_seed))
        self._gather_cache = {}    # treedef -> jitted replicating gather
        coordinator.driver_attached = True
        coordinator.bind(trainer)   # auxiliary local fits don't count
        coordinator.install()
        coordinator.on_sync = self._on_sync
        if monitor:
            coordinator.start_monitor()
        self._prev_signals = None
        if sigterm:
            # previous handlers restored in close(): runners created
            # sequentially must not chain a dead coordinator's handler
            try:
                self._prev_signals = \
                    _coord.install_preemption_handler(coordinator)
            except ValueError:
                # signal API is main-thread-only; a runner built on a
                # worker thread simply runs without the SIGTERM hook
                pass
        if guardian is not None:
            guardian.driver_attached = True
            guardian.bind(trainer)  # auxiliary local fits don't report
            guardian.install()

    # -- host snapshot (the coordinated-save core) -----------------------
    def _gather_replicated(self, tree):
        """All processes jit-gather the tree to fully-replicated (the
        dp-sharded encoder / ZeRO leaves ride one all-gather), then each
        snapshots its LOCAL copy to host numpy. Every process ends up
        with the identical full state — process 0 saves it, everyone
        else verifies the manifest against it. The jitted gather is
        cached per tree structure (a fresh lambda per save would
        recompile the all-gather at every checkpoint)."""
        treedef = jax.tree_util.tree_structure(tree)
        fn = self._gather_cache.get(treedef)
        if fn is None:
            rep = NamedSharding(self.trainer.mesh, P())
            shardings = jax.tree_util.tree_unflatten(
                treedef, [rep] * treedef.num_leaves)
            fn = jax.jit(lambda t: t, out_shardings=shardings)
            self._gather_cache[treedef] = fn
        gathered = fn(tree)

        def host(a):
            if not hasattr(a, "addressable_shards"):
                return np.array(a)
            return np.array(a.addressable_shards[0].data)

        return jax.tree_util.tree_map(host, gathered)

    def _host_state(self, params, opt_state):
        return {"params": self._gather_replicated(params),
                "opt_state": self._gather_replicated(opt_state)}

    # -- save ------------------------------------------------------------
    def _save(self, params, opt_state, wait=False):
        g = self.guardian
        if g is not None and not g.verify_now():
            if _mon.enabled():
                _mon.get_registry().counter(
                    _mon.GUARDIAN_SAVES_GATED,
                    help="checkpoint saves withheld because the "
                         "guardian could not vouch for the params").inc()
            return False
        # EVERY process gathers, even a peer with verify_saves=False:
        # the gather is one SPMD all-gather over globally-sharded
        # arrays — skipping it on peers would leave the primary's
        # collective waiting forever
        host = self._host_state(params, opt_state)
        self._last_host_state = host   # elastic re-form reuses this
        #                                snapshot (no second old-mesh
        #                                collective once a host is gone)
        if self.primary:
            self.ckpt.save(self.step, host["params"], host["opt_state"],
                           wait=wait,
                           verdict=None if g is None else "verified")
        if self.coordinator.num_processes > 1:
            # the manifest is written synchronously inside save(), so
            # once the primary reaches this fence peers can verify even
            # an async save's manifest
            self._save_seq += 1
            self.coordinator.barrier(f"save/{self.step}/{self._save_seq}")
            if not self.primary and self.verify_saves:
                self._verify_manifest(self.step, host)
        return True

    def _fetch_decision(self, key, what):
        """Wait for a control decision process 0 publishes AFTER a
        potentially long local phase (checkpoint scan, rollback
        restore). A fixed timeout would misread a primary that is
        merely busy restoring a large state as dead — so the wait is
        bounded by the primary's LIVENESS (monitor beats), not by the
        size of its work: keep waiting in short slices while process 0
        beats; raise PeerLostError only once it goes silent past the
        peer timeout (or immediately when no liveness keys exist to
        adjudicate on)."""
        import time as _time
        c = self.coordinator
        slice_s = min(c.barrier_timeout, 15.0)
        start = _time.monotonic()
        # hard ceiling even while process 0's monitor keeps beating:
        # the monitor is a daemon THREAD, so its beats prove the
        # process is alive, not that the main thread is making progress
        # — a wedged restore must still surface in bounded time ('never
        # a silent hang' is the module contract)
        hard_cap = max(4.0 * c.barrier_timeout, 2.0 * c.peer_timeout)
        while True:
            try:
                return c.fetch(key, timeout=slice_s)
            except Exception as e:  # noqa: BLE001 — timeout slice over
                waited = _time.monotonic() - start
                try:
                    alive = c.alive_info()
                except Exception as kv_err:  # noqa: BLE001 — service gone
                    raise c._peer_lost_error(
                        f"coordination service unreachable while "
                        f"waiting for the {what} decision — the "
                        f"coordinator process likely died ({kv_err})",
                        cause=e) from e
                if waited > hard_cap:
                    raise c._peer_lost_error(
                        f"no {what} decision from process 0 within the "
                        f"{hard_cap:.0f} s ceiling — its process is "
                        f"{'still beating (main thread wedged?)' if alive else 'silent'}; "
                        f"raise DL4J_BARRIER_TIMEOUT for very large "
                        f"states", cause=e) from e
                if alive:
                    # liveness evidence exists: adjudicate on it — keep
                    # waiting while process 0 beats, declare it lost
                    # only when its silence crosses the peer timeout
                    if 0 in c._stale_peers():
                        raise c._peer_lost_error(
                            f"process 0 never published its {what} "
                            f"decision and has stopped heartbeating — "
                            f"it likely died mid-{what}", cause=e) from e
                elif waited > c.barrier_timeout:
                    # no monitors anywhere: cannot tell dead from slow —
                    # fail typed after the barrier budget, honestly
                    raise c._peer_lost_error(
                        f"no {what} decision from process 0 within "
                        f"{c.barrier_timeout:.0f} s and no liveness "
                        f"evidence to wait on (PeerMonitor off) — it "
                        f"may have died, or may still be working a "
                        f"large state; raise DL4J_BARRIER_TIMEOUT or "
                        f"enable the monitor", cause=e) from e

    def _verify_manifest(self, step, host_state):
        """Peer-side split-brain check: the manifest process 0 just
        wrote must checksum-match THIS process's own snapshot of the
        (supposedly replicated) state. A mismatch means the hosts'
        models diverged — fail loudly now, not at some future restore."""
        from deeplearning4j_tpu.resilience import integrity as _integrity
        state = {"params": host_state["params"],
                 "opt_state": host_state["opt_state"]}
        try:
            _integrity.verify_restored(self.directory, step, state,
                                       check_finite=False)
        except CheckpointIntegrityError as e:
            raise PeerDesyncError(
                f"step {step}: this process's state does not match the "
                f"manifest process 0 wrote — replicated model state "
                f"has diverged across hosts ({e})",
                peers=self.coordinator.peer_table()) from e

    # -- restore ---------------------------------------------------------
    def _restore_placed(self, step, like_live, verified_scan=False):
        """Restore generation `step` (or the newest verified when
        `verified_scan`) as HOST arrays, integrity-verify, then re-place
        onto the live tree's shardings (cross-process placements go
        shard-by-shard). Returns (step, placed_state).

        Checkpoints written BEFORE the bucketed exchange (encoder
        residual keyed by param leaf, one shared threshold per worker)
        restore through the legacy-layout fallback and are migrated
        in-place to the per-bucket layout — residual BITS preserved
        (each bucket's flat vector is the concat of its leaves'
        residuals), the shared threshold tiled across buckets."""
        from deeplearning4j_tpu.parallel.elastic import replace_on_mesh
        from deeplearning4j_tpu.resilience import integrity as _integrity
        like_host = jax.tree_util.tree_map(
            lambda a: np.zeros(a.shape, a.dtype)
            if hasattr(a, "shape") else a, like_live)
        if verified_scan:
            try:
                s, state = self.ckpt.restore_verified(like=like_host)
            except CheckpointIntegrityError as e:
                s, state = self._restore_legacy(None, like_host, e)
        else:
            _debug("restore: reading generation", step)
            try:
                s, state = self.ckpt.restore(step=step, like=like_host)
                _debug("restore: verifying generation", s)
                _integrity.verify_restored(self.directory, s, state)
            except (ValueError, CheckpointIntegrityError) as e:
                s, state = self._restore_legacy(step, like_host, e)
        if s is None:
            return None, None
        _debug("restore: re-placing generation", s, "on the mesh")
        placed = replace_on_mesh(self.trainer.mesh, like_live, state)
        _debug("restore: placed generation", s)
        return s, placed

    def _legacy_encoder_like(self, like_host):
        """Host-zeros restore target in the PRE-bucketing encoder
        layout (PR 7): residual = params-shaped tree of per-worker
        stacks, ONE shared threshold / nnz scalar per worker. None when
        this runner's state has no encoder (nothing legacy to match)."""
        opt = like_host.get("opt_state")
        plan = getattr(self.trainer, "bucket_plan", None)
        if not (isinstance(opt, dict) and "encoder" in opt
                and plan is not None):
            return None
        dp = opt["encoder"]["threshold"].shape[0]
        residual = jax.tree_util.tree_unflatten(
            plan.treedef,
            [np.zeros((dp,) + plan.shapes[i], plan.dtypes[i])
             for i in range(len(plan.shapes))])
        legacy_opt = dict(opt)
        legacy_opt["encoder"] = {"residual": residual,
                                 "threshold": np.zeros((dp,),
                                                       np.float32),
                                 "nnz": np.zeros((dp,), np.int32)}
        out = dict(like_host)
        out["opt_state"] = legacy_opt
        return out

    def _migrate_encoder(self, state):
        """Legacy -> per-bucket encoder layout, on host arrays:
        bucket b's flat residual = concat of its leaves' residual rows
        (bit-preserving), threshold tiled per bucket (every bucket
        resumes the shared adaptive threshold it would have had), nnz
        reset to 0 (pure last-step telemetry, not encoder input)."""
        plan = self.trainer.bucket_plan
        enc = state["opt_state"]["encoder"]
        leaves = jax.tree_util.tree_leaves(enc["residual"])
        dp = leaves[0].shape[0]
        residual = {
            str(b): np.concatenate(
                [np.asarray(leaves[i]).reshape(dp, -1)
                 for i in plan.buckets[b]], axis=1)
            for b in range(plan.num_buckets)}
        thr = np.tile(
            np.asarray(enc["threshold"], np.float32).reshape(dp, 1),
            (1, plan.num_buckets))
        new_opt = dict(state["opt_state"])
        new_opt["encoder"] = {
            "residual": residual, "threshold": thr,
            "nnz": np.zeros((dp, plan.num_buckets), np.int32)}
        out = dict(state)
        out["opt_state"] = new_opt
        return out

    def _restore_legacy(self, step, like_host, cause):
        """Fallback restore for pre-bucketing checkpoints: re-restore
        against the legacy encoder layout (the manifest verifies
        against THAT tree), then migrate to the per-bucket layout.
        Re-raises `cause` when the state has no encoder or the legacy
        layout doesn't match either (genuine corruption)."""
        from deeplearning4j_tpu.resilience import integrity as _integrity
        legacy_like = self._legacy_encoder_like(like_host)
        if legacy_like is None:
            raise cause
        try:
            if step is None:
                s, state = self.ckpt.restore_verified(like=legacy_like)
            else:
                s, state = self.ckpt.restore(step=step, like=legacy_like)
                _integrity.verify_restored(self.directory, s, state)
        except Exception:  # noqa: BLE001 — not legacy either
            raise cause
        if s is None:
            return None, None
        _debug("restore: migrating legacy encoder layout, generation",
               s)
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.DIST_ENCODER_MIGRATIONS,
                help="pre-bucketing encoder states migrated to the "
                     "per-bucket layout on restore").inc()
        return s, self._migrate_encoder(state)

    def resume_or_init(self, init_params):
        """All hosts land on the SAME generation: process 0 scans for
        the newest verified checkpoint (manifest checksums + finiteness,
        falling back a generation on corruption) and publishes its
        choice; peers restore exactly that step and verify it
        themselves. Returns (params, opt_state) with `self.step` set to
        the restored step (0 when starting fresh)."""
        c = self.coordinator
        params, opt_state = self.trainer.init(init_params)
        like = {"params": params, "opt_state": opt_state}
        if c.num_processes <= 1:
            s, placed = self._restore_placed(None, like,
                                             verified_scan=True)
            if s is not None:
                self.step = int(s)
                self._note_resume()
                return placed["params"], placed["opt_state"]
            return params, opt_state
        if self.primary:
            _debug("resume: primary scanning for newest verified")
            try:
                s, placed = self._restore_placed(None, like,
                                                 verified_scan=True)
            except BaseException:
                # ANY primary-side failure (integrity, I/O, orbax,
                # placement) must unblock the peers promptly with a
                # clear verdict — silence would leave them waiting out
                # the full liveness ceiling blaming the wrong host
                try:
                    c.publish("ctl/resume", "fail")
                except Exception:  # noqa: BLE001
                    pass
                raise
            _debug("resume: primary restored", s, "— publishing")
            c.publish("ctl/resume", str(-1 if s is None else int(s)))
        else:
            v = self._fetch_decision("ctl/resume", "resume")
            _debug("resume: peer fetched decision", v)
            s = None
            if v == "fail":
                raise CheckpointIntegrityError(
                    "process 0 failed its checkpoint scan/restore — "
                    "see its logs; refusing to resume")
            s = int(v)
            if s < 0:
                s, placed = None, None
            else:
                s, placed = self._restore_placed(s, like)
            _debug("resume: peer restored", s)
        c.barrier("resume")
        _debug("resume: barrier passed, step", s)
        if s is None:
            return params, opt_state
        self.step = int(s)
        self._note_resume()
        return placed["params"], placed["opt_state"]

    def _note_resume(self):
        self.resumed_step = self.step
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.RESILIENCE_RESUMES,
                        help="checkpoint resumes after restart").inc()
            reg.gauge(_mon.RESILIENCE_RESUME_STEP,
                      help="step the latest resume restored") \
                .set(self.step)

    # -- rollback --------------------------------------------------------
    def _rollback(self, g, params, opt_state):
        """Guardian-requested rollback, coordinated: process 0 picks
        the newest verified generation and publishes it under a key
        derived from the (coordinated) rollback count, so every host
        restores — and verifies — exactly the same one."""
        c = self.coordinator
        like = {"params": params, "opt_state": opt_state}
        key = f"ctl/rollback/{g.rollbacks}"
        if c.num_processes <= 1 or self.primary:
            try:
                self.ckpt.manager.wait_until_finished()
                s, placed = self._restore_placed(None, like,
                                                 verified_scan=True)
                if s is None:
                    raise CheckpointIntegrityError(
                        "guardian requested rollback but no verified "
                        "checkpoint exists yet")
            except BaseException:
                # unblock the peers with a verdict (see resume_or_init)
                if c.num_processes > 1:
                    try:
                        c.publish(key, "fail")
                    except Exception:  # noqa: BLE001
                        pass
                raise
            if c.num_processes > 1:
                c.publish(key, str(int(s)))
        else:
            v = self._fetch_decision(key, "rollback")
            if v == "fail":
                raise CheckpointIntegrityError(
                    "process 0 failed its rollback restore — see its "
                    "logs")
            s, placed = self._restore_placed(int(v), like)
        if c.num_processes > 1:
            c.barrier(f"rollback/{g.rollbacks}")
        g.note_rollback(int(s))
        return placed["params"], placed["opt_state"]

    # -- elastic membership: mid-run join / leave / replace --------------
    def request_leave(self):
        """Announce a GRACEFUL leave for this host: the next sync point
        agrees the REFORM on every member, the final state drains to a
        verified checkpoint on the old mesh, the survivors re-form, and
        this host's fit loop unwinds with `PreemptionSignal` — the same
        clean-exit contract the SIGTERM drain gives."""
        if not self.elastic:
            raise MembershipChangeError(
                "request_leave() requires an elastic runner "
                "(elastic=True with a mesh_factory)")
        return self.membership.announce_leave()

    def _encoder_dp(self, opt_state):
        """The per-worker encoder stack width of this state, or None
        when the trainer doesn't compress (nothing width-dependent)."""
        if not getattr(self.trainer, "compress", False) \
                or not isinstance(opt_state, dict) \
                or "encoder" not in opt_state:
            return None
        return int(opt_state["encoder"]["threshold"].shape[0])

    def _elastic_like(self, like_host, dp):
        """Host-zeros restore target with the encoder stacks at width
        `dp` (the width the checkpoint was WRITTEN at) — None when the
        current width already matches or there is no encoder."""
        opt = like_host.get("opt_state")
        if dp is None or not (isinstance(opt, dict) and "encoder" in opt):
            return None
        enc = opt["encoder"]
        if int(np.asarray(enc["threshold"]).shape[0]) == int(dp):
            return None

        def widen(a):
            a = np.asarray(a)
            return np.zeros((int(dp),) + a.shape[1:], a.dtype)

        new_opt = dict(opt)
        new_opt["encoder"] = {
            "residual": {b: widen(r)
                         for b, r in enc["residual"].items()},
            "threshold": widen(enc["threshold"]),
            "nnz": widen(enc["nnz"])}
        out = dict(like_host)
        out["opt_state"] = new_opt
        return out

    def _restore_restacked(self, step, like_live, old_dp,
                           verified_scan=False):
        """`_restore_placed` for a WIDTH-CHANGED resume: restore (and
        integrity-verify) against the checkpoint's own old-width
        encoder layout, re-stack the per-worker encoder state for the
        live width (`membership.restack_encoder`), then re-place on the
        live mesh. Falls through to the plain path when widths match."""
        from deeplearning4j_tpu.parallel.elastic import replace_on_mesh
        from deeplearning4j_tpu.parallel.membership import restack_encoder
        from deeplearning4j_tpu.resilience import integrity as _integrity
        like_host = jax.tree_util.tree_map(
            lambda a: np.zeros(a.shape, a.dtype)
            if hasattr(a, "shape") else a, like_live)
        wide = self._elastic_like(like_host, old_dp)
        if wide is None:
            return self._restore_placed(step, like_live,
                                        verified_scan=verified_scan)
        if verified_scan:
            s, state = self.ckpt.restore_verified(like=wide)
        else:
            s, state = self.ckpt.restore(step=step, like=wide)
            _integrity.verify_restored(self.directory, s, state)
        if s is None:
            return None, None
        new_dp = self._encoder_dp(like_host.get("opt_state"))
        new_opt = dict(state["opt_state"])
        new_opt["encoder"] = restack_encoder(new_opt["encoder"], new_dp)
        state = dict(state)
        state["opt_state"] = new_opt
        placed = replace_on_mesh(self.trainer.mesh, like_live, state)
        return s, placed

    def _reform(self, params, opt_state, delta):
        """Execute an AGREED membership change at this step boundary:
        coordinated drain save on the OLD mesh (the joiner's warm start
        and the leaver's final state), the join-admission fault window,
        leader commit (+ departed-host KV reap), then the survivors
        rebuild on the new mesh. Returns (None, None) on the leaving
        host — the caller unwinds with the drain signal."""
        import time as _time
        joins, leaves = delta
        c = self.coordinator
        if 0 in leaves:
            self.membership.abandon(leaves=[0])
            raise MembershipChangeError(
                "process 0 cannot leave an elastic run: it owns the "
                "checkpoint directory and hosts the coordination "
                "service — drain the whole run (preemption) instead")
        t0 = _time.monotonic()
        saved = self._save(params, opt_state, wait=True)
        host = self._last_host_state if saved \
            else self._host_state(params, opt_state)
        if joins and not saved:
            # the guardian could not vouch, so no drain checkpoint was
            # written: a joiner warm-starting an OLDER generation would
            # desync against the survivors' live step — withdraw the
            # joins (they re-announce later), keep any leaves
            self.membership.abandon(joins=joins)
            joins = []
            if not leaves:
                return params, opt_state
        if _faults.ACTIVE is not None:
            # host.join: an injected failure in the admission window
            # abandons the announcements — the OLD roster stays
            # authoritative and live state is untouched (typed failure,
            # never a half-applied roster)
            try:
                _faults.ACTIVE.fire(_faults.HOST_JOIN)
            except BaseException as e:
                self.membership.abandon(joins=joins, leaves=leaves)
                raise MembershipChangeError(
                    f"membership change (join={joins}, leave={leaves}) "
                    f"failed before commit — previous roster stays "
                    f"authoritative: {e}") from e
        info = {"step": self.step, "cstep": c.step, "rounds": c.rounds,
                "save_seq": self._save_seq,
                "dp": self._encoder_dp(opt_state),
                "flushes": getattr(self.guardian, "_flushes", 0),
                "rollbacks": getattr(self.guardian, "rollbacks", 0)}
        new_members = self.membership.commit(joins, leaves, info=info)
        if c.process_id in leaves:
            return None, None
        params, opt_state = self._rebuild(host, new_members)
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.DIST_REFORMS,
                        labels={"kind": "join" if joins else "leave"},
                        help="elastic mesh re-forms executed").inc()
            reg.gauge(_mon.DIST_REFORM_MS,
                      help="wall ms of the last elastic re-form "
                           "(drain save + rebuild + re-place)") \
                .set(round((_time.monotonic() - t0) * 1000.0, 3))
        return params, opt_state

    def _rebuild(self, host, new_members):
        """Re-form onto the NEW roster from the replicated host
        snapshot: fresh trainer on `mesh_factory(members)`, per-worker
        encoder stacks re-stacked for the new dp width (residual mass
        conserved), every leaf re-placed on the new mesh."""
        from deeplearning4j_tpu.parallel.elastic import replace_on_mesh
        from deeplearning4j_tpu.parallel.membership import restack_encoder
        new_mesh = self.mesh_factory(list(new_members))
        new_trainer = self.trainer.rebuild(new_mesh)
        fresh_p, fresh_o = new_trainer.init(
            jax.tree_util.tree_map(np.asarray, host["params"]))
        like = {"params": fresh_p, "opt_state": fresh_o}
        state = {"params": host["params"],
                 "opt_state": dict(host["opt_state"])
                 if isinstance(host["opt_state"], dict)
                 else host["opt_state"]}
        if isinstance(state["opt_state"], dict) \
                and "encoder" in state["opt_state"] \
                and getattr(new_trainer, "compress", False):
            new_dp = int(fresh_o["encoder"]["threshold"].shape[0])
            state["opt_state"]["encoder"] = restack_encoder(
                state["opt_state"]["encoder"], new_dp)
        placed = replace_on_mesh(new_mesh, like, state)
        self.trainer = new_trainer
        self._gather_cache = {}
        self._last_opt_state = None
        self.coordinator.bind(new_trainer)
        if self.guardian is not None:
            self.guardian.bind(new_trainer)
        return placed["params"], placed["opt_state"]

    def _replace_lost(self, params, opt_state, exc):
        """A peer died mid-run (`PeerLostError`): the survivors re-form
        on the reduced roster and KEEP TRAINING from the newest
        verified checkpoint (the step may rewind by < save_every); a
        restarted or standby host joins back through `join_cluster`
        later. The live state is unusable — in a real multi-host run it
        spans the dead host's devices — so replacement is a restore,
        not a migration. Re-raises `exc` when nothing can survive
        (process 0 died: it owns the checkpoints and the KV store)."""
        import time as _time
        c = self.coordinator
        lost = sorted(set(c._lost) & set(c.members))
        if not lost or c.process_id in lost:
            raise exc
        if 0 in lost:
            raise exc
        survivors = [p for p in c.members if p not in lost]
        if not survivors:
            raise exc
        t0 = _time.monotonic()
        old_dp = self._encoder_dp(opt_state)
        self._replaces += 1
        m = self.membership
        if c.process_id == min(survivors):
            for pid in lost:
                m.reap_host(pid)
        m.members = list(survivors)
        m.epoch += 1
        c.reform(survivors)
        new_mesh = self.mesh_factory(list(survivors))
        new_trainer = self.trainer.rebuild(new_mesh)
        host_like = jax.tree_util.tree_map(
            lambda a: np.zeros(a.shape, a.dtype)
            if hasattr(a, "shape") else a, params)
        fresh_p, fresh_o = new_trainer.init(host_like)
        self.trainer = new_trainer
        self._gather_cache = {}
        self._last_opt_state = None
        c.bind(new_trainer)
        if self.guardian is not None:
            self.guardian.bind(new_trainer)
        like = {"params": fresh_p, "opt_state": fresh_o}
        key = f"ctl/replace/{self._replaces}"
        if len(survivors) <= 1 or self.primary:
            try:
                self.ckpt.manager.wait_until_finished()
                s, placed = self._restore_restacked(
                    None, like, old_dp, verified_scan=True)
                if s is None:
                    raise CheckpointIntegrityError(
                        f"peer(s) {lost} lost but no verified "
                        f"checkpoint exists to re-form from") from exc
            except BaseException:
                if len(survivors) > 1:
                    try:
                        c.publish(key, "fail")
                    except Exception:  # noqa: BLE001
                        pass
                raise
            if len(survivors) > 1:
                c.publish(key, str(int(s)))
        else:
            v = self._fetch_decision(key, "replace")
            if v == "fail":
                raise CheckpointIntegrityError(
                    "the lead survivor failed its replacement restore "
                    "— see its logs") from exc
            s, placed = self._restore_restacked(int(v), like, old_dp)
        if len(survivors) > 1:
            c.barrier(f"replace/{self._replaces}")
        self.step = int(s)
        self._note_resume()
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter(_mon.DIST_REFORMS, labels={"kind": "replace"},
                        help="elastic mesh re-forms executed").inc()
            reg.gauge(_mon.DIST_REFORM_MS,
                      help="wall ms of the last elastic re-form "
                           "(drain save + rebuild + re-place)") \
                .set(round((_time.monotonic() - t0) * 1000.0, 3))
            _events.emit("parallel", _events.MEMBERSHIP_REPLACED,
                         attrs={"lost": sorted(lost),
                                "survivors": sorted(survivors),
                                "step": self.step},
                         correlation_id="membership")
        return placed["params"], placed["opt_state"]

    @classmethod
    def join_cluster(cls, trainer_factory, directory, coordinator,
                     mesh_factory, init_params, timeout=None, **kw):
        """JOINER bootstrap: announce on the KV, wait for the running
        cluster to agree and admit at a step boundary, build the
        trainer on the NEW mesh (`trainer_factory(mesh)`), warm-start
        from the drain checkpoint the members wrote at that boundary
        (encoder stacks re-stacked to the new dp width), and adopt the
        members' step / round / barrier counters so lockstep agreement
        holds from the first step. Returns (runner, params, opt_state).

        Raises the typed `MembershipChangeError` (announcement
        withdrawn, cluster untouched) when admission fails or the
        `host.join` fault fires."""
        m = ElasticMembership(coordinator,
                              members=[coordinator.process_id])
        m.announce_join()
        if _faults.ACTIVE is not None:
            try:
                _faults.ACTIVE.fire(_faults.HOST_JOIN)
            except BaseException as e:
                m.abandon(joins=[coordinator.process_id])
                raise MembershipChangeError(
                    f"join aborted before admission — announcement "
                    f"withdrawn, cluster roster untouched: {e}") from e
        info = m.await_admission(timeout=timeout)
        trainer = trainer_factory(mesh_factory(list(m.members)))
        coordinator.step = int(info.get("cstep") or 0)
        coordinator.rounds = int(info.get("rounds") or 0)
        runner = cls(trainer, directory, coordinator, elastic=True,
                     mesh_factory=mesh_factory, membership=m, **kw)
        runner._save_seq = int(info.get("save_seq") or 0)
        g = runner.guardian
        if g is not None:
            g._flushes = int(info.get("flushes") or 0)
            if hasattr(g, "rollbacks"):
                g.rollbacks = int(info.get("rollbacks") or 0)
        params, opt_state = runner.trainer.init(init_params)
        like = {"params": params, "opt_state": opt_state}
        step = int(info.get("step") or 0)
        s, placed = runner._restore_restacked(
            step if step > 0 else None, like, info.get("dp"),
            verified_scan=step <= 0)
        if s is not None:
            runner.step = int(s)
            runner._note_resume()
            params, opt_state = placed["params"], placed["opt_state"]
        return runner, params, opt_state

    # -- the step --------------------------------------------------------
    def _on_sync(self, coordinator):
        """Sync-point piggyback: refresh the compression wire telemetry
        at flush cadence (never per step). The per-sync encoded-bytes
        figure rides the NEXT heartbeat + cluster metrics snapshot via
        `coordinator.stats_extra`, giving the process-0 peer table its
        per-peer exchange-bytes column."""
        opt_state = getattr(self, "_last_opt_state", None)
        if opt_state is not None and \
                getattr(self.trainer, "compress", False):
            try:
                host = self.trainer.encoder_stats(opt_state)
                if host is not None:
                    coordinator.stats_extra["exchange_bytes"] = \
                        host["encoded_bytes"]
                    if "wire_bytes" in host:
                        coordinator.stats_extra["wire_bytes"] = \
                            host["wire_bytes"]
            except Exception:  # noqa: BLE001 — telemetry is best-effort
                pass

    def fit_batch(self, params, opt_state, batch, rng=None):
        """One coordinated step: dispatch (with peer autopsy on
        collective failure), guardian escalation consumption, the
        preemption drain, and the periodic coordinated save. `rng`
        defaults to `fold_in(root, step)` so kill/resume replays the
        exact key stream."""
        if rng is None:
            rng = jax.random.fold_in(self.root_rng, self.step)
        self._last_opt_state = opt_state
        try:
            params, opt_state, loss = self.trainer.fit_batch(
                params, opt_state, batch, rng)
        except PeerLostError as e:
            if not self.elastic:
                raise
            # survivors re-form on the reduced roster instead of dying
            # with the peer; the batch is dropped (its buffers may be
            # donated) and loss is None — the caller re-batches on the
            # NEW trainer.mesh at the (possibly rewound) runner.step
            params, opt_state = self._replace_lost(params, opt_state, e)
            return params, opt_state, None
        self._last_opt_state = opt_state
        self.step += 1
        g = self.guardian
        if g is not None:
            act = g.take_action()
            # functional style: the batch's buffers were donated, so the
            # RETRY rung cannot literally re-run it — the reduced
            # lr_scale applies from the next step (the guarded step
            # already refused the bad update); ROLLBACK restores the
            # newest verified generation on every host
            if act == _guardian.ROLLBACK:
                params, opt_state = self._rollback(g, params, opt_state)
        d = self.coordinator.take_decision()
        if d == _coord.PREEMPT:
            saved = self._save(params, opt_state, wait=True)
            raise PreemptionSignal(
                (f"coordinated drain complete at step {self.step} — "
                 f"checkpoint written and verified; exit and resume")
                if saved else
                (f"coordinated drain at step {self.step} — the guardian "
                 f"could not vouch for the params, so NO drain "
                 f"checkpoint was written; resume falls back to the "
                 f"last verified generation"),
                step=self.step)
        if d == _coord.REFORM and self.elastic:
            delta = self.coordinator.take_reform()
            if delta is not None:
                params, opt_state = self._reform(params, opt_state,
                                                 delta)
                if params is None:
                    raise PreemptionSignal(
                        f"graceful leave complete at step {self.step} "
                        f"— final state drained to a checkpoint and "
                        f"the survivors re-formed without this host",
                        step=self.step)
                # the re-form just drain-saved THIS step; a second
                # periodic save would advance _save_seq past the
                # joiner's adopted ticket value and fence on a member
                # that is still warm-starting
                return params, opt_state, loss
        if self.step % self.save_every == 0:
            self._save(params, opt_state, wait=False)
        return params, opt_state, loss

    def finalize(self, params=None, opt_state=None):
        """Final synchronous coordinated save + close."""
        try:
            if params is not None:
                self._save(params, opt_state, wait=True)
        finally:
            self.close()

    def close(self):
        c = self.coordinator
        c.stop_monitor()
        c.driver_attached = False
        c.on_sync = None
        c.bind(None)
        c.uninstall()
        if self._prev_signals:
            import signal as _signal
            for s, h in self._prev_signals.items():
                try:
                    _signal.signal(s, h)
                except (ValueError, TypeError):
                    pass
            self._prev_signals = None
        if self.guardian is not None:
            self.guardian.driver_attached = False
            self.guardian.bind(None)
            self.guardian.uninstall()
        self.ckpt.close()
