"""ZeRO-1 optimizer-state sharding over the data-parallel axis.

The reference's data-parallel trainers (deeplearning4j-parallel-wrapper ::
parallelism.ParallelWrapper, dl4j-spark :: SharedTrainingMaster) replicate
the full updater state on every worker. On TPU the updater state for a
large model (Adam: 2 fp32 moments + fp32 master = 12 bytes/param) is the
dominant per-chip memory cost of data parallelism — ZeRO stage 1
(Rajbhandari et al. 2019, arXiv:1910.02054) shards it across the dp axis
instead.

TPU-native inversion: no gradient bucketing or hand-written
reduce-scatter. Each optimizer-state leaf is placed with a NamedSharding
that splits its largest dp-divisible axis; parameters stay replicated.
Inside the SAME jitted train step GSPMD then partitions the update math
by the state sharding and inserts the reduce-scatter (for the gradient
slice each device consumes) and the all-gather (to rebuild replicated
updated params) as ICI collectives — the step stays ONE XLA program and
the memory for moments drops by ~dp×.

Usage:
    pw = (ParallelWrapper.Builder(net).workers(8)
          .shardOptimizerState(True).build())
    pw.fit(iterator)
or directly:
    opt_state = shard_optimizer_state(opt_state, mesh, axis="dp")
"""
from __future__ import annotations

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def _leaf_spec(shape, n, axis):
    """PartitionSpec for one state leaf: the largest axis whose size is
    divisible by `n` is sharded over mesh axis `axis`; P() (replicated)
    when no axis qualifies (small/scalar leaves)."""
    best = -1
    for d, s in enumerate(shape):
        if s % n == 0 and s >= n and (best < 0 or s > shape[best]):
            best = d
    if best < 0:
        return P()
    spec = [None] * len(shape)
    spec[best] = axis
    return P(*spec)


def shard_optimizer_state(opt_state, mesh, axis="dp"):
    """Place every array leaf of an optax state tree with its largest
    dp-divisible axis sharded over `axis`; everything else replicated.

    mesh: DeviceMesh or jax.sharding.Mesh."""
    jmesh = getattr(mesh, "mesh", mesh)
    n = dict(zip(jmesh.axis_names, jmesh.devices.shape))[axis]

    def place(leaf):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            return leaf
        sh = NamedSharding(jmesh, _leaf_spec(shape, n, axis))
        return jax.device_put(leaf, sh)

    return jax.tree_util.tree_map(place, opt_state)


def state_memory_bytes(opt_state):
    """Total bytes of the state tree as addressed on THIS process — with
    ZeRO sharding each process holds ~1/dp of the replicated size."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(opt_state):
        if hasattr(leaf, "addressable_shards"):
            total += sum(s.data.nbytes for s in leaf.addressable_shards)
        elif hasattr(leaf, "nbytes"):
            total += leaf.nbytes
    return total
