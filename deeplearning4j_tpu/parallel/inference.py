"""ParallelInference (≡ deeplearning4j-parallel-wrapper ::
inference.ParallelInference) — high-throughput shared-model inference.

The reference keeps a pool of model replicas on worker threads and a
batching queue in front of them (BATCHED mode: requests are coalesced up
to batchLimit before a forward pass). TPU-native inversion: the model is
ONE jitted executable that any thread may call (pure function of params),
so replicas are pointless — the value is in the coalescing. A collector
thread drains the request queue, groups compatible shapes, pads the
batch dim to a power-of-two bucket (static shapes → no fresh XLA
compiles per request count), runs a single forward, and scatters the
rows back to their futures.

Graceful degradation (resilience/): callers NEVER block indefinitely.
- `output(x, timeout_ms=...)` enforces a per-request deadline — expiry
  cancels the request and raises `InferenceTimeoutError`;
- enqueue is bounded: a queue that stays full for `enqueue_timeout_ms`
  sheds the request with `InferenceOverloadedError` instead of blocking;
- a dead collector thread is restarted behind a `CircuitBreaker` —
  repeated deaths OPEN the breaker and requests are served directly
  (degraded, uncoalesced) until the cooldown's half-open probe brings
  the collector back;
- `shutdown()` is idempotent and drains the queue clean.
Sheds, timeouts, and restarts count through `monitoring/`
(`dl4j.resilience.inference_*` / `collector_restarts`).

Serving-grade AOT path (runtime/executables.py): configuring a bucket
ladder switches dispatch from the live `model.output` trace to
ahead-of-time compiled executables — one per bucketed input signature,
warmed at startup (`warmup()`), persisted/restored through the
versioned on-disk executable cache (`DL4J_EXEC_CACHE`). Steady state
is then enqueue → pad-to-bucket → dispatch with ZERO jit traces, ZERO
XLA compiles and zero host-owned aliasing (inputs enter the device
through `StagingRing`/`xla_owned_copy` and are donated). Oversized
batches split across max-bucket chunks (µ-cuDNN micro-batching)
instead of compiling a novel shape; for sequence models a length
ladder pads the time axis under a validity mask. Any AOT-path failure
counts `dl4j.serving.aot_fallbacks` and OPENS a half-open circuit
breaker: dispatch degrades to the legacy live path for the cooldown,
then ONE probe re-tries the AOT path — success restores zero-trace
steady state, failure re-opens for another cooldown. Serving never
goes down over a cache problem, and a transient cache problem never
permanently costs the AOT fast path.

Usage parity:
    pi = (ParallelInference.Builder(net)
          .inferenceMode(InferenceMode.BATCHED)
          .batchLimit(32).queueLimit(256).build())
    out = pi.output(x)                    # thread-safe, blocks
    out = pi.output(x, timeout_ms=50)     # bounded wait
    pi.shutdown()

Low-latency serving:
    pi = (ParallelInference.Builder(net)
          .bucketLadder([1, 2, 4, 8, 16])     # batch buckets
          .executableCacheDir("/var/dl4j/exec")
          .build())
    pi.warmup()                           # ladder pre-compiled/loaded
    out = pi.output(x)                    # zero-compile steady state
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.monitoring import requests as _req
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.errors import (InferenceOverloadedError,
                                                  InferenceTimeoutError)
from deeplearning4j_tpu.resilience.policy import CircuitBreaker


class InferenceMode:
    SEQUENTIAL = "SEQUENTIAL"   # direct call, no queue
    BATCHED = "BATCHED"         # coalesce requests up to batchLimit
    INPLACE = "INPLACE"         # reference alias: shared model, no copy —
    #                             identical to BATCHED here (the jitted
    #                             executable is already shared and pure)


def _bucket(n):
    b = 1
    while b < n:
        b *= 2
    return b


def bounded_enqueue(q, item, deadline, enqueue_timeout, count_timeout=None,
                    what="inference"):
    """Bounded admission shared by ParallelInference and the generation
    subsystem's GenerationServer: wait at most `enqueue_timeout` seconds
    (clipped to the caller's deadline) for queue space, then SHED with
    `InferenceOverloadedError` — callers never block indefinitely. A
    caller deadline that expires while waiting raises
    `InferenceTimeoutError` instead (callers retry on overloaded, not
    on timeout); `count_timeout` lets the owner count that case on its
    own metric."""
    wait = enqueue_timeout
    if deadline is not None:
        wait = min(wait, max(0.0, deadline - time.monotonic()))
    try:
        if wait > 0:
            q.put(item, timeout=wait)
        else:
            q.put_nowait(item)
    except queue.Full:
        if deadline is not None and time.monotonic() >= deadline:
            if count_timeout is not None:
                count_timeout()
            raise InferenceTimeoutError(
                f"{what} request deadline expired while waiting "
                "for queue space") from None
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.RESILIENCE_INFERENCE_SHED,
                help="requests shed because the queue stayed full "
                     "for the whole bounded enqueue wait").inc()
        raise InferenceOverloadedError(
            f"{what} queue full (limit {q.maxsize}) "
            f"after {wait * 1e3:.6g} ms — request shed") from None


class _Request:
    __slots__ = ("x", "event", "result", "error", "claimed", "cancelled",
                 "server", "timeline")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.claimed = False
        self.cancelled = False  # deadline expired: discard, never serve
        self.server = None      # thread that claimed it (set under lock)
        self.timeline = None    # request trace (monitoring/requests.py)


class ParallelInference:
    def __init__(self, model, inference_mode=InferenceMode.BATCHED,
                 batch_limit=32, queue_limit=256, collect_timeout_ms=2.0,
                 enqueue_timeout_ms=100.0, breaker=None,
                 bucket_ladder=None, length_buckets=None,
                 exec_cache_dir=None, staging_depth=2,
                 aot_breaker=None):
        self.model = model
        self.mode = inference_mode
        # AOT serving: a configured ladder closes the shape vocabulary
        # and switches dispatch to pre-compiled executables; admission
        # then coalesces up to the ladder's top bucket by default
        self._ladder = None
        self._length_buckets = length_buckets  # warmup()'s default ladder
        if bucket_ladder is not None:
            from deeplearning4j_tpu.runtime.executables import BucketLadder
            self._ladder = (bucket_ladder
                            if isinstance(bucket_ladder, BucketLadder)
                            else BucketLadder(batch=bucket_ladder,
                                              length=length_buckets))
            batch_limit = self._ladder.max_batch
        self.batch_limit = int(batch_limit)
        self.collect_timeout = collect_timeout_ms / 1e3
        self.enqueue_timeout = enqueue_timeout_ms / 1e3
        self.model_calls = 0          # diagnostic: forwards actually run
        self.collector_restarts = 0   # diagnostic: breaker-guarded revives
        self.collector_error = None   # last error that killed a collector
        self._restart_unconfirmed = False   # revive awaiting 1st success
        self._exec_cache_dir = exec_cache_dir
        self._staging_depth = int(staging_depth)
        self._store = None            # ExecutableStore, built lazily
        self._ring = None             # StagingRing, built with the store
        self._aot_error = None        # last AOT failure (diagnostic)
        # AOT-path breaker: ONE dispatch failure opens it (serve legacy
        # during the cooldown), the half-open probe re-tries the AOT
        # path — a transient cache problem never permanently costs the
        # zero-compile fast path
        self._aot_breaker = aot_breaker or CircuitBreaker(
            failure_threshold=1, cooldown=30.0, name="inference.aot")
        self._queue = queue.Queue(maxsize=int(queue_limit))
        self._claim_lock = threading.Lock()
        self._lifecycle_lock = threading.Lock()   # restart + shutdown
        self._breaker = breaker or CircuitBreaker(
            failure_threshold=3, cooldown=5.0, name="inference.collector")
        self._last_dead = None    # thread whose death was already recorded
        self._shutdown = False
        self._thread = None
        if self.mode != InferenceMode.SEQUENTIAL:
            self._thread = self._start_collector()

    def _start_collector(self):
        t = threading.Thread(target=self._collector_main, daemon=True)
        t.start()
        return t

    def _collector_main(self):
        try:
            self._collector()
        except BaseException as e:  # noqa: BLE001 — thread is dying anyway
            # remember why (surfaced by the revive path / diagnostics)
            # instead of spewing a default thread traceback; waiting
            # clients detect the death and revive or degrade
            self.collector_error = e

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def inferenceMode(self, mode):
            self._kw["inference_mode"] = mode
            return self

        def batchLimit(self, n):
            self._kw["batch_limit"] = int(n)
            return self

        def queueLimit(self, n):
            self._kw["queue_limit"] = int(n)
            return self

        def enqueueTimeoutMs(self, ms):
            """How long output() may wait for queue space before shedding
            with InferenceOverloadedError."""
            self._kw["enqueue_timeout_ms"] = float(ms)
            return self

        def breaker(self, breaker):
            """Circuit breaker guarding collector-thread restarts."""
            self._kw["breaker"] = breaker
            return self

        def aotBreaker(self, breaker):
            """Circuit breaker guarding the AOT dispatch path: a
            failure opens it (legacy serving during cooldown), the
            half-open probe re-tries AOT."""
            self._kw["aot_breaker"] = breaker
            return self

        def bucketLadder(self, buckets):
            """Batch-bucket ladder (list of ints or a BucketLadder):
            switches dispatch to AOT pre-compiled executables, one per
            bucketed signature. batchLimit defaults to the top rung."""
            self._kw["bucket_ladder"] = buckets
            return self

        def lengthBuckets(self, buckets):
            """Sequence-length ladder: recurrent inputs pad their time
            axis to the smallest admitting rung under a validity mask."""
            self._kw["length_buckets"] = buckets
            return self

        def executableCacheDir(self, path):
            """On-disk AOT executable cache root (default
            $DL4J_EXEC_CACHE): a restarted replica warmup()s by
            deserializing, not compiling."""
            self._kw["exec_cache_dir"] = path
            return self

        def stagingDepth(self, n):
            """Device input staging-ring depth (how many dispatches of
            inputs may be staged ahead, default 2)."""
            self._kw["staging_depth"] = int(n)
            return self

        def workers(self, *_):
            return self  # one jitted executable serves all threads

        def build(self):
            return ParallelInference(self._model, **self._kw)

    # -- client side -----------------------------------------------------
    def output(self, x, timeout_ms=None):
        """Thread-safe inference. x: one example (features without batch
        dim) or a batch; for multi-input ComputationGraphs a LIST/TUPLE
        with one array per model input (coalesced per-input). Returns the
        model output with matching leading dims.

        timeout_ms bounds the WHOLE call (enqueue + wait): expiry cancels
        the request and raises InferenceTimeoutError. A full queue that
        stays full past the bounded enqueue wait sheds the request with
        InferenceOverloadedError — callers never block indefinitely.
        Direct (SEQUENTIAL / degraded / post-shutdown) forwards run
        synchronously and cannot be interrupted mid-flight: the deadline
        is enforced after the forward, so the worst-case latency of a
        timed-out direct call is one model forward."""
        if _mon.enabled():
            _mon.get_registry().counter(
                "dl4j.inference.requests",
                help="ParallelInference.output calls").inc()
        n_inputs = len(self._input_ranks())
        if isinstance(x, (list, tuple)) and n_inputs > 1:
            if len(x) != n_inputs:
                raise ValueError(
                    f"model has {n_inputs} inputs but output() got "
                    f"{len(x)} arrays")
            multi = True
            xs = tuple(np.asarray(a, np.float32) for a in x)
        else:
            # single-input model: a list of rows is just a batch
            multi = False
            xs = (np.asarray(x, np.float32),)
        single = self._needs_batch(xs)
        if single:
            xs = tuple(a[None] for a in xs)
        deadline = None if timeout_ms is None \
            else time.monotonic() + float(timeout_ms) / 1e3
        # request-scoped tracing: one bounded timeline per request (None
        # when monitoring is off — every append below is one branch);
        # the request-latency histogram keeps EXEMPLAR trace ids so a
        # bad p99 on /metrics links to a concrete timeline on /requests
        tl = _req.start("inference", meta={"rows": int(xs[0].shape[0])})
        t_req = time.perf_counter()
        try:
            out = self._output_traced(xs, multi, single, deadline,
                                      timeout_ms, tl)
        except InferenceTimeoutError:
            if tl is not None:
                tl.event("timeout")
                tl.finish("timeout")
            raise
        except InferenceOverloadedError:
            if tl is not None:
                tl.event("shed")
                tl.finish("shed")
            raise
        except BaseException as e:
            if tl is not None:
                tl.event("failed", error=type(e).__name__)
                tl.finish("error")
            raise
        if tl is not None:
            tl.event("done")
            tl.finish("ok")
            if _mon.enabled():
                _mon.get_registry().histogram(
                    _mon.INFERENCE_REQUEST_MS,
                    help="end-to-end inference request latency "
                         "(enqueue to delivery)").observe(
                    (time.perf_counter() - t_req) * 1e3,
                    trace_id=tl.trace_id)
        return out

    def _output_traced(self, xs, multi, single, deadline, timeout_ms,
                       tl):
        if self.mode == InferenceMode.SEQUENTIAL or self._shutdown:
            return self._direct_deadline(xs, multi, single, deadline)
        if self._thread is not None and not self._thread.is_alive():
            # dead collector noticed up front: revive (breaker willing)
            # or serve this request directly — no pointless queue wait
            if not self._revive_collector():
                return self._direct_deadline(xs, multi, single, deadline)
        req = _Request(xs)
        req.timeline = tl
        if tl is not None:
            tl.event("enqueue", queued=self._queue.qsize())
        self._enqueue(req, deadline)
        degraded = False
        while not req.event.is_set():
            wait = 0.25
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._cancel(req)
                    raise InferenceTimeoutError(
                        f"inference request missed its "
                        f"{float(timeout_ms):.6g} ms deadline")
                wait = min(wait, remaining)
            if req.event.wait(wait):
                break
            dead = self._thread is not None and not self._thread.is_alive()
            if dead and not self._shutdown:
                # breaker-guarded revive; False → breaker OPEN, serve
                # this request directly (degraded but live)
                if self._revive_collector():
                    continue
                degraded = True
            if self._shutdown or (dead and degraded):
                with self._claim_lock:
                    # reclaim an unclaimed request, or one whose claiming
                    # THREAD died before delivering (a claim held by a live
                    # thread — e.g. shutdown()'s drain — stays theirs, so a
                    # request is never served twice)
                    orphaned = (req.claimed and req.server is not None
                                and not req.server.is_alive()
                                and not req.event.is_set())
                    mine = not req.claimed or orphaned
                    req.claimed = True
                    if mine:
                        req.server = threading.current_thread()
                if mine:
                    self._run([req])  # forward OUTSIDE the lock
                # else a live thread claimed it: keep waiting below
        if req.error is not None:
            raise req.error
        if deadline is not None and time.monotonic() > deadline:
            # result landed after the deadline (e.g. a degraded direct
            # serve that outran the budget): honour the contract
            self._count_timeout()
            raise InferenceTimeoutError(
                f"inference request missed its "
                f"{float(timeout_ms):.6g} ms deadline (late result "
                "discarded)")
        if self._restart_unconfirmed and not degraded:
            # the FIRST queued result after a restart proves the revived
            # collector is healthy: close the breaker exactly once (a
            # permanent every-request record_success would also zero the
            # failure count between deaths, so a flapping collector
            # could never trip to degraded mode)
            self._restart_unconfirmed = False
            self._breaker.record_success()
        return req.result[0] if single else req.result

    def _direct(self, xs, multi, single):
        self.model_calls += 1
        out = self.model.output(list(xs) if multi else xs[0])
        out = (out[0] if isinstance(out, list) else out).numpy()
        return out[0] if single else out

    def _direct_deadline(self, xs, multi, single, deadline):
        """Direct serve with the deadline enforced AFTER the forward
        (a synchronous jitted call cannot be interrupted mid-flight)."""
        out = self._direct(xs, multi, single)
        if deadline is not None and time.monotonic() > deadline:
            self._count_timeout()
            raise InferenceTimeoutError(
                "inference request missed its deadline (direct forward "
                "finished late; result discarded)")
        return out

    def _count_timeout(self):
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.RESILIENCE_INFERENCE_TIMEOUTS,
                help="requests cancelled at their deadline").inc()

    def _enqueue(self, req, deadline):
        # the caller's deadline — not the enqueue budget — expiring
        # while waiting for space is a timeout, not a shed (callers
        # retry on overloaded, not timeout)
        bounded_enqueue(self._queue, req, deadline, self.enqueue_timeout,
                        count_timeout=self._count_timeout)

    def _cancel(self, req):
        """Deadline expiry: mark the request so no thread serves it (or,
        if already in flight, so its late result is discarded)."""
        with self._claim_lock:
            req.cancelled = True
            req.claimed = True
        self._count_timeout()

    def _revive_collector(self):
        """Restart a dead collector behind the circuit breaker. Each
        distinct thread death records ONE breaker failure (not one per
        waiting caller); when the breaker is OPEN the restart is shed
        and the caller degrades to direct serving. Returns True when a
        live collector exists after the call."""
        with self._lifecycle_lock:
            if self._shutdown:
                return False
            t = self._thread
            if t is None or t.is_alive():
                return True
            if t is not self._last_dead:
                self._last_dead = t
                self._breaker.record_failure()
            if not self._breaker.allow():
                return False
            self._thread = self._start_collector()
            self.collector_restarts += 1
            self._restart_unconfirmed = True
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.RESILIENCE_COLLECTOR_RESTARTS,
                help="collector threads restarted after death").inc()
        return True

    def _input_ranks(self):
        want = getattr(self.model, "_input_ranks", None)
        if want is None:
            want = self._infer_input_ranks()
            self.model._input_ranks = want
        return want

    def _needs_batch(self, xs):
        """True when xs holds ONE example (no batch dim): the FIRST
        input's rank equals the model's expected feature rank."""
        return xs[0].ndim == self._input_ranks()[0]

    def _infer_input_ranks(self):
        """Expected FEATURE rank (no batch dim) per model input."""
        from deeplearning4j_tpu.nn.conf.inputs import (ConvolutionalType,
                                                       RecurrentType)

        def rank(it):
            if isinstance(it, ConvolutionalType):
                return 3
            if isinstance(it, RecurrentType):
                return 2
            return 1

        conf = getattr(self.model, "conf", None)
        if conf is not None:
            node_types = getattr(conf, "node_output_types", None)
            input_names = getattr(conf, "input_names", None)
            if node_types and input_names:
                return [rank(node_types.get(n)) for n in input_names]
            return [rank(getattr(conf, "input_type", None))]
        return [1]

    # -- collector thread ------------------------------------------------
    def _collector(self):
        while not self._shutdown:
            # fault site OUTSIDE the per-batch try: a fault here kills
            # the collector thread (the auto-restart path under test)
            if _faults.ACTIVE is not None:
                _faults.ACTIVE.fire(_faults.INFERENCE_COLLECTOR)
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is None:
                break
            batch = [first]
            strays = []    # incompatible shapes: run AFTER the main batch
            total = first.x[0].shape[0]
            # continuous batching: admit queued requests into the next
            # dispatch up to the bucket boundary (with a ladder,
            # batch_limit IS the top bucket) or a brief quiet period;
            # whatever arrives during the dispatch queues for the next
            while total < self.batch_limit:
                try:
                    nxt = self._queue.get(timeout=self.collect_timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    self._shutdown = True
                    break
                if self._incompatible(nxt, first):
                    strays.append(nxt)
                    continue
                batch.append(nxt)
                total += nxt.x[0].shape[0]
            self._dispatch(batch)
            for s in strays:
                self._dispatch([s])

    def _incompatible(self, nxt, first):
        """Can nxt coalesce into first's dispatch? Exact feature-shape
        match normally; under a length ladder, sequence inputs may
        differ in their time axis (axis 1) — they pad to one length
        bucket under a validity mask. The tolerance applies only when
        the FIRST input is the sequence (mirroring _serve_aot, which
        derives the mask and length bucket from input 0): a model
        whose sequence input is elsewhere falls back to exact-shape
        coalescing, so mismatched-T requests become strays and serve
        individually at their native shapes instead of producing an
        un-concatenatable batch."""
        if len(nxt.x) != len(first.x):
            return True
        seq_ok = (self._ladder is not None
                  and self._ladder.length is not None
                  and first.x[0].ndim == 3 and nxt.x[0].ndim == 3)
        for a, b in zip(nxt.x, first.x):
            if a.shape[1:] == b.shape[1:]:
                continue
            if not (seq_ok and a.ndim == 3 and b.ndim == 3
                    and a.shape[2:] == b.shape[2:]):
                return True
        return False

    def _dispatch(self, batch):
        """Claim-then-run: a request the fallback path already claimed
        (shutdown race) or that was cancelled at its deadline must not
        be served (twice / at all)."""
        with self._claim_lock:
            batch = [r for r in batch if not r.claimed and not r.cancelled]
            me = threading.current_thread()
            for r in batch:
                r.claimed = True
                r.server = me
        if batch:
            self._run(batch)

    def _run(self, batch):
        try:
            for r in batch:
                if r.timeline is not None:
                    r.timeline.event("dispatch",
                                     rows=int(r.x[0].shape[0]),
                                     coalesced=len(batch))
            if _faults.ACTIVE is not None:
                _faults.ACTIVE.fire(_faults.INFERENCE_FORWARD)
            if self._ladder is not None and self._aot_breaker.allow():
                try:
                    self._serve_aot(batch)
                    self._aot_breaker.record_success()
                    return
                except Exception as e:  # noqa: BLE001 — degrade, stay up
                    self._note_aot_fallback(e)
            self._serve_legacy(batch)
        except BaseException as e:  # noqa: BLE001 — deliver to the waiter
            # even KeyboardInterrupt/SystemExit must release the waiters
            # before propagating, or output() blocks forever
            err = e if isinstance(e, Exception) else RuntimeError(
                f"inference worker died: {type(e).__name__}: {e}")
            for r in batch:
                r.error = err
                r.event.set()
            if not isinstance(e, Exception):
                raise

    def _serve_legacy(self, batch):
        """Live-trace path (no ladder configured, or AOT disabled after
        a failure): one eager `model.output` per coalesced batch, batch
        dim padded to the next power of two."""
        n_inputs = len(batch[0].x)
        cols = []
        for j in range(n_inputs):
            xj = np.concatenate([r.x[j] for r in batch], axis=0)
            cols.append(xj)
        n = cols[0].shape[0]
        nb = _bucket(n)
        if nb != n:
            # pad with copies of the last row: static bucket shapes
            # keep XLA from compiling one executable per request count
            cols = [np.concatenate(
                [xj, np.repeat(xj[-1:], nb - n, axis=0)], axis=0)
                for xj in cols]
        self.model_calls += 1
        if _mon.enabled():
            reg = _mon.get_registry()
            reg.counter("dl4j.inference.forwards",
                        help="coalesced forward passes").inc()
            reg.histogram(
                "dl4j.inference.batch_rows",
                help="rows per coalesced forward (pre-padding)"
            ).observe(n)
            _mon.record_transfer(sum(c.nbytes for c in cols))
        with _mon.span("inference.forward"):
            out = self.model.output(cols if n_inputs > 1 else cols[0])
            out = (out[0] if isinstance(out, list)
                   else out).numpy()[:n]
        i = 0
        for r in batch:
            k = r.x[0].shape[0]
            r.result = out[i:i + k]
            i += k
            r.event.set()

    # -- AOT serving path (runtime/executables.py) ------------------------
    def warmup(self, buckets=None, lengths=None, example=None):
        """Pre-resolve the whole bucket ladder at startup, so steady
        state never compiles: every ladder signature is deserialized
        from the on-disk executable cache (warm replica: seconds) or
        live-compiled once and persisted (cold cache: pays today what
        the request path would have paid per shape).

        `buckets`/`lengths` (re)configure the ladder; with neither
        given nor a Builder ladder, a power-of-two ladder up to
        batchLimit is installed. Per-input feature shapes come from
        `example` (one example or a batch, like output()) or from the
        model's InputType conf. Returns the warmup stats dict
        {compiled, from_disk, seconds, signatures}. A successful
        warmup closes the AOT breaker: the operator just proved the
        executable layer works, so dispatch goes straight back to the
        zero-compile path without waiting out a cooldown."""
        from deeplearning4j_tpu.runtime.executables import BucketLadder
        if buckets is not None or self._ladder is None:
            if buckets is None:
                b, ladder = 1, []
                while b < self.batch_limit:
                    ladder.append(b)
                    b *= 2
                buckets = ladder + [self.batch_limit]
            self._ladder = BucketLadder(
                batch=buckets,
                length=(lengths if lengths is not None
                        else (self._ladder.length if self._ladder
                              else self._length_buckets)))
            self.batch_limit = self._ladder.max_batch
        elif lengths is not None:
            self._ladder = BucketLadder(batch=self._ladder.batch,
                                        length=lengths)
        store, _ = self._ensure_aot()
        shapes = self._warmup_shapes(example)
        sigs = []
        for b in self._ladder.batch:
            for feats in shapes:
                sig = tuple(((b,) + tuple(shp), "float32")
                            for shp in feats)
                # mirror _serve_aot exactly: masked iff a length ladder
                # is set and the FIRST input is a (B, T, F) sequence
                with_mask = (self._ladder.length is not None
                             and len(sig[0][0]) == 3)
                sigs.append((sig, with_mask))
        stats = store.warmup(sigs)
        stats["signatures"] = len(sigs)
        self._aot_breaker.record_success()
        return stats

    def _warmup_shapes(self, example):
        """Per-input FEATURE shape lists to warm: [[shape_per_input]].
        From an example request (preferred — exact), else from the
        conf's InputTypes; sequence inputs expand across the length
        ladder (their conf length is often None/variable)."""
        if example is not None:
            n_inputs = len(self._input_ranks())
            if isinstance(example, (list, tuple)) and n_inputs > 1:
                xs = tuple(np.asarray(a, np.float32) for a in example)
            else:
                xs = (np.asarray(example, np.float32),)
            if self._needs_batch(xs):
                feats = [tuple(a.shape) for a in xs]
            else:
                feats = [tuple(a.shape[1:]) for a in xs]
        else:
            feats = [tuple(t.shape())
                     for t in self._input_types()]
        if self._ladder.length is None:
            if any(d is None for shp in feats for d in shp):
                raise ValueError(
                    f"cannot warm variable-length inputs {feats} "
                    "without length buckets; pass lengths=[...] or an "
                    "example")
            return [feats]
        out = []
        for tb in self._ladder.length:
            row = []
            for shp in feats:
                if len(shp) == 2:   # recurrent (time, features)
                    row.append((tb, shp[1]))
                else:
                    row.append(shp)
            out.append(row)
        return out

    def _input_types(self):
        """InputType conf objects, one per model input."""
        conf = getattr(self.model, "conf", None)
        if conf is None:
            raise ValueError("model has no conf: pass warmup(example=)")
        node_types = getattr(conf, "node_output_types", None)
        input_names = getattr(conf, "input_names", None)
        if node_types and input_names:
            return [node_types[n] for n in input_names]
        it = getattr(conf, "input_type", None)
        if it is None or not hasattr(it, "shape"):
            raise ValueError(
                "model conf has no sized InputType: pass "
                "warmup(example=)")
        return [it]

    def _ensure_aot(self):
        """Build the executable store + staging ring once (lazily, so a
        Builder-configured instance pays nothing until first use).
        Double-checked: the steady-state dispatch takes no lock."""
        store = self._store
        if store is not None:
            return store, self._ring
        with self._lifecycle_lock:
            if self._store is None:
                from deeplearning4j_tpu.runtime.executables import (
                    ExecutableStore, StagingRing)
                # ring BEFORE store: the unlocked fast path keys on
                # _store, so _ring must already be visible then
                self._ring = StagingRing(self._staging_depth)
                self._store = ExecutableStore(
                    self.model, directory=self._exec_cache_dir)
        return self._store, self._ring

    def _note_aot_fallback(self, e):
        """An AOT dispatch failure opens the breaker: serving degrades
        to the legacy live path for the cooldown (availability beats
        executable-cache purity), then the half-open probe re-tries the
        AOT path — zero-trace steady state comes back on its own once
        the cause clears."""
        self._aot_error = e
        self._aot_breaker.record_failure()
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.SERVING_AOT_FALLBACKS,
                help="AOT serving failures (breaker-guarded fallback "
                     "to the legacy live path)").inc()

    def _serve_aot(self, batch):
        """Steady-state serving: pad-to-bucket, stage XLA-owned input
        buffers, dispatch pre-compiled executables. No jit, no trace,
        no host-owned aliasing; oversized batches split across
        max-bucket chunks. Results are delivered only after EVERY chunk
        dispatched, so a mid-batch failure can still fall back to the
        legacy path without double-serving."""
        if _faults.ACTIVE is not None:
            _faults.ACTIVE.fire(_faults.SERVING_DISPATCH)
        store, ring = self._ensure_aot()
        ladder = self._ladder
        n_inputs = len(batch[0].x)
        with_mask = (ladder.length is not None
                     and batch[0].x[0].ndim == 3)
        if with_mask:
            # one length bucket covers EVERY sequence input (a second
            # rank-3 input longer than input 0 must not overflow tb)
            tb = ladder.length_bucket(
                max(r.x[j].shape[1] for r in batch
                    for j in range(n_inputs) if r.x[j].ndim == 3))
            cols, mask = self._pad_time(batch, n_inputs, tb)
        else:
            cols = [np.concatenate([r.x[j] for r in batch], axis=0)
                    for j in range(n_inputs)]
            mask = None
        n = cols[0].shape[0]
        chunks = ladder.chunks(n)
        mon_on = _mon.enabled()
        pending = []
        i = 0
        for c in chunks:
            b = ladder.bucket(c)
            pad = b - c
            ccols = [col[i:i + c] for col in cols]
            if pad:
                # pad with copies of the last row (numerically inert:
                # padded rows are sliced away before delivery)
                ccols = [np.concatenate(
                    [xj, np.repeat(xj[-1:], pad, axis=0)], axis=0)
                    for xj in ccols]
            sig = tuple((tuple(xj.shape), str(xj.dtype)) for xj in ccols)
            entry = store.lookup(sig, with_mask)
            if entry is None:
                # miss path: deserialize from disk or live-compile —
                # never reached once warmup() covered the ladder
                entry = store.load_or_compile(sig, with_mask=with_mask)
            arrays = ccols
            if with_mask:
                cmask = mask[i:i + c]
                if pad:
                    cmask = np.concatenate(
                        [cmask, np.zeros((pad, cmask.shape[1]),
                                         np.float32)], axis=0)
                arrays = ccols + [cmask]
            self.model_calls += 1
            if mon_on:
                reg = _mon.get_registry()
                reg.counter("dl4j.inference.forwards",
                            help="coalesced forward passes").inc()
                reg.histogram(
                    "dl4j.inference.batch_rows",
                    help="rows per coalesced forward (pre-padding)"
                ).observe(c)
                reg.counter(_mon.SERVING_ROWS,
                            help="real rows dispatched through the AOT "
                                 "serving path").inc(c)
                if pad:
                    reg.counter(
                        _mon.SERVING_PADDED_ROWS,
                        help="bucket-padding rows (waste ratio = "
                             "padded / (rows + padded))").inc(pad)
                reg.histogram(_mon.SERVING_BUCKET_OCCUPANCY,
                              help="per-dispatch fill ratio "
                                   "rows/bucket").observe(c / b)
                _mon.record_transfer(sum(a.nbytes for a in arrays))
            # stage → donate: inputs enter the device as XLA-owned
            # copies; the executable may reuse their allocations.
            # stage() returns THIS chunk's buffers (concurrent
            # dispatchers never serve each other's inputs)
            bufs = ring.stage(arrays)
            try:
                with _mon.span("inference.forward"):
                    out = entry.call(self.model._params,
                                     self.model._state, *bufs)
            finally:
                # a leaked slot would strand later dispatchers in
                # stage() forever once the ring fills
                ring.release()
            pending.append((c, out))
            i += c
        if mon_on and len(chunks) > 1:
            _mon.get_registry().counter(
                _mon.SERVING_SPLITS,
                help="oversized batches split across bucket chunks "
                     "instead of compiling a novel shape").inc()
        # materialize (blocks on the device) AFTER all dispatches so
        # chunk k+1's staging overlapped chunk k's compute
        parts = [np.asarray(out[0])[:c] for c, out in pending]
        full = parts[0] if len(parts) == 1 else np.concatenate(parts, 0)
        i = 0
        for r in batch:
            k = r.x[0].shape[0]
            res = full[i:i + k]
            if with_mask and res.ndim == 3:
                res = res[:, :r.x[0].shape[1]]   # drop padded timesteps
            r.result = res
            i += k
        for r in batch:
            r.event.set()

    @staticmethod
    def _pad_time(batch, n_inputs, tb):
        """Pad sequence inputs (axis 1) to the length bucket; returns
        per-input concatenated columns + an (N, tb) validity mask
        (1 = real timestep) fed to the masked executable so padded
        steps hold recurrent carries and emit zeros."""
        cols = []
        for j in range(n_inputs):
            parts = []
            for r in batch:
                xj = r.x[j]
                if xj.ndim == 3 and xj.shape[1] < tb:
                    xj = np.pad(
                        xj, [(0, 0), (0, tb - xj.shape[1]), (0, 0)])
                parts.append(xj)
            cols.append(np.concatenate(parts, axis=0))
        mask = np.zeros((cols[0].shape[0], tb), np.float32)
        i = 0
        for r in batch:
            k, t = r.x[0].shape[0], r.x[0].shape[1]
            mask[i:i + k, :t] = 1.0
            i += k
        return cols, mask

    def shutdown(self):
        """Idempotent: the first call stops the collector and drains the
        queue (serving every live request, discarding cancelled ones);
        repeats are no-ops. Post-shutdown output() serves directly."""
        with self._lifecycle_lock:
            if self._shutdown:
                return
            self._shutdown = True
            t = self._thread
        if t is not None:
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass
            t.join(timeout=5)
        # serve anything the collector left behind
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                break
            if r is not None:
                self._dispatch([r])
