"""ParallelInference (≡ deeplearning4j-parallel-wrapper ::
inference.ParallelInference) — high-throughput shared-model inference.

The reference keeps a pool of model replicas on worker threads and a
batching queue in front of them (BATCHED mode: requests are coalesced up
to batchLimit before a forward pass). TPU-native inversion: the model is
ONE jitted executable that any thread may call (pure function of params),
so replicas are pointless — the value is in the coalescing. A collector
thread drains the request queue, groups compatible shapes, pads the
batch dim to a power-of-two bucket (static shapes → no fresh XLA
compiles per request count), runs a single forward, and scatters the
rows back to their futures.

Usage parity:
    pi = (ParallelInference.Builder(net)
          .inferenceMode(InferenceMode.BATCHED)
          .batchLimit(32).queueLimit(256).build())
    out = pi.output(x)          # thread-safe, blocks for the result
    pi.shutdown()
"""
from __future__ import annotations

import queue
import threading

import numpy as np


class InferenceMode:
    SEQUENTIAL = "SEQUENTIAL"   # direct call, no queue
    BATCHED = "BATCHED"         # coalesce requests up to batchLimit
    INPLACE = "INPLACE"         # reference alias: shared model, no copy —
    #                             identical to BATCHED here (the jitted
    #                             executable is already shared and pure)


def _bucket(n):
    b = 1
    while b < n:
        b *= 2
    return b


class _Request:
    __slots__ = ("x", "event", "result", "error", "claimed", "server")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.claimed = False
        self.server = None      # thread that claimed it (set under lock)


class ParallelInference:
    def __init__(self, model, inference_mode=InferenceMode.BATCHED,
                 batch_limit=32, queue_limit=256, collect_timeout_ms=2.0):
        self.model = model
        self.mode = inference_mode
        self.batch_limit = int(batch_limit)
        self.collect_timeout = collect_timeout_ms / 1e3
        self.model_calls = 0          # diagnostic: forwards actually run
        self._queue = queue.Queue(maxsize=int(queue_limit))
        self._claim_lock = threading.Lock()
        self._shutdown = False
        self._thread = None
        if self.mode != InferenceMode.SEQUENTIAL:
            self._thread = threading.Thread(target=self._collector,
                                            daemon=True)
            self._thread.start()

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def inferenceMode(self, mode):
            self._kw["inference_mode"] = mode
            return self

        def batchLimit(self, n):
            self._kw["batch_limit"] = int(n)
            return self

        def queueLimit(self, n):
            self._kw["queue_limit"] = int(n)
            return self

        def workers(self, *_):
            return self  # one jitted executable serves all threads

        def build(self):
            return ParallelInference(self._model, **self._kw)

    # -- client side -----------------------------------------------------
    def output(self, x):
        """Thread-safe inference. x: one example (features without batch
        dim) or a batch; returns the model output with matching leading
        dims."""
        x = np.asarray(x, np.float32)
        single = self._needs_batch(x)
        if self.mode == InferenceMode.SEQUENTIAL or self._shutdown:
            self.model_calls += 1
            out = self.model.output(x[None] if single else x)
            out = (out[0] if isinstance(out, list) else out).numpy()
            return out[0] if single else out
        req = _Request(x[None] if single else x)
        self._queue.put(req)
        # wait with a shutdown escape: a request enqueued as the collector
        # exits would otherwise block forever — claim it and serve direct
        while not req.event.wait(0.25):
            dead = self._thread is not None and not self._thread.is_alive()
            if dead:
                # collector is gone for good: flip to direct-serve mode so
                # later calls stop enqueueing into a queue nobody drains
                self._shutdown = True
            if self._shutdown or dead:
                with self._claim_lock:
                    # reclaim an unclaimed request, or one whose claiming
                    # THREAD died before delivering (a claim held by a live
                    # thread — e.g. shutdown()'s drain — stays theirs, so a
                    # request is never served twice)
                    orphaned = (req.claimed and req.server is not None
                                and not req.server.is_alive()
                                and not req.event.is_set())
                    mine = not req.claimed or orphaned
                    req.claimed = True
                    if mine:
                        req.server = threading.current_thread()
                if mine:
                    self._run([req])  # forward OUTSIDE the lock
                # else a live thread claimed it: keep waiting below
        if req.error is not None:
            raise req.error
        return req.result[0] if single else req.result

    def _needs_batch(self, x):
        """True when x is ONE example (no batch dim): its rank equals the
        model's expected feature rank."""
        want = getattr(self.model, "_input_rank", None)
        if want is None:
            want = self._infer_input_rank()
            self.model._input_rank = want
        return x.ndim == want

    def _infer_input_rank(self):
        conf = getattr(self.model, "conf", None)
        it = None
        if conf is not None:
            node_types = getattr(conf, "node_output_types", None)
            input_names = getattr(conf, "input_names", None)
            if node_types and input_names:
                it = node_types.get(input_names[0])
            else:
                it = getattr(conf, "input_type", None)
        from deeplearning4j_tpu.nn.conf.inputs import (ConvolutionalType,
                                                       RecurrentType)
        if isinstance(it, ConvolutionalType):
            return 3
        if isinstance(it, RecurrentType):
            return 2
        return 1

    # -- collector thread ------------------------------------------------
    def _collector(self):
        while not self._shutdown:
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is None:
                break
            batch = [first]
            strays = []    # incompatible shapes: run AFTER the main batch
            total = first.x.shape[0]
            # coalesce until batchLimit or a brief quiet period
            while total < self.batch_limit:
                try:
                    nxt = self._queue.get(timeout=self.collect_timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    self._shutdown = True
                    break
                if nxt.x.shape[1:] != first.x.shape[1:]:
                    strays.append(nxt)
                    continue
                batch.append(nxt)
                total += nxt.x.shape[0]
            self._dispatch(batch)
            for s in strays:
                self._dispatch([s])

    def _dispatch(self, batch):
        """Claim-then-run: a request the fallback path already claimed
        (shutdown race) must not be served twice."""
        with self._claim_lock:
            batch = [r for r in batch if not r.claimed]
            me = threading.current_thread()
            for r in batch:
                r.claimed = True
                r.server = me
        if batch:
            self._run(batch)

    def _run(self, batch):
        try:
            xs = np.concatenate([r.x for r in batch], axis=0)
            n = xs.shape[0]
            nb = _bucket(n)
            if nb != n:
                # pad with copies of the last row: static bucket shapes
                # keep XLA from compiling one executable per request count
                xs = np.concatenate(
                    [xs, np.repeat(xs[-1:], nb - n, axis=0)], axis=0)
            self.model_calls += 1
            out = self.model.output(xs)
            out = (out[0] if isinstance(out, list) else out).numpy()[:n]
            i = 0
            for r in batch:
                k = r.x.shape[0]
                r.result = out[i:i + k]
                i += k
                r.event.set()
        except BaseException as e:  # noqa: BLE001 — deliver to the waiter
            # even KeyboardInterrupt/SystemExit must release the waiters
            # before propagating, or output() blocks forever
            err = e if isinstance(e, Exception) else RuntimeError(
                f"inference worker died: {type(e).__name__}: {e}")
            for r in batch:
                r.error = err
                r.event.set()
            if not isinstance(e, Exception):
                raise

    def shutdown(self):
        if self._thread is not None and not self._shutdown:
            self._shutdown = True
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass
            self._thread.join(timeout=5)
            # serve anything the collector left behind
            while True:
                try:
                    r = self._queue.get_nowait()
                except queue.Empty:
                    break
                if r is not None:
                    self._dispatch([r])
