"""ParallelInference (≡ deeplearning4j-parallel-wrapper ::
inference.ParallelInference) — high-throughput shared-model inference.

The reference keeps a pool of model replicas on worker threads and a
batching queue in front of them (BATCHED mode: requests are coalesced up
to batchLimit before a forward pass). TPU-native inversion: the model is
ONE jitted executable that any thread may call (pure function of params),
so replicas are pointless — the value is in the coalescing. A collector
thread drains the request queue, groups compatible shapes, pads the
batch dim to a power-of-two bucket (static shapes → no fresh XLA
compiles per request count), runs a single forward, and scatters the
rows back to their futures.

Usage parity:
    pi = (ParallelInference.Builder(net)
          .inferenceMode(InferenceMode.BATCHED)
          .batchLimit(32).queueLimit(256).build())
    out = pi.output(x)          # thread-safe, blocks for the result
    pi.shutdown()
"""
from __future__ import annotations

import queue
import threading

import numpy as np

from deeplearning4j_tpu import monitoring as _mon


class InferenceMode:
    SEQUENTIAL = "SEQUENTIAL"   # direct call, no queue
    BATCHED = "BATCHED"         # coalesce requests up to batchLimit
    INPLACE = "INPLACE"         # reference alias: shared model, no copy —
    #                             identical to BATCHED here (the jitted
    #                             executable is already shared and pure)


def _bucket(n):
    b = 1
    while b < n:
        b *= 2
    return b


class _Request:
    __slots__ = ("x", "event", "result", "error", "claimed", "server")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.claimed = False
        self.server = None      # thread that claimed it (set under lock)


class ParallelInference:
    def __init__(self, model, inference_mode=InferenceMode.BATCHED,
                 batch_limit=32, queue_limit=256, collect_timeout_ms=2.0):
        self.model = model
        self.mode = inference_mode
        self.batch_limit = int(batch_limit)
        self.collect_timeout = collect_timeout_ms / 1e3
        self.model_calls = 0          # diagnostic: forwards actually run
        self._queue = queue.Queue(maxsize=int(queue_limit))
        self._claim_lock = threading.Lock()
        self._shutdown = False
        self._thread = None
        if self.mode != InferenceMode.SEQUENTIAL:
            self._thread = threading.Thread(target=self._collector,
                                            daemon=True)
            self._thread.start()

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def inferenceMode(self, mode):
            self._kw["inference_mode"] = mode
            return self

        def batchLimit(self, n):
            self._kw["batch_limit"] = int(n)
            return self

        def queueLimit(self, n):
            self._kw["queue_limit"] = int(n)
            return self

        def workers(self, *_):
            return self  # one jitted executable serves all threads

        def build(self):
            return ParallelInference(self._model, **self._kw)

    # -- client side -----------------------------------------------------
    def output(self, x):
        """Thread-safe inference. x: one example (features without batch
        dim) or a batch; for multi-input ComputationGraphs a LIST/TUPLE
        with one array per model input (coalesced per-input). Returns the
        model output with matching leading dims."""
        if _mon.enabled():
            _mon.get_registry().counter(
                "dl4j.inference.requests",
                help="ParallelInference.output calls").inc()
        n_inputs = len(self._input_ranks())
        if isinstance(x, (list, tuple)) and n_inputs > 1:
            if len(x) != n_inputs:
                raise ValueError(
                    f"model has {n_inputs} inputs but output() got "
                    f"{len(x)} arrays")
            multi = True
            xs = tuple(np.asarray(a, np.float32) for a in x)
        else:
            # single-input model: a list of rows is just a batch
            multi = False
            xs = (np.asarray(x, np.float32),)
        single = self._needs_batch(xs)
        if single:
            xs = tuple(a[None] for a in xs)
        if self.mode == InferenceMode.SEQUENTIAL or self._shutdown:
            self.model_calls += 1
            out = self.model.output(list(xs) if multi else xs[0])
            out = (out[0] if isinstance(out, list) else out).numpy()
            return out[0] if single else out
        req = _Request(xs)
        self._queue.put(req)
        # wait with a shutdown escape: a request enqueued as the collector
        # exits would otherwise block forever — claim it and serve direct
        while not req.event.wait(0.25):
            dead = self._thread is not None and not self._thread.is_alive()
            if dead:
                # collector is gone for good: flip to direct-serve mode so
                # later calls stop enqueueing into a queue nobody drains
                self._shutdown = True
            if self._shutdown or dead:
                with self._claim_lock:
                    # reclaim an unclaimed request, or one whose claiming
                    # THREAD died before delivering (a claim held by a live
                    # thread — e.g. shutdown()'s drain — stays theirs, so a
                    # request is never served twice)
                    orphaned = (req.claimed and req.server is not None
                                and not req.server.is_alive()
                                and not req.event.is_set())
                    mine = not req.claimed or orphaned
                    req.claimed = True
                    if mine:
                        req.server = threading.current_thread()
                if mine:
                    self._run([req])  # forward OUTSIDE the lock
                # else a live thread claimed it: keep waiting below
        if req.error is not None:
            raise req.error
        return req.result[0] if single else req.result

    def _input_ranks(self):
        want = getattr(self.model, "_input_ranks", None)
        if want is None:
            want = self._infer_input_ranks()
            self.model._input_ranks = want
        return want

    def _needs_batch(self, xs):
        """True when xs holds ONE example (no batch dim): the FIRST
        input's rank equals the model's expected feature rank."""
        return xs[0].ndim == self._input_ranks()[0]

    def _infer_input_ranks(self):
        """Expected FEATURE rank (no batch dim) per model input."""
        from deeplearning4j_tpu.nn.conf.inputs import (ConvolutionalType,
                                                       RecurrentType)

        def rank(it):
            if isinstance(it, ConvolutionalType):
                return 3
            if isinstance(it, RecurrentType):
                return 2
            return 1

        conf = getattr(self.model, "conf", None)
        if conf is not None:
            node_types = getattr(conf, "node_output_types", None)
            input_names = getattr(conf, "input_names", None)
            if node_types and input_names:
                return [rank(node_types.get(n)) for n in input_names]
            return [rank(getattr(conf, "input_type", None))]
        return [1]

    # -- collector thread ------------------------------------------------
    def _collector(self):
        while not self._shutdown:
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is None:
                break
            batch = [first]
            strays = []    # incompatible shapes: run AFTER the main batch
            total = first.x[0].shape[0]
            # coalesce until batchLimit or a brief quiet period
            while total < self.batch_limit:
                try:
                    nxt = self._queue.get(timeout=self.collect_timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    self._shutdown = True
                    break
                if (len(nxt.x) != len(first.x)
                        or any(a.shape[1:] != b.shape[1:]
                               for a, b in zip(nxt.x, first.x))):
                    strays.append(nxt)
                    continue
                batch.append(nxt)
                total += nxt.x[0].shape[0]
            self._dispatch(batch)
            for s in strays:
                self._dispatch([s])

    def _dispatch(self, batch):
        """Claim-then-run: a request the fallback path already claimed
        (shutdown race) must not be served twice."""
        with self._claim_lock:
            batch = [r for r in batch if not r.claimed]
            me = threading.current_thread()
            for r in batch:
                r.claimed = True
                r.server = me
        if batch:
            self._run(batch)

    def _run(self, batch):
        try:
            n_inputs = len(batch[0].x)
            cols = []
            for j in range(n_inputs):
                xj = np.concatenate([r.x[j] for r in batch], axis=0)
                cols.append(xj)
            n = cols[0].shape[0]
            nb = _bucket(n)
            if nb != n:
                # pad with copies of the last row: static bucket shapes
                # keep XLA from compiling one executable per request count
                cols = [np.concatenate(
                    [xj, np.repeat(xj[-1:], nb - n, axis=0)], axis=0)
                    for xj in cols]
            self.model_calls += 1
            if _mon.enabled():
                reg = _mon.get_registry()
                reg.counter("dl4j.inference.forwards",
                            help="coalesced forward passes").inc()
                reg.histogram(
                    "dl4j.inference.batch_rows",
                    help="rows per coalesced forward (pre-padding)"
                ).observe(n)
                _mon.record_transfer(sum(c.nbytes for c in cols))
            with _mon.span("inference.forward"):
                out = self.model.output(cols if n_inputs > 1 else cols[0])
                out = (out[0] if isinstance(out, list)
                       else out).numpy()[:n]
            i = 0
            for r in batch:
                k = r.x[0].shape[0]
                r.result = out[i:i + k]
                i += k
                r.event.set()
        except BaseException as e:  # noqa: BLE001 — deliver to the waiter
            # even KeyboardInterrupt/SystemExit must release the waiters
            # before propagating, or output() blocks forever
            err = e if isinstance(e, Exception) else RuntimeError(
                f"inference worker died: {type(e).__name__}: {e}")
            for r in batch:
                r.error = err
                r.event.set()
            if not isinstance(e, Exception):
                raise

    def shutdown(self):
        if self._thread is not None and not self._shutdown:
            self._shutdown = True
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass
            self._thread.join(timeout=5)
            # serve anything the collector left behind
            while True:
                try:
                    r = self._queue.get_nowait()
                except queue.Empty:
                    break
                if r is not None:
                    self._dispatch([r])
