"""ParallelInference (≡ deeplearning4j-parallel-wrapper ::
inference.ParallelInference) — high-throughput shared-model inference.

The reference keeps a pool of model replicas on worker threads and a
batching queue in front of them (BATCHED mode: requests are coalesced up
to batchLimit before a forward pass). TPU-native inversion: the model is
ONE jitted executable that any thread may call (pure function of params),
so replicas are pointless — the value is in the coalescing. A collector
thread drains the request queue, groups compatible shapes, pads the
batch dim to a power-of-two bucket (static shapes → no fresh XLA
compiles per request count), runs a single forward, and scatters the
rows back to their futures.

Graceful degradation (resilience/): callers NEVER block indefinitely.
- `output(x, timeout_ms=...)` enforces a per-request deadline — expiry
  cancels the request and raises `InferenceTimeoutError`;
- enqueue is bounded: a queue that stays full for `enqueue_timeout_ms`
  sheds the request with `InferenceOverloadedError` instead of blocking;
- a dead collector thread is restarted behind a `CircuitBreaker` —
  repeated deaths OPEN the breaker and requests are served directly
  (degraded, uncoalesced) until the cooldown's half-open probe brings
  the collector back;
- `shutdown()` is idempotent and drains the queue clean.
Sheds, timeouts, and restarts count through `monitoring/`
(`dl4j.resilience.inference_*` / `collector_restarts`).

Usage parity:
    pi = (ParallelInference.Builder(net)
          .inferenceMode(InferenceMode.BATCHED)
          .batchLimit(32).queueLimit(256).build())
    out = pi.output(x)                    # thread-safe, blocks
    out = pi.output(x, timeout_ms=50)     # bounded wait
    pi.shutdown()
"""
from __future__ import annotations

import queue
import threading
import time

import numpy as np

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.resilience import faults as _faults
from deeplearning4j_tpu.resilience.errors import (InferenceOverloadedError,
                                                  InferenceTimeoutError)
from deeplearning4j_tpu.resilience.policy import CircuitBreaker


class InferenceMode:
    SEQUENTIAL = "SEQUENTIAL"   # direct call, no queue
    BATCHED = "BATCHED"         # coalesce requests up to batchLimit
    INPLACE = "INPLACE"         # reference alias: shared model, no copy —
    #                             identical to BATCHED here (the jitted
    #                             executable is already shared and pure)


def _bucket(n):
    b = 1
    while b < n:
        b *= 2
    return b


class _Request:
    __slots__ = ("x", "event", "result", "error", "claimed", "cancelled",
                 "server")

    def __init__(self, x):
        self.x = x
        self.event = threading.Event()
        self.result = None
        self.error = None
        self.claimed = False
        self.cancelled = False  # deadline expired: discard, never serve
        self.server = None      # thread that claimed it (set under lock)


class ParallelInference:
    def __init__(self, model, inference_mode=InferenceMode.BATCHED,
                 batch_limit=32, queue_limit=256, collect_timeout_ms=2.0,
                 enqueue_timeout_ms=100.0, breaker=None):
        self.model = model
        self.mode = inference_mode
        self.batch_limit = int(batch_limit)
        self.collect_timeout = collect_timeout_ms / 1e3
        self.enqueue_timeout = enqueue_timeout_ms / 1e3
        self.model_calls = 0          # diagnostic: forwards actually run
        self.collector_restarts = 0   # diagnostic: breaker-guarded revives
        self.collector_error = None   # last error that killed a collector
        self._restart_unconfirmed = False   # revive awaiting 1st success
        self._queue = queue.Queue(maxsize=int(queue_limit))
        self._claim_lock = threading.Lock()
        self._lifecycle_lock = threading.Lock()   # restart + shutdown
        self._breaker = breaker or CircuitBreaker(
            failure_threshold=3, cooldown=5.0, name="inference.collector")
        self._last_dead = None    # thread whose death was already recorded
        self._shutdown = False
        self._thread = None
        if self.mode != InferenceMode.SEQUENTIAL:
            self._thread = self._start_collector()

    def _start_collector(self):
        t = threading.Thread(target=self._collector_main, daemon=True)
        t.start()
        return t

    def _collector_main(self):
        try:
            self._collector()
        except BaseException as e:  # noqa: BLE001 — thread is dying anyway
            # remember why (surfaced by the revive path / diagnostics)
            # instead of spewing a default thread traceback; waiting
            # clients detect the death and revive or degrade
            self.collector_error = e

    class Builder:
        def __init__(self, model):
            self._model = model
            self._kw = {}

        def inferenceMode(self, mode):
            self._kw["inference_mode"] = mode
            return self

        def batchLimit(self, n):
            self._kw["batch_limit"] = int(n)
            return self

        def queueLimit(self, n):
            self._kw["queue_limit"] = int(n)
            return self

        def enqueueTimeoutMs(self, ms):
            """How long output() may wait for queue space before shedding
            with InferenceOverloadedError."""
            self._kw["enqueue_timeout_ms"] = float(ms)
            return self

        def breaker(self, breaker):
            """Circuit breaker guarding collector-thread restarts."""
            self._kw["breaker"] = breaker
            return self

        def workers(self, *_):
            return self  # one jitted executable serves all threads

        def build(self):
            return ParallelInference(self._model, **self._kw)

    # -- client side -----------------------------------------------------
    def output(self, x, timeout_ms=None):
        """Thread-safe inference. x: one example (features without batch
        dim) or a batch; for multi-input ComputationGraphs a LIST/TUPLE
        with one array per model input (coalesced per-input). Returns the
        model output with matching leading dims.

        timeout_ms bounds the WHOLE call (enqueue + wait): expiry cancels
        the request and raises InferenceTimeoutError. A full queue that
        stays full past the bounded enqueue wait sheds the request with
        InferenceOverloadedError — callers never block indefinitely.
        Direct (SEQUENTIAL / degraded / post-shutdown) forwards run
        synchronously and cannot be interrupted mid-flight: the deadline
        is enforced after the forward, so the worst-case latency of a
        timed-out direct call is one model forward."""
        if _mon.enabled():
            _mon.get_registry().counter(
                "dl4j.inference.requests",
                help="ParallelInference.output calls").inc()
        n_inputs = len(self._input_ranks())
        if isinstance(x, (list, tuple)) and n_inputs > 1:
            if len(x) != n_inputs:
                raise ValueError(
                    f"model has {n_inputs} inputs but output() got "
                    f"{len(x)} arrays")
            multi = True
            xs = tuple(np.asarray(a, np.float32) for a in x)
        else:
            # single-input model: a list of rows is just a batch
            multi = False
            xs = (np.asarray(x, np.float32),)
        single = self._needs_batch(xs)
        if single:
            xs = tuple(a[None] for a in xs)
        deadline = None if timeout_ms is None \
            else time.monotonic() + float(timeout_ms) / 1e3
        if self.mode == InferenceMode.SEQUENTIAL or self._shutdown:
            return self._direct_deadline(xs, multi, single, deadline)
        if self._thread is not None and not self._thread.is_alive():
            # dead collector noticed up front: revive (breaker willing)
            # or serve this request directly — no pointless queue wait
            if not self._revive_collector():
                return self._direct_deadline(xs, multi, single, deadline)
        req = _Request(xs)
        self._enqueue(req, deadline)
        degraded = False
        while not req.event.is_set():
            wait = 0.25
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    self._cancel(req)
                    raise InferenceTimeoutError(
                        f"inference request missed its "
                        f"{float(timeout_ms):.6g} ms deadline")
                wait = min(wait, remaining)
            if req.event.wait(wait):
                break
            dead = self._thread is not None and not self._thread.is_alive()
            if dead and not self._shutdown:
                # breaker-guarded revive; False → breaker OPEN, serve
                # this request directly (degraded but live)
                if self._revive_collector():
                    continue
                degraded = True
            if self._shutdown or (dead and degraded):
                with self._claim_lock:
                    # reclaim an unclaimed request, or one whose claiming
                    # THREAD died before delivering (a claim held by a live
                    # thread — e.g. shutdown()'s drain — stays theirs, so a
                    # request is never served twice)
                    orphaned = (req.claimed and req.server is not None
                                and not req.server.is_alive()
                                and not req.event.is_set())
                    mine = not req.claimed or orphaned
                    req.claimed = True
                    if mine:
                        req.server = threading.current_thread()
                if mine:
                    self._run([req])  # forward OUTSIDE the lock
                # else a live thread claimed it: keep waiting below
        if req.error is not None:
            raise req.error
        if deadline is not None and time.monotonic() > deadline:
            # result landed after the deadline (e.g. a degraded direct
            # serve that outran the budget): honour the contract
            self._count_timeout()
            raise InferenceTimeoutError(
                f"inference request missed its "
                f"{float(timeout_ms):.6g} ms deadline (late result "
                "discarded)")
        if self._restart_unconfirmed and not degraded:
            # the FIRST queued result after a restart proves the revived
            # collector is healthy: close the breaker exactly once (a
            # permanent every-request record_success would also zero the
            # failure count between deaths, so a flapping collector
            # could never trip to degraded mode)
            self._restart_unconfirmed = False
            self._breaker.record_success()
        return req.result[0] if single else req.result

    def _direct(self, xs, multi, single):
        self.model_calls += 1
        out = self.model.output(list(xs) if multi else xs[0])
        out = (out[0] if isinstance(out, list) else out).numpy()
        return out[0] if single else out

    def _direct_deadline(self, xs, multi, single, deadline):
        """Direct serve with the deadline enforced AFTER the forward
        (a synchronous jitted call cannot be interrupted mid-flight)."""
        out = self._direct(xs, multi, single)
        if deadline is not None and time.monotonic() > deadline:
            self._count_timeout()
            raise InferenceTimeoutError(
                "inference request missed its deadline (direct forward "
                "finished late; result discarded)")
        return out

    def _count_timeout(self):
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.RESILIENCE_INFERENCE_TIMEOUTS,
                help="requests cancelled at their deadline").inc()

    def _enqueue(self, req, deadline):
        wait = self.enqueue_timeout
        if deadline is not None:
            wait = min(wait, max(0.0, deadline - time.monotonic()))
        try:
            if wait > 0:
                self._queue.put(req, timeout=wait)
            else:
                self._queue.put_nowait(req)
        except queue.Full:
            if deadline is not None and time.monotonic() >= deadline:
                # the caller's deadline — not the enqueue budget —
                # expired while waiting for space: that is a timeout,
                # not a shed (callers retry on overloaded, not timeout)
                self._count_timeout()
                raise InferenceTimeoutError(
                    "inference request deadline expired while waiting "
                    "for queue space") from None
            if _mon.enabled():
                _mon.get_registry().counter(
                    _mon.RESILIENCE_INFERENCE_SHED,
                    help="requests shed because the queue stayed full "
                         "for the whole bounded enqueue wait").inc()
            raise InferenceOverloadedError(
                f"inference queue full (limit {self._queue.maxsize}) "
                f"after {wait * 1e3:.6g} ms — request shed") from None

    def _cancel(self, req):
        """Deadline expiry: mark the request so no thread serves it (or,
        if already in flight, so its late result is discarded)."""
        with self._claim_lock:
            req.cancelled = True
            req.claimed = True
        self._count_timeout()

    def _revive_collector(self):
        """Restart a dead collector behind the circuit breaker. Each
        distinct thread death records ONE breaker failure (not one per
        waiting caller); when the breaker is OPEN the restart is shed
        and the caller degrades to direct serving. Returns True when a
        live collector exists after the call."""
        with self._lifecycle_lock:
            if self._shutdown:
                return False
            t = self._thread
            if t is None or t.is_alive():
                return True
            if t is not self._last_dead:
                self._last_dead = t
                self._breaker.record_failure()
            if not self._breaker.allow():
                return False
            self._thread = self._start_collector()
            self.collector_restarts += 1
            self._restart_unconfirmed = True
        if _mon.enabled():
            _mon.get_registry().counter(
                _mon.RESILIENCE_COLLECTOR_RESTARTS,
                help="collector threads restarted after death").inc()
        return True

    def _input_ranks(self):
        want = getattr(self.model, "_input_ranks", None)
        if want is None:
            want = self._infer_input_ranks()
            self.model._input_ranks = want
        return want

    def _needs_batch(self, xs):
        """True when xs holds ONE example (no batch dim): the FIRST
        input's rank equals the model's expected feature rank."""
        return xs[0].ndim == self._input_ranks()[0]

    def _infer_input_ranks(self):
        """Expected FEATURE rank (no batch dim) per model input."""
        from deeplearning4j_tpu.nn.conf.inputs import (ConvolutionalType,
                                                       RecurrentType)

        def rank(it):
            if isinstance(it, ConvolutionalType):
                return 3
            if isinstance(it, RecurrentType):
                return 2
            return 1

        conf = getattr(self.model, "conf", None)
        if conf is not None:
            node_types = getattr(conf, "node_output_types", None)
            input_names = getattr(conf, "input_names", None)
            if node_types and input_names:
                return [rank(node_types.get(n)) for n in input_names]
            return [rank(getattr(conf, "input_type", None))]
        return [1]

    # -- collector thread ------------------------------------------------
    def _collector(self):
        while not self._shutdown:
            # fault site OUTSIDE the per-batch try: a fault here kills
            # the collector thread (the auto-restart path under test)
            if _faults.ACTIVE is not None:
                _faults.ACTIVE.fire(_faults.INFERENCE_COLLECTOR)
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            if first is None:
                break
            batch = [first]
            strays = []    # incompatible shapes: run AFTER the main batch
            total = first.x[0].shape[0]
            # coalesce until batchLimit or a brief quiet period
            while total < self.batch_limit:
                try:
                    nxt = self._queue.get(timeout=self.collect_timeout)
                except queue.Empty:
                    break
                if nxt is None:
                    self._shutdown = True
                    break
                if (len(nxt.x) != len(first.x)
                        or any(a.shape[1:] != b.shape[1:]
                               for a, b in zip(nxt.x, first.x))):
                    strays.append(nxt)
                    continue
                batch.append(nxt)
                total += nxt.x[0].shape[0]
            self._dispatch(batch)
            for s in strays:
                self._dispatch([s])

    def _dispatch(self, batch):
        """Claim-then-run: a request the fallback path already claimed
        (shutdown race) or that was cancelled at its deadline must not
        be served (twice / at all)."""
        with self._claim_lock:
            batch = [r for r in batch if not r.claimed and not r.cancelled]
            me = threading.current_thread()
            for r in batch:
                r.claimed = True
                r.server = me
        if batch:
            self._run(batch)

    def _run(self, batch):
        try:
            if _faults.ACTIVE is not None:
                _faults.ACTIVE.fire(_faults.INFERENCE_FORWARD)
            n_inputs = len(batch[0].x)
            cols = []
            for j in range(n_inputs):
                xj = np.concatenate([r.x[j] for r in batch], axis=0)
                cols.append(xj)
            n = cols[0].shape[0]
            nb = _bucket(n)
            if nb != n:
                # pad with copies of the last row: static bucket shapes
                # keep XLA from compiling one executable per request count
                cols = [np.concatenate(
                    [xj, np.repeat(xj[-1:], nb - n, axis=0)], axis=0)
                    for xj in cols]
            self.model_calls += 1
            if _mon.enabled():
                reg = _mon.get_registry()
                reg.counter("dl4j.inference.forwards",
                            help="coalesced forward passes").inc()
                reg.histogram(
                    "dl4j.inference.batch_rows",
                    help="rows per coalesced forward (pre-padding)"
                ).observe(n)
                _mon.record_transfer(sum(c.nbytes for c in cols))
            with _mon.span("inference.forward"):
                out = self.model.output(cols if n_inputs > 1 else cols[0])
                out = (out[0] if isinstance(out, list)
                       else out).numpy()[:n]
            i = 0
            for r in batch:
                k = r.x[0].shape[0]
                r.result = out[i:i + k]
                i += k
                r.event.set()
        except BaseException as e:  # noqa: BLE001 — deliver to the waiter
            # even KeyboardInterrupt/SystemExit must release the waiters
            # before propagating, or output() blocks forever
            err = e if isinstance(e, Exception) else RuntimeError(
                f"inference worker died: {type(e).__name__}: {e}")
            for r in batch:
                r.error = err
                r.event.set()
            if not isinstance(e, Exception):
                raise

    def shutdown(self):
        """Idempotent: the first call stops the collector and drains the
        queue (serving every live request, discarding cancelled ones);
        repeats are no-ops. Post-shutdown output() serves directly."""
        with self._lifecycle_lock:
            if self._shutdown:
                return
            self._shutdown = True
            t = self._thread
        if t is not None:
            try:
                self._queue.put_nowait(None)
            except queue.Full:
                pass
            t.join(timeout=5)
        # serve anything the collector left behind
        while True:
            try:
                r = self._queue.get_nowait()
            except queue.Empty:
                break
            if r is not None:
                self._dispatch([r])
