from deeplearning4j_tpu.util.crash_reporting import CrashReportingUtil
from deeplearning4j_tpu.util.model_guesser import (ModelGuesser,
                                                   ModelGuesserException)
from deeplearning4j_tpu.util.model_serializer import ModelSerializer

__all__ = ["ModelSerializer", "ModelGuesser", "ModelGuesserException",
           "CrashReportingUtil"]
