"""Signature introspection for the attention mask-arity guards.

A padded batch must never silently attend to padding: custom attention
impls (models/bert.py `attn_impl`, parallel/ulysses.py `attn_fn`) have to
DECLARE the mask they receive. The old `inspect.signature(...).bind(...)`
check was satisfied by any `*args`/`**kwargs` catch-all — a
kwargs-swallowing impl would pass the guard and drop the mask on the
floor, the exact failure the check exists to make loud (ADVICE r5,
bert.py:167). This helper requires an EXPLICIT parameter, and reports
the calling convention it is actually reachable by, so the guard never
approves an impl the call site cannot invoke.
"""
from __future__ import annotations

import inspect


def explicit_mask_param(fn, names=("mask", "attn_mask", "kv_mask"),
                        positional_slot=None):
    """How can `fn` explicitly receive the mask? Returns

    - ("keyword", name) when a parameter from `names` is callable by
      keyword (POSITIONAL_OR_KEYWORD or KEYWORD_ONLY — bare `**kwargs`
      does NOT count, and neither does a positional-only parameter that
      merely shares the name). Checked FIRST so an impl like
      f(q, k, v, causal=False, mask=None) gets the mask bound to `mask`,
      never mis-bound to `causal` by slot counting;
    - ("positional", None) when `positional_slot` is given and the
      parameter at that slot (POSITIONAL_ONLY or POSITIONAL_OR_KEYWORD —
      `*args` does NOT count) is either named in `names` or has no
      default. A required 4th positional arg IS the mask slot by
      construction of the attn_impl(q, k, v, mask) convention; a
      DEFAULTED 4th positional with a non-mask name (e.g. causal=False)
      is rejected — binding the mask there would silently change an
      unrelated knob;
    - None when neither holds, or the signature is not introspectable
      (builtins, some C callables) — callers refuse both the same way:
      wrap the callable with an explicit signature to use it on masked
      batches.
    """
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return None
    params = sig.parameters
    for n in names:
        p = params.get(n)
        if p is not None and p.kind in (p.POSITIONAL_OR_KEYWORD,
                                        p.KEYWORD_ONLY):
            return ("keyword", n)
    if positional_slot is not None:
        positional = [p for p in params.values()
                      if p.kind in (p.POSITIONAL_ONLY,
                                    p.POSITIONAL_OR_KEYWORD)]
        if len(positional) >= positional_slot:
            slot = positional[positional_slot - 1]
            if slot.name in names or slot.default is inspect.Parameter.empty:
                return ("positional", None)
    return None


def accepts_explicit_mask(fn, names=("mask", "attn_mask", "kv_mask"),
                          min_positional=None):
    """Boolean convenience over explicit_mask_param: True/False when the
    signature is introspectable, None when it is not."""
    try:
        inspect.signature(fn)
    except (TypeError, ValueError):
        return None
    return explicit_mask_param(fn, names,
                               positional_slot=min_positional) is not None
