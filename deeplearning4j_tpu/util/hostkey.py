"""Host-keyed persistent-compile-cache paths.

XLA:CPU stores AOT machine code in the jax persistent cache; entries
written on a different machine type load with "could lead to execution
errors such as SIGILL" warnings. Keying the cache directory by the host's
CPU feature flags makes cross-machine entries simply miss instead."""
from __future__ import annotations

import hashlib
import os
import platform


def host_cpu_key() -> str:
    """Short stable hash of this host's CPU feature flags AND the jax/
    python flavour. The AOT machine-code flavour depends on the compiling
    jax build as well as the CPU (observed: two jax installs on one box
    sharing a cache produce 'prefer-no-gather ... could lead to SIGILL'
    load warnings), so both go into the key."""
    feats = platform.machine()
    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                if line.startswith("flags"):
                    feats += " ".join(sorted(line.split(":", 1)[1].split()))
                    break
    except OSError:
        pass
    try:
        from jax import version as _jv
        feats += f" jax={_jv.__version__}"
    except Exception:
        pass
    import sys
    feats += f" py={sys.version_info[:2]} exe={sys.executable}"
    return hashlib.sha256(feats.encode()).hexdigest()[:12]


def cache_dir(root: str) -> str:
    """Per-host-flavour jax compilation cache dir under `root`."""
    return os.path.join(root, ".jax_cache", f"cpu-{host_cpu_key()}")


def enable_compile_cache(root: str, min_compile_secs: float = 2.0) -> None:
    """Point jax's persistent compilation cache at cache_dir(root).

    Single definition shared by bench.py and exp_tpu_r4.py so the two
    chip-facing entry points can never silently diverge on cache policy.

    min_compile_secs floor of 2.0 is deliberate: XLA:CPU's serialized
    executable for at least one borderline-fast (~1 s) compile in this
    codebase deserializes WRONG — the reader gets bad numerics and a
    corrupted heap (GC segfault at exit) while the writer, which keeps
    using its in-memory executable, stays green. Keeping sub-2 s
    compiles out of the cache costs little (they are cheap to redo by
    definition) and keeps the poison class off disk entirely."""
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir(root))
    jax.config.update("jax_persistent_cache_min_compile_time_secs",
                      min_compile_secs)
