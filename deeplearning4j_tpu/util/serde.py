"""Config JSON serde (≡ MultiLayerConfiguration.toJson/fromJson — the
reference persists configs as Jackson JSON inside model zips; same idea).

Objects from our config namespaces encode as {"@class": name, ...fields};
decode resolves the class from a registry of config modules.
"""
from __future__ import annotations

import importlib
import types

_CONFIG_MODULES = [
    "deeplearning4j_tpu.nn.conf.layers",
    "deeplearning4j_tpu.nn.conf.special_layers",
    "deeplearning4j_tpu.nn.conf.variational",
    "deeplearning4j_tpu.nn.conf.weightnoise",
    "deeplearning4j_tpu.nn.conf.objdetect",
    "deeplearning4j_tpu.nn.losses",
    "deeplearning4j_tpu.nn.conf.inputs",
    "deeplearning4j_tpu.nn.conf.preprocessors",
    "deeplearning4j_tpu.nn.conf.builders",
    "deeplearning4j_tpu.nn.conf.recurrent",
    "deeplearning4j_tpu.nn.conf.attention",
    "deeplearning4j_tpu.nn.conf.samediff_layers",
    "deeplearning4j_tpu.nn.conf.layers3d",
    "deeplearning4j_tpu.nn.conf.sequence_layers",
    "deeplearning4j_tpu.nn.conf.capsules",
    "deeplearning4j_tpu.nn.conf.graph_vertices",
    "deeplearning4j_tpu.nn.updaters",
    "deeplearning4j_tpu.nn.schedules",
    # precision policies ride on layer confs (QAT), and quantized
    # layer confs replace trained layers after quantize_network()
    "deeplearning4j_tpu.quantize.policy",
    "deeplearning4j_tpu.quantize.infer",
]


#: modules explicitly trusted for custom-class restore (beyond ones the
#: restoring process has ALREADY imported) — see registerCustomModule
_TRUSTED_CUSTOM_MODULES = set()


def registerCustomModule(module_name):
    """Trust `module_name` for custom-layer restore. Without registration,
    decode only resolves custom classes from modules the restoring process
    has already imported — config JSON can never trigger an import (the
    Jackson-polymorphic-deserialization gadget class the reference's
    ObjectMapper had to lock down with subtype registration)."""
    _TRUSTED_CUSTOM_MODULES.add(str(module_name))


def _resolve_custom(name, module):
    """Resolve a user-defined config class recorded with its module path.
    The module must already be imported (the class was defined somewhere in
    this process, the normal case) or explicitly trusted via
    registerCustomModule; the class must be a config-base subclass."""
    import sys
    m = sys.modules.get(module)
    if m is None:
        if module not in _TRUSTED_CUSTOM_MODULES:
            raise ValueError(
                f"Cannot restore custom layer '{name}': its defining module "
                f"'{module}' is not imported. Import it first (or call "
                f"util.serde.registerCustomModule({module!r})) — config "
                "files cannot trigger imports themselves.")
        try:
            m = importlib.import_module(module)
        except ImportError as e:
            raise ValueError(
                f"Cannot restore custom layer '{name}': trusted module "
                f"'{module}' failed to import ({e}).") from e
    if not hasattr(m, name):
        raise ValueError(
            f"Cannot restore custom layer: module '{module}' has no class "
            f"'{name}'")
    cls = getattr(m, name)
    from deeplearning4j_tpu.nn.conf.graph_vertices import GraphVertex
    from deeplearning4j_tpu.nn.conf.layers import Layer
    from deeplearning4j_tpu.nn.conf.preprocessors import InputPreProcessor
    if not (isinstance(cls, type) and issubclass(
            cls, (Layer, GraphVertex, InputPreProcessor))):
        raise ValueError(
            f"Cannot restore '{module}.{name}': custom config classes must "
            "subclass Layer, GraphVertex or InputPreProcessor")
    return cls


def _resolve(name):
    for mod in _CONFIG_MODULES:
        try:
            m = importlib.import_module(mod)
        except ImportError:
            continue
        if hasattr(m, name):
            return getattr(m, name)
    raise ValueError(f"Cannot resolve config class '{name}'")


def encode(obj):
    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, (list, tuple)):
        return {"@tuple": [encode(o) for o in obj]} if isinstance(obj, tuple) \
            else [encode(o) for o in obj]
    if isinstance(obj, dict):
        return {"@dict": {str(k): encode(v) for k, v in obj.items()}}
    # config object: class + public fields; user-defined classes (custom
    # SameDiffLayer subclasses etc.) also record their defining module so
    # decode can import it (≡ the reference's Jackson subtype registry —
    # the class must be importable at restore time)
    d = {"@class": type(obj).__name__}
    mod = type(obj).__module__
    if mod not in _CONFIG_MODULES:
        d["@module"] = mod
    for k, v in obj.__dict__.items():
        # skip functions/methods, but keep callable CONFIG OBJECTS
        # (e.g. LossMCXENT instances) — they encode via @class like any
        # other config value
        if k.startswith("_") or isinstance(
                v, (types.FunctionType, types.MethodType,
                    types.BuiltinFunctionType, type)):
            continue
        d[k] = encode(v)
    return d


def decode(obj):
    if isinstance(obj, list):
        return [decode(o) for o in obj]
    if isinstance(obj, dict):
        if "@tuple" in obj:
            return tuple(decode(o) for o in obj["@tuple"])
        if "@dict" in obj:
            return {k: decode(v) for k, v in obj["@dict"].items()}
        if "@class" in obj:
            if "@module" in obj:
                cls = _resolve_custom(obj["@class"], obj["@module"])
            else:
                cls = _resolve(obj["@class"])
            inst = cls.__new__(cls)
            for k, v in obj.items():
                if k not in ("@class", "@module"):
                    # object.__setattr__ so frozen dataclasses (InputType)
                    # decode too
                    object.__setattr__(inst, k, decode(v))
            return inst
        return {k: decode(v) for k, v in obj.items()}
    return obj


def config_to_dict(conf):
    from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
    return {
        "format": "deeplearning4j_tpu/MultiLayerConfiguration/v1",
        "defaults": encode({k: v for k, v in conf.defaults.items()}),
        "layers": [encode(l) for l in conf.layers],
        "input_type": encode(conf.input_type),
        "preprocessors": {str(k): encode(v) for k, v in conf.preprocessors.items()},
        "backprop_type": conf.backprop_type,
        "tbptt_fwd_length": conf.tbptt_fwd_length,
        "tbptt_back_length": conf.tbptt_back_length,
        "data_type": conf.data_type,
        "seed": conf.seed,
    }


def config_from_dict(d):
    from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
    defaults = decode(d["defaults"])
    return MultiLayerConfiguration(
        defaults if isinstance(defaults, dict) else {},
        [decode(l) for l in d["layers"]],
        decode(d["input_type"]),
        {int(k): decode(v) for k, v in d.get("preprocessors", {}).items()},
        d.get("backprop_type", "standard"),
        d.get("tbptt_fwd_length", 20),
        d.get("tbptt_back_length", 20),
        d.get("data_type", "float32"),
        d.get("seed", 0))
