"""ModelGuesser (≡ deeplearning4j-core ::
org.deeplearning4j.util.ModelGuesser / ModelGuesserException).

Loads "whatever model file this is": tries the DL4J zip archive first
(MultiLayerNetwork, then ComputationGraph), then a Keras JSON config
(sequential, then functional) — the same fall-through order the
reference uses.
"""
from __future__ import annotations

import zipfile

from deeplearning4j_tpu.util.model_serializer import ModelSerializer

__all__ = ["ModelGuesser", "ModelGuesserException"]


class ModelGuesserException(Exception):
    pass


class ModelGuesser:
    @staticmethod
    def loadModelGuess(path, inputType=None):
        """Returns a MultiLayerNetwork, ComputationGraph, or Keras-imported
        network; raises ModelGuesserException when nothing matches."""
        errors = []
        if zipfile.is_zipfile(path):
            with zipfile.ZipFile(path) as zf:
                names = set(zf.namelist())
            if "samediff.json" in names:   # SameDiff full-graph artifact
                from deeplearning4j_tpu.autodiff.samediff import SameDiff
                try:
                    return SameDiff.load(path)
                except Exception as e:
                    errors.append(f"samediff: {e}")
            for restore in (ModelSerializer.restoreMultiLayerNetwork,
                            ModelSerializer.restoreComputationGraph):
                try:
                    return restore(path)
                except Exception as e:  # try the next format
                    errors.append(f"{restore.__name__}: {e}")
        else:
            from deeplearning4j_tpu.keras_import.keras_import import \
                KerasModelImport
            try:
                return KerasModelImport.importKerasSequentialModelAndWeights(
                    path, inputType=inputType)
            except Exception as e:
                errors.append(f"keras sequential: {e}")
            try:
                return KerasModelImport.importKerasModelAndWeights(path)
            except Exception as e:
                errors.append(f"keras functional: {e}")
        raise ModelGuesserException(
            f"could not load {path!r} as any known model format: "
            + "; ".join(errors))

    @staticmethod
    def loadNormalizer(path):
        """≡ ModelGuesser.loadNormalizer — normalizer from a model zip."""
        try:
            return ModelSerializer.restoreNormalizerFromFile(path)
        except Exception as e:
            raise ModelGuesserException(str(e))
