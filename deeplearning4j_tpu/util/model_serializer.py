"""ModelSerializer (≡ deeplearning4j-core :: util.ModelSerializer).

Same idea as the reference's zip format: a zip holding the config JSON
("configuration.json"), parameter tensors ("coefficients.npz"), mutable
layer state ("state.npz") and optionally the updater state
("updaterState.npz"). Also carries normalizers, like the reference's
addNormalizerToModel.
"""
from __future__ import annotations

import io
import json
import pickle
import zipfile

import jax
import numpy as np

CONFIG_JSON = "configuration.json"
PARAMS_NPZ = "coefficients.npz"
STATE_NPZ = "state.npz"
UPDATER_PKL = "updaterState.bin"
NORMALIZER_PKL = "normalizer.bin"
KIND_TXT = "modeltype.txt"


def _tree_to_npz_bytes(tree):
    flat = {}

    def walk(prefix, node):
        if isinstance(node, dict):
            for k, v in node.items():
                walk(f"{prefix}/{k}" if prefix else str(k), v)
        else:
            flat[prefix] = np.asarray(node)

    walk("", tree or {})
    buf = io.BytesIO()
    np.savez(buf, **flat)
    return buf.getvalue()


def _npz_bytes_to_tree(data):
    loaded = np.load(io.BytesIO(data))
    tree = {}
    for key in loaded.files:
        parts = key.split("/")
        node = tree
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = jax.numpy.asarray(loaded[key])
    return tree


class ModelSerializer:
    @staticmethod
    def writeModel(model, path, saveUpdater=True, normalizer=None):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        kind = "ComputationGraph"
        if isinstance(model, MultiLayerNetwork):
            kind = "MultiLayerNetwork"
        with zipfile.ZipFile(path, "w", zipfile.ZIP_DEFLATED) as zf:
            zf.writestr(KIND_TXT, kind)
            zf.writestr(CONFIG_JSON, model.conf.toJson())
            zf.writestr(PARAMS_NPZ, _tree_to_npz_bytes(model._params))
            zf.writestr(STATE_NPZ, _tree_to_npz_bytes(model._state))
            if saveUpdater and model._opt_state is not None:
                # leaves only: optax state treedefs don't survive pickling
                # across versions; restore rebuilds structure from config
                leaves = jax.tree_util.tree_leaves(model._opt_state)
                zf.writestr(UPDATER_PKL, pickle.dumps(
                    [np.asarray(l) for l in leaves]))
            if normalizer is not None:
                zf.writestr(NORMALIZER_PKL, pickle.dumps(normalizer))
        return path

    @staticmethod
    def _restore(path, loadUpdater, expected_kind):
        with zipfile.ZipFile(path, "r") as zf:
            kind = zf.read(KIND_TXT).decode()
            conf_json = zf.read(CONFIG_JSON).decode()
            params = _npz_bytes_to_tree(zf.read(PARAMS_NPZ))
            state = _npz_bytes_to_tree(zf.read(STATE_NPZ))
            updater_blob = (zf.read(UPDATER_PKL)
                            if loadUpdater and UPDATER_PKL in zf.namelist()
                            else None)
        if expected_kind and kind != expected_kind:
            raise ValueError(f"Model in {path} is a {kind}, expected {expected_kind}")
        if kind == "MultiLayerNetwork":
            from deeplearning4j_tpu.nn.conf.builders import MultiLayerConfiguration
            from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
            conf = MultiLayerConfiguration.fromJson(conf_json)
            model = MultiLayerNetwork(conf)
        else:
            from deeplearning4j_tpu.nn.conf.graph_builder import \
                ComputationGraphConfiguration
            from deeplearning4j_tpu.nn.graph import ComputationGraph
            conf = ComputationGraphConfiguration.fromJson(conf_json)
            model = ComputationGraph(conf)
        model.init()
        model._params = params
        model._state = state
        model._build_optimizer()
        if updater_blob is not None:
            loaded = pickle.loads(updater_blob)
            # pre-fix archives stored (leaves, treedef); now leaves only
            leaves = loaded[0] if isinstance(loaded, tuple) else loaded
            # unflatten against the freshly-initialized optimizer state:
            # same config ⇒ identical structure/leaf order
            fresh_def = jax.tree_util.tree_structure(model._opt_state)
            model._opt_state = jax.tree_util.tree_unflatten(
                fresh_def, [jax.numpy.asarray(l) for l in leaves])
        return model

    @staticmethod
    def restoreMultiLayerNetwork(path, loadUpdater=True):
        return ModelSerializer._restore(path, loadUpdater, "MultiLayerNetwork")

    @staticmethod
    def restoreComputationGraph(path, loadUpdater=True):
        return ModelSerializer._restore(path, loadUpdater, "ComputationGraph")

    @staticmethod
    def restoreModel(path, loadUpdater=True):
        return ModelSerializer._restore(path, loadUpdater, None)

    @staticmethod
    def addNormalizerToModel(path, normalizer):
        with zipfile.ZipFile(path, "a") as zf:
            zf.writestr(NORMALIZER_PKL, pickle.dumps(normalizer))

    @staticmethod
    def restoreNormalizerFromFile(path):
        with zipfile.ZipFile(path, "r") as zf:
            if NORMALIZER_PKL not in zf.namelist():
                return None
            return pickle.loads(zf.read(NORMALIZER_PKL))
