"""OOM crash reporting (≡ deeplearning4j-core ::
org.deeplearning4j.util.CrashReportingUtil).

Reference behavior: when training/inference dies with an OOM, DL4J
writes a `dl4j-memory-crash-dump-<ts>.txt` with JVM/device memory state,
network configuration, and per-layer memory use; enabled by default,
`CrashReportingUtil.crashDumpsEnabled(false)` to turn off.

TPU equivalent: on an XLA RESOURCE_EXHAUSTED (HBM exhausted) escaping
`fit()`/`output()`, write a report with per-device memory stats (live
HBM bytes on TPU backends), per-layer parameter/updater footprints, the
training configuration, and the TPU-specific mitigations this framework
ships (per-layer remat, ZeRO-1 optimizer sharding, bf16, smaller batch,
gradient accumulation). The dump is advisory and never masks the
original exception.
"""
from __future__ import annotations

import datetime
import os
import traceback

import numpy as np

__all__ = ["CrashReportingUtil"]

import re

#: word-bounded so e.g. a tensor named "BLOOM_head" in a ValueError does
#: not read as an OOM
_OOM_RE = re.compile(
    r"RESOURCE_EXHAUSTED|[Oo]ut of memory|\bOOM\b|Allocation failure"
    r"|failed to allocate")


def _tree_bytes(tree):
    total = 0
    for leaf in _leaves(tree):
        total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
    return total


def _leaves(tree):
    import jax
    return [l for l in jax.tree_util.tree_leaves(tree)
            if hasattr(l, "shape") and hasattr(l, "dtype")]


class CrashReportingUtil:
    _enabled = True
    _directory = "."

    @staticmethod
    def crashDumpsEnabled(enabled):
        CrashReportingUtil._enabled = bool(enabled)

    @staticmethod
    def crashDumpOutputDirectory(directory=None):
        if directory is not None:
            CrashReportingUtil._directory = str(directory)
        return CrashReportingUtil._directory

    @staticmethod
    def is_oom(exception):
        msg = f"{type(exception).__name__}: {exception}"
        return _OOM_RE.search(msg) is not None

    @staticmethod
    def maybe_dump(model, exception):
        """Write a crash dump if reporting is enabled and the exception
        looks like device OOM. Returns the path or None; never raises.
        Dumps once per exception object — nested decorated calls
        (output() inside a fit() listener) do not dump twice."""
        try:
            if not CrashReportingUtil._enabled or \
                    not CrashReportingUtil.is_oom(exception) or \
                    getattr(exception, "_dl4j_tpu_dumped", False):
                return None
            path = CrashReportingUtil.writeMemoryCrashDump(model, exception)
            try:
                exception._dl4j_tpu_dumped = True
            except Exception:  # noqa: BLE001 — exceptions w/o __dict__
                pass
            return path
        except Exception:  # noqa: BLE001 — never mask the original error
            return None

    @staticmethod
    def writeMemoryCrashDump(model, exception, path=None):
        ts = datetime.datetime.now().strftime("%Y%m%d-%H%M%S")
        if path is None:
            base = os.path.join(
                CrashReportingUtil._directory,
                f"dl4j-tpu-memory-crash-dump-{ts}-{os.getpid()}")
            path, n = f"{base}.txt", 0
            while os.path.exists(path):   # two OOMs in one second
                n += 1
                path = f"{base}-{n}.txt"
        lines = [f"deeplearning4j_tpu memory crash dump ({ts})", "=" * 60, ""]
        lines.append("Exception:")
        lines.append("".join(traceback.format_exception_only(
            type(exception), exception)).strip())
        lines.append("")

        # device memory state (TPU backends expose memory_stats)
        try:
            import jax
            for d in jax.local_devices():
                stats = getattr(d, "memory_stats", lambda: None)() or {}
                lines.append(f"Device {d}:")
                if stats:
                    for k in sorted(stats):
                        lines.append(f"  {k}: {stats[k]:,}")
                else:
                    lines.append("  (no memory_stats on this backend)")
        except Exception as e:  # noqa: BLE001 — report what we can
            lines.append(f"(device query failed: {e})")
        lines.append("")

        # per-layer parameter footprint
        params = getattr(model, "_params", None)
        if params:
            lines.append("Parameters by layer:")
            total = 0
            for name in params:
                b = _tree_bytes(params[name])
                total += b
                shapes = {k: tuple(v.shape) for k, v in params[name].items()
                          if hasattr(v, "shape")}
                lines.append(f"  {name}: {b:,} bytes  {shapes}")
            lines.append(f"  TOTAL params: {total:,} bytes")
            opt = getattr(model, "_opt_state", None)
            if opt is not None:
                lines.append(f"  updater state: {_tree_bytes(opt):,} bytes")
        lines.append("")

        conf = getattr(model, "conf", None)
        if conf is not None:
            lines.append(f"Configuration: {type(conf).__name__} "
                         f"(layers: {len(getattr(conf, 'layers', []) or [])})")
        lines.append("")

        # OOM forensics: the LAST telemetry reading taken BEFORE the
        # crash (monitoring/memory.py sample()) — after an OOM the
        # allocator has often unwound, so the live post-mortem numbers
        # above under-report the spike; this is the last-known-good view
        try:
            from deeplearning4j_tpu.monitoring import memory as _mem
            snap = _mem.last_sample()
            if snap is not None:
                age = datetime.datetime.now().timestamp() - snap["ts"]
                lines.append(f"Device memory telemetry "
                             f"(last reading, {age:.1f}s before dump):")
                for dev, stats in snap["devices"].items():
                    if stats:
                        keep = {k: stats[k] for k in
                                ("bytes_in_use", "peak_bytes_in_use",
                                 "bytes_limit") if k in stats}
                        lines.append(f"  {dev}: " + ", ".join(
                            f"{k}={v:,}" for k, v in keep.items()))
                    else:
                        lines.append(f"  {dev}: (no memory_stats)")
                if "model" in snap:
                    lines.append("  model footprint: " + ", ".join(
                        f"{k}={v:,}" for k, v in snap["model"].items()))
                lines.append("")
        except Exception as e:  # noqa: BLE001 — dumps must never raise
            lines.append(f"(memory telemetry unavailable: {e})")
            lines.append("")

        # step-time flight recorder: percentile summary + the last few
        # per-step attribution records (monitoring/steps.py) — "what was
        # each step doing right before the OOM"
        try:
            from deeplearning4j_tpu.monitoring import steps as _steps
            rec = _steps.recorder()
            if rec.records(last=1):
                lines.append("Step-time flight recorder:")
                lines.extend(rec.crash_lines())
                lines.append("")
        except Exception as e:  # noqa: BLE001
            lines.append(f"(flight recorder unavailable: {e})")
            lines.append("")

        # ops event journal tail: the SAME section stall and peer
        # reports embed (monitoring/events.py) — the ordered causal
        # record leading into this crash, plus the machine-readable
        # post-mortem bundle alongside the text dump
        try:
            from deeplearning4j_tpu import monitoring as _mon
            from deeplearning4j_tpu.monitoring import events as _events
            lines.extend(_events.event_tail_lines())
            lines.append("")
            if _mon.enabled():
                bundle_path = _events.write_bundle(
                    dump_dir=os.path.dirname(path) or None,
                    headline=f"memory crash dump: see {path}")
                lines.append(f"Post-mortem bundle: "
                             f"{bundle_path or '(failed)'}")
                lines.append("")
        except Exception as e:  # noqa: BLE001 — dumps must never raise
            lines.append(f"(event journal unavailable: {e})")
            lines.append("")

        # monitoring snapshot: what was the process DOING at OOM time?
        # (counters tell the story so far, the open span stack tells the
        # phase that died). Only when monitoring is on — the dump must
        # not wake the subsystem up.
        try:
            from deeplearning4j_tpu import monitoring as _mon
            if _mon.enabled():
                lines.append("Monitoring at crash time:")
                stack = _mon.get_tracer().current_stack()
                lines.append("  open spans: "
                             + (" > ".join(stack) if stack else "(none)"))
                snap = _mon.get_registry().snapshot()
                for name in sorted(snap):
                    for rec in snap[name]:
                        lbl = "".join(f"[{k}={v}]"
                                      for k, v in rec["labels"].items())
                        if rec["kind"] == "histogram":
                            lines.append(
                                f"  {name}{lbl}: count={rec['count']} "
                                f"sum={rec['sum']:.6g} p99={rec['p99']}")
                        else:
                            lines.append(f"  {name}{lbl}: {rec['value']}")
                lines.append("")
        except Exception as e:  # noqa: BLE001 — dumps must never raise
            lines.append(f"(monitoring snapshot failed: {e})")
            lines.append("")
        lines.append("Mitigations (TPU):")
        lines.append("  - reduce the batch size (HBM high-water scales ~"
                     "linearly with batch)")
        lines.append("  - enable per-layer rematerialization: "
                     "layer.remat(True) / BertConfig(remat=True)")
        lines.append("  - shard optimizer state: ParallelWrapper."
                     "shardOptimizerState(True) (ZeRO-1)")
        lines.append("  - train in bfloat16 (dtype='bfloat16' on layers)")
        lines.append("  - split the step: fit(it, stepsPerDispatch=1) and "
                     "smaller iterator batches")
        with open(path, "w") as f:
            f.write("\n".join(lines) + "\n")
        return path


def with_crash_dump(fn):
    """Decorator for fit()/output(): on an escaping device-OOM, write the
    crash dump (when enabled), note its path on stderr, re-raise."""
    import functools
    import sys

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        try:
            return fn(self, *args, **kwargs)
        except Exception as e:
            path = CrashReportingUtil.maybe_dump(self, e)
            if path:
                print(f"[deeplearning4j_tpu] device OOM — memory crash "
                      f"dump written to {path}", file=sys.stderr)
            raise
    return wrapper
