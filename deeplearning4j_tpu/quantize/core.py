"""Quantization primitives: per-channel symmetric scales, int8
quantize/dequantize, straight-through-estimator fake-quant, and the int8
GEMM with a fused dequant epilogue.

Why this exists (ROADMAP item 3, BENCH_r04): the ResNet-50 step runs at
93.7% of the HBM-bandwidth roof — XLA knobs are exhausted, the remaining
lever is moving FEWER BYTES. The cuDNN paper's precision argument applies
directly: half (or a quarter) of the activation bytes is half (a quarter)
of the traffic on a bandwidth-bound step. Everything here is symmetric
int8 (no zero-points): TPU MXUs take int8×int8→int32 natively, symmetric
scales keep the epilogue a single fused multiply, and the absence of a
zero-point term keeps the GEMM exactly `acc * (sx*sw)` — no cross terms.

Two executable strategies for the SAME arithmetic, chosen per backend:

- ``int8_dot``: the canonical int8×int8→int32 `lax.dot_general` — one
  MXU-native kernel on TPU. (On XLA:CPU this lowers to a scalar loop;
  the inference rewriter in `quantize/infer.py` uses the cache-resident
  tiled strategy there instead — see its module docstring.)
- ``scaled_int8_dot``: int-valued operands contracted in float32 with
  the dequant scales folded into the epilogue. For |q| <= 127 and
  K <= 2^10 every product (< 2^14) and partial sum (< 2^24) is exactly
  representable in float32, so this is BIT-equivalent to int32
  accumulation followed by a float multiply — it exists because XLA:CPU
  has no fast int8 GEMM lowering while its f32 GEMM runs near peak.

Gradients: training never calls the real int8 path. QAT uses
``fake_quant`` — forward quantize→dequantize, backward straight-through
(gradient passes unchanged inside the clip range, zero outside), the
standard STE from Jacob et al. / the cuDNN-paper lineage.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

#: symmetric int8 range: [-127, 127] (−128 unused, keeps |q| symmetric so
#: the MXU's int8×int8 products never overflow int16 pairs)
INT8_MAX = 127.0

__all__ = [
    "INT8_MAX", "per_channel_scales", "per_tensor_scale", "quantize",
    "dequantize", "fake_quant", "int8_dot", "scaled_int8_dot",
    "dequant_epilogue",
]


def per_channel_scales(w, channel_axis=-1):
    """Symmetric per-output-channel scales for a weight tensor: one
    float32 scale per channel, absmax/127, zero-guarded (an all-zero
    channel gets scale 1 so q = 0 round-trips)."""
    axes = tuple(i for i in range(w.ndim) if i != channel_axis % w.ndim)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=axes)
    return jnp.where(amax > 0, amax / INT8_MAX, 1.0)


def per_tensor_scale(x):
    """Symmetric whole-tensor scale (activations): absmax/127."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)))
    return jnp.where(amax > 0, amax / INT8_MAX, 1.0)


def _broadcast_scale(x, scale, channel_axis):
    """Scale shaped to broadcast against x: scalar as-is, a per-channel
    vector reshaped onto `channel_axis`. THE one broadcast rule shared
    by quantize/dequantize/fake_quant (they must never disagree)."""
    s = jnp.asarray(scale, jnp.float32)
    if channel_axis is not None and s.ndim == 1:
        shape = [1] * x.ndim
        shape[channel_axis % x.ndim] = s.shape[0]
        s = s.reshape(shape)
    return s


def quantize(x, scale, channel_axis=None):
    """x/scale, rounded and clipped to [-127, 127], as int8. `scale` is
    a scalar (per-tensor) or a per-channel vector (then `channel_axis`
    names the axis it broadcasts over)."""
    s = _broadcast_scale(x, scale, channel_axis)
    q = jnp.round(x.astype(jnp.float32) / s)
    return jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8)


def dequantize(q, scale, channel_axis=None, dtype=jnp.float32):
    s = _broadcast_scale(q, scale, channel_axis)
    return (q.astype(jnp.float32) * s).astype(dtype)


# -- QAT fake-quant (straight-through estimator) ----------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def fake_quant(x, scale, channel_axis=None):
    """quantize→dequantize in the forward pass; straight-through gradient
    in the backward pass (dx = dy inside the representable range
    [-127·s, 127·s], 0 where the forward CLIPPED — the clipped-STE that
    keeps QAT stable, values the int8 lattice cannot express stop pulling
    gradient). `scale` receives no gradient (recomputed from data each
    step by the callers)."""
    y, _ = _fake_quant_fwd(x, scale, channel_axis)
    return y


def _fake_quant_fwd(x, scale, channel_axis):
    s = _broadcast_scale(x, scale, channel_axis)
    xf = x.astype(jnp.float32)
    q = jnp.clip(jnp.round(xf / s), -INT8_MAX, INT8_MAX)
    y = (q * s).astype(x.dtype)
    inside = (jnp.abs(xf) <= INT8_MAX * s)
    return y, inside


def _fake_quant_bwd(channel_axis, inside, dy):
    dx = jnp.where(inside, dy, 0).astype(dy.dtype)
    return dx, None   # scale: no gradient (data-derived)


fake_quant.defvjp(_fake_quant_fwd, _fake_quant_bwd)


def fake_quant_weight(w, channel_axis=-1):
    """QAT weight fake-quant: per-output-channel dynamic scales from the
    CURRENT weights (scales track the weights as they train)."""
    return fake_quant(w, per_channel_scales(w, channel_axis), channel_axis)


def fake_quant_act(x):
    """QAT activation fake-quant: per-tensor dynamic absmax scale."""
    return fake_quant(x, per_tensor_scale(x), None)


# -- the int8 GEMM ----------------------------------------------------------
def int8_dot(xq, wq):
    """int8 (..., K) × int8 (K, N) → int32 (..., N): the canonical
    quantized contraction over the trailing axis. Lowers to one
    MXU-native kernel on TPU; on XLA:CPU the lowering is a scalar
    loop — prefer `scaled_int8_dot` there."""
    return lax.dot_general(xq, wq, (((xq.ndim - 1,), (0,)), ((), ())),
                           preferred_element_type=jnp.int32)


def scaled_int8_dot(xq, wq, out_scale):
    """The same contraction computed exactly in float32: int-valued
    operands (|q| <= 127) contracted with preferred f32 and the dequant
    scale applied after. For K <= 2^10 every partial sum fits in f32's
    24-bit mantissa, so this equals int32 accumulation bit-for-bit —
    it exists for backends (XLA:CPU) whose f32 GEMM is the only fast
    GEMM. `out_scale`: scalar or (N,) per-channel dequant factor."""
    xf = xq.astype(jnp.float32)
    acc = lax.dot_general(xf, wq.astype(jnp.float32),
                          (((xf.ndim - 1,), (0,)), ((), ())),
                          preferred_element_type=jnp.float32)
    return acc * out_scale


def dequant_epilogue(acc, scale, bias=None, residual=None, act=None):
    """The fused dequant+bias+activation epilogue over a raw int32 (or
    exactly-int-valued f32) accumulator: y = act(acc·scale + bias
    [+ residual]). One elementwise pass; XLA fuses it into the
    accumulator's consumer chain so the int32 tensor never round-trips
    HBM on its own."""
    y = acc.astype(jnp.float32) * scale
    if bias is not None:
        y = y + bias
    if residual is not None:
        y = y + residual.astype(jnp.float32)
    if act is not None:
        from deeplearning4j_tpu.nn.activations import get_activation
        y = get_activation(act)(y)
    return y
