"""PrecisionPolicy — the layer-conf DSL's quantization knob.

One object describes BOTH halves of the int8 story:

- **training** (QAT): layers carrying a policy fake-quantize their
  weights (per-output-channel scales) and input activations (per-tensor
  scale) inside the normal fp forward — gradients flow through the
  straight-through estimator, so the trained weights land on (near) the
  int8 lattice and the post-training int8 rewrite loses almost nothing.
- **inference**: `quantize.infer.quantize_network` consults the same
  policy to decide which layers get the REAL int8 path (int8 weights,
  int8×int8 contraction, fused dequant+bias+activation epilogue).

Wired through the conf DSL like every other inherited hyperparameter:

    NeuralNetConfiguration.Builder()
        .precisionPolicy(PrecisionPolicy.int8())
        ...                       # every layer inherits the policy
    DenseLayer.Builder().precisionPolicy(None)   # per-layer opt-out

Output layers are excluded by default (`quantize_heads=False`) — the
classifier head's logits are the one place int8 resolution visibly moves
top-1 decisions.
"""
from __future__ import annotations

__all__ = ["PrecisionPolicy"]


class PrecisionPolicy:
    """Symmetric int8 precision policy.

    weights / activations: fake-quant the respective tensors during QAT
    (the real int8 inference path always quantizes both).
    quantize_heads: include output/loss-head layers.
    min_channels: skip layers narrower than this (tiny layers gain
    nothing and lose the most resolution)."""

    kind = "int8"

    def __init__(self, weights=True, activations=True,
                 quantize_heads=False, min_channels=1, enabled=True):
        self.weights = bool(weights)
        self.activations = bool(activations)
        self.quantize_heads = bool(quantize_heads)
        self.min_channels = int(min_channels)
        self.enabled = bool(enabled)

    @staticmethod
    def int8(**kw):
        return PrecisionPolicy(**kw)

    @staticmethod
    def off():
        """The per-layer OPT-OUT sentinel: a disabled policy that
        shadows an inherited one. `.precisionPolicy(None)` on a layer
        builder resolves to this (a literal None would read as "unset"
        and inherit the global policy right back)."""
        return PrecisionPolicy(enabled=False)

    # -- eligibility -------------------------------------------------------
    def _head(self, layer):
        return hasattr(layer, "compute_loss")

    def applies_to(self, layer):
        """QAT eligibility: any dense/conv layer with a weight matrix —
        fake-quant only SIMULATES int8, so every kernel size qualifies."""
        from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                       DenseLayer)
        if not self.enabled:
            return False
        if self._head(layer) and not self.quantize_heads:
            return False
        if not isinstance(layer, (DenseLayer, ConvolutionLayer)):
            return False
        n = getattr(layer, "nOut", None)
        if n is not None and int(n) < self.min_channels:
            return False
        return True

    def int8_servable(self, layer):
        """REAL int8 path eligibility: dense layers and pad-free
        1×1 convolutions — the shapes that are a single GEMM with a
        per-channel dequant epilogue. Everything else stays fp and is
        counted on dl4j.quant.dequant_fallbacks by the rewriter."""
        from deeplearning4j_tpu.nn.conf.layers import (ConvolutionLayer,
                                                       DenseLayer)
        if not self.applies_to(layer):
            return False
        if type(layer) is DenseLayer or (
                self.quantize_heads and isinstance(layer, DenseLayer)
                and self._head(layer)):
            return True
        if type(layer) is ConvolutionLayer:
            pad_free = (str(layer.convolutionMode).lower() == "same"
                        or tuple(layer.padding) == (0, 0))
            return (tuple(layer.kernelSize) == (1, 1)
                    and tuple(layer.dilation) == (1, 1)
                    and pad_free
                    and layer.stride[0] == layer.stride[1]
                    and getattr(layer, "spaceToDepth", 1) == 1)
        return False

    def __repr__(self):
        if not self.enabled:
            return "PrecisionPolicy(off)"
        return (f"PrecisionPolicy(int8, weights={self.weights}, "
                f"activations={self.activations}, "
                f"quantize_heads={self.quantize_heads})")
