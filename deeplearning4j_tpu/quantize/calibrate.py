"""Activation-scale calibration for the real int8 inference path.

A quantized layer needs ONE static number the trace can bake in: the
symmetric scale of its input activations. Two sources, in preference
order (both land on `dl4j.quant.calibrations`):

- **BN/moving statistics** (free — no data pass): a layer fed by a
  BatchNormalization's output has a known post-affine distribution —
  per channel mean≈beta, std≈gamma — so absmax ≈ max_c(|beta_c| +
  k·|gamma_c|) with k standard deviations of headroom (k=4 covers
  99.99% of a gaussian; clipping the tail is what symmetric int8 does
  anyway). This is how the ResNet-style hot path calibrates without
  ever seeing data: every 1×1 conv sits behind a BN.
- **observed absmax** (one fp forward over calibration batches):
  `observe()` runs the fp net over sample data and records each
  layer input's absmax; the classic max-calibration pass.

`resolve_scales` merges both: observed wins where present, BN-derived
fills the gaps, and anything still unknown falls back to scale-from-
weight-headroom (conservative; flagged in the result so callers can tell
a guessed scale from a calibrated one).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.quantize.core import INT8_MAX

__all__ = ["bn_param_scale", "observe", "resolve_scales"]

#: standard deviations of post-BN headroom baked into the derived scale
BN_SIGMA_K = 4.0

#: scale assumed when neither statistics nor data are available —
#: generous for relu-family activations; flagged as "default" so the
#: caller can surface it
DEFAULT_ABSMAX = 8.0


def bn_param_scale(p_bn, k=BN_SIGMA_K):
    """Input scale for a layer fed by a BatchNormalization, from the
    BN's LIVE gamma/beta (no data needed): the normalized-then-affine
    activation is per-channel ≈ N(beta_c, gamma_c²), so
    absmax ≈ max_c(|beta_c| + k·|gamma_c|). A relu after the BN only
    clips negatives — the positive absmax bound is unchanged."""
    gamma = np.asarray(p_bn.get("gamma", np.ones(1)), np.float32)
    beta = np.asarray(p_bn.get("beta", np.zeros(1)), np.float32)
    absmax = float(np.max(np.abs(beta) + k * np.abs(gamma)))
    return max(absmax, 1e-6) / INT8_MAX


def observe(forward_collect, batches):
    """Max-calibration pass: `forward_collect(x) -> {key: activation}`
    runs the fp net and returns each quantizable layer's INPUT tensor
    keyed by layer; `batches` is an iterable of feature arrays. Returns
    {key: absmax float} over all batches."""
    absmax = {}
    for x in batches:
        for key, act in forward_collect(x).items():
            m = float(jnp.max(jnp.abs(act.astype(jnp.float32))))
            prev = absmax.get(key)
            absmax[key] = m if prev is None else max(prev, m)
    return absmax


def resolve_scales(keys, observed=None, bn_scales=None):
    """Merge calibration sources into {key: (scale, source)} for every
    key in `keys`. observed: {key: absmax}; bn_scales: {key: scale}.
    Priority: observed > bn-derived > DEFAULT_ABSMAX fallback."""
    observed = observed or {}
    bn_scales = bn_scales or {}
    out = {}
    calibrated = 0
    for key in keys:
        if key in observed:
            out[key] = (max(observed[key], 1e-6) / INT8_MAX, "observed")
            calibrated += 1
        elif key in bn_scales:
            out[key] = (bn_scales[key], "bn-stats")
            calibrated += 1
        else:
            out[key] = (DEFAULT_ABSMAX / INT8_MAX, "default")
    if _mon.enabled() and calibrated:
        _mon.get_registry().counter(
            _mon.QUANT_CALIBRATIONS,
            help="activation scales calibrated (observed or BN-derived)"
        ).inc(calibrated)
    return out
