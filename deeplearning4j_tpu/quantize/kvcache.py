"""int8 KV-cache codec for the generation decode path.

Steady-state decode traffic is dominated by reading the whole K/V cache
once per token (the single-query attention is a GEMV — pure bandwidth).
Storing the cache int8 with one float32 scale per (head, position) row
cuts that read to ~¼ (bf16 caches: ~½) at a per-row quantization error
attention's softmax largely absorbs — the PR 8 decode tests hold the
int8 token stream to the fp stream within tolerance.

Layout: alongside each `(..., C, D)` cache tensor rides a `(..., C)`
float32 scale tensor — "per-head scales": every head quantizes each of
its cached rows against that row's own absmax, so one outlier head (or
one outlier position) cannot crush the resolution of the rest.

Dequantization happens INSIDE `flash_attention_decode` (the scales ride
into the attention contraction as epilogue multipliers — for the score
pass the row scale folds onto the logits, for the value pass it folds
onto the softmax weights), so no dequantized fp copy of the cache is
ever materialized in HBM.

The codec composes with the paged KV layout unchanged: an int8 page is
the same `(H, ps, Dh)` block plus its `(H, ps)` scale page, so paging
halves again on top of the int8 ¼ — `cache_page_bytes` is the one
place that arithmetic lives (the bench ledger and the pool-sizing docs
both read it)."""
from __future__ import annotations

import jax.numpy as jnp

from deeplearning4j_tpu.quantize.core import INT8_MAX

__all__ = ["quantize_rows", "dequantize_rows", "cache_page_bytes"]


def cache_page_bytes(layers, heads, page_size, head_dim, kv_dtype="fp",
                     dtype_bytes=4):
    """HBM bytes one physical KV page costs across all layers: K and V
    blocks of `(heads, page_size, head_dim)` per layer — int8 pages pay
    1 byte/element plus the per-(head, row) float32 scale columns, fp
    pages pay `dtype_bytes`. Host-side sizing arithmetic only (pool
    provisioning, the paged bench's bytes-saved ledger); nothing here
    touches a device value."""
    rows = int(heads) * int(page_size)
    if kv_dtype == "int8":
        per = rows * int(head_dim) * 1 + rows * 4   # payload + scales
    else:
        per = rows * int(head_dim) * int(dtype_bytes)
    return 2 * int(layers) * per                    # K and V pools


def quantize_rows(x):
    """Per-row symmetric int8: x (..., D) → (q int8 (..., D), scale f32
    (...,)) with scale = absmax(row)/127 (all-zero rows get scale 1 so
    they round-trip to zeros)."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / INT8_MAX, 1.0)
    q = jnp.round(xf / scale[..., None])
    q = jnp.clip(q, -INT8_MAX, INT8_MAX).astype(jnp.int8)
    return q, scale


def dequantize_rows(q, scale, dtype=jnp.float32):
    """Inverse of quantize_rows (materializing — prefer the fused
    in-attention dequant on the hot path)."""
    return (q.astype(jnp.float32) * scale[..., None]).astype(dtype)
