"""Quantization + memory-traffic diet (ROADMAP item 3).

The ResNet-50 step measured at 93.7% of the HBM-bandwidth roof
(BENCH_r04_local) — XLA knobs exhausted; the remaining single-chip
lever is moving fewer bytes. This package is that lever:

- `core` — symmetric int8 primitives: per-channel scales, quantize /
  dequantize, the straight-through-estimator `fake_quant`, the
  int8×int8→int32 contraction and its exact f32 twin, and the fused
  dequant+bias+activation epilogue.
- `policy` — `PrecisionPolicy`: the conf-DSL knob
  (`.precisionPolicy(PrecisionPolicy.int8())`) driving training-time
  QAT fake-quant AND the inference rewrite's eligibility.
- `calibrate` — activation-scale calibration: observed absmax over
  sample batches, or derived from BatchNorm statistics (data-free).
- `infer` — `quantize_network(net)`: the post-training rewrite to an
  inference-only int8 twin (BN folding, fused epilogues, and the
  cache-resident tiled chain executor for pointwise/residual runs),
  served through ExecutableStore / ParallelInference unchanged.
- `kvcache` — int8 KV-cache codec for the generation decode path
  (per-head row scales, dequant inside attention).
- `traffic` — the bytes ledger: activation-traffic / saved-for-backward
  estimates by precision + remat policy, published to
  `dl4j.quant.activation_traffic_bytes`.
"""
from deeplearning4j_tpu.quantize.core import (  # noqa: F401
    INT8_MAX, dequant_epilogue, dequantize, fake_quant, fake_quant_act,
    fake_quant_weight, int8_dot, per_channel_scales, per_tensor_scale,
    quantize, scaled_int8_dot)
from deeplearning4j_tpu.quantize.policy import (  # noqa: F401
    PrecisionPolicy)
from deeplearning4j_tpu.quantize.infer import (  # noqa: F401
    QuantPassthrough, QuantizedConv1x1, QuantizedDense,
    quantize_network)
from deeplearning4j_tpu.quantize.traffic import (  # noqa: F401
    activation_report, publish)

__all__ = [
    "INT8_MAX", "PrecisionPolicy", "QuantPassthrough",
    "QuantizedConv1x1", "QuantizedDense", "activation_report",
    "dequant_epilogue", "dequantize", "fake_quant", "fake_quant_act",
    "fake_quant_weight", "int8_dot", "per_channel_scales",
    "per_tensor_scale", "publish", "quantize", "quantize_network",
    "scaled_int8_dot",
]
