"""Activation-traffic estimator: how many bytes does a model's forward
(and backward) actually move, under which precision/remat policy?

The BENCH roofline work (BENCH_r04_local: 93.7% of the HBM bound) made
bytes the currency of this repo's perf axis — so the diet needs a
ledger. This module walks a built configuration and prices every
activation tensor at its policy-resolved width:

- ``activation_report``: per-layer/per-node activation sizes for one
  batch, split into forward traffic (every activation written once) and
  **backward saved bytes** (what autodiff keeps for the backward pass) —
  under the model's remat policy, "blocks" keeps only segment
  boundaries, "layers"/flagged layers keep only layer inputs.
- ``publish``: pushes the estimate onto the
  ``dl4j.quant.activation_traffic_bytes`` gauge (labels: model, policy)
  so `GET /metrics` shows the diet per served model.

Estimates price TENSOR TRAFFIC, not compute: elementwise passes XLA
fuses away are not modeled, so treat the numbers as a policy-relative
comparison (fp32 vs int8 vs remat), which is exactly how bench_quant.py
uses them (the remat acceptance bar is the RATIO of saved-for-backward
bytes, not an absolute).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from deeplearning4j_tpu import monitoring as _mon

__all__ = ["activation_report", "publish"]


def _dtype_bytes(conf, layer=None):
    from deeplearning4j_tpu.ops.ndarray import resolve_dtype
    dt = resolve_dtype(conf.data_type) or jnp.float32
    return jnp.dtype(dt).itemsize


def _quantized_width(layer):
    """Bytes/element of the layer's OUTPUT under its precision state:
    rewritten int8 layers store int8 activations at memory boundaries."""
    return 1 if type(layer).__name__ in ("QuantizedConv1x1",
                                         "QuantizedDense") else None


def _type_elems(t):
    if t is None:
        return 0
    shape = t.shape() if callable(getattr(t, "shape", None)) else None
    if not shape:
        return 0
    return int(np.prod([d for d in shape if d]))


def activation_report(net, batch=1):
    """{'per_layer': [...], 'forward_bytes': n, 'saved_bytes': n,
    'saved_bytes_plain': n, 'remat_policy': p, 'policy': str} for one
    forward/backward at `batch` rows.

    saved_bytes: LAYER-OUTPUT activations kept for backward under the
    active remat policy; saved_bytes_plain: the same without remat —
    the reduction ratio is the remat diet. For "blocks" the kept set
    is `conf.remat_plan()`'s saved outputs — the SAME rule the graph
    executor saves by, so the ledger cannot drift from reality on
    interleaved/branching graphs. Per-layer remat ("layers" / .remat
    flags) is NOT a diet at this granularity: jax.checkpoint on a
    single layer still saves that layer's INPUT (= the previous
    layer's output), so every boundary tensor stays live — its wins
    are the intra-layer intermediates this output-level ledger does
    not price, and it is reported as saving nothing here rather than
    as a fictitious ~100% cut."""
    conf = net.conf
    base = _dtype_bytes(conf)
    per = []
    is_graph = hasattr(conf, "topo_order")
    policy = getattr(conf, "remat_policy", "none")
    if is_graph:
        names = [n for n in conf.topo_order
                 if conf.nodes[n].kind != "input"]
        kept = set(names)
        if policy == "blocks":
            kept = {n for _seg, outs in conf.remat_plan()
                    for n in outs}
        for name in names:
            node = conf.nodes[name]
            t = conf.node_output_types.get(name)
            elems = _type_elems(t) * int(batch)
            width = (_quantized_width(node.ref)
                     if node.kind == "layer" else None) or base
            per.append({"name": name, "elements": elems,
                        "bytes": elems * width,
                        "saved": name in kept})
    else:
        # sequential nets only carry per-layer remat flags — every
        # layer output stays saved at this granularity (see docstring)
        for i, layer in enumerate(conf.layers):
            t = conf.input_types[i] if conf.input_types else None
            t_out = layer.output_type(t) if t is not None else None
            elems = _type_elems(t_out) * int(batch)
            width = _quantized_width(layer) or base
            per.append({"name": getattr(layer, "name", str(i)),
                        "elements": elems, "bytes": elems * width,
                        "saved": True})
    fwd = sum(p["bytes"] for p in per)
    saved = sum(p["bytes"] for p in per if p["saved"])
    plain = fwd
    qp = (getattr(conf, "defaults", {}) or {}).get("precisionPolicy")
    return {"per_layer": per, "forward_bytes": int(fwd),
            "saved_bytes": int(saved), "saved_bytes_plain": int(plain),
            "remat_policy": policy,
            "policy": repr(qp) if qp is not None else "fp"}


def publish(net, batch=1, model_name=None):
    """Estimate + publish the per-model activation-traffic gauge
    (no-op when monitoring is disabled). Returns the report."""
    rep = activation_report(net, batch)
    if _mon.enabled():
        name = model_name or type(net).__name__
        _mon.get_registry().gauge(
            _mon.QUANT_ACTIVATION_BYTES,
            labels={"model": name, "policy": rep["policy"]},
            help="estimated forward activation traffic per batch, "
                 "priced at each tensor's precision-policy width"
        ).set(rep["forward_bytes"])
    return rep
