"""Real int8 inference: post-training network rewrite + fused execution.

`quantize_network(net)` takes a TRAINED MultiLayerNetwork or
ComputationGraph and returns an INFERENCE-ONLY twin whose eligible
layers (dense, pad-free 1×1 convolutions — the policy's
`int8_servable` set) carry int8 weights with per-output-channel scales
and execute through an int8 contraction with a fused
dequant+bias+activation epilogue. A 1×1 conv feeding only a
BatchNormalization absorbs the BN's inference affine INTO that epilogue
(the BN node degrades to a pass-through), so conv+BN+act is one GEMM +
one fused elementwise tail — no standalone BN pass. Ineligible
weight-bearing layers stay fp and are counted on
`dl4j.quant.dequant_fallbacks`.

Execution strategies (`impl=`, default "auto"):

- **"dot"** — the canonical int8×int8→int32 `lax.dot_general` per layer
  (`quantize/core.int8_dot`), MXU-native on TPU. Activations quantize at
  every layer boundary.
- **"chain"** — the CPU-tuned shape (auto default off-TPU, where XLA
  lowers int8 contractions to a scalar loop): maximal runs of quantized
  pointwise layers — including residual adds and relu/identity
  activations — execute as ONE cache-resident tiled pipeline:
  `lax.scan` over row tiles, each tile dequantized once, pushed through
  the whole run's GEMMs/epilogues/residuals while resident in cache,
  and requantized to int8 on the single write back out. RAM sees int8
  at run boundaries and nothing in between — the measured-write-
  bandwidth-bound regime this box's BENCH profile lives in. Chain
  entry/exit are the only activation-quantization points (strictly
  less rounding error than per-layer "dot").

The rewritten net keeps the original layer/node names and indices
(folded BN nodes become `QuantPassthrough`), so `ExecutableStore` /
`ParallelInference` serve it exactly like any model — the
model fingerprint changes with the int8 param trees, so quantized
executables cache separately from their fp twins.
"""
from __future__ import annotations

import copy

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from deeplearning4j_tpu import monitoring as _mon
from deeplearning4j_tpu.quantize import calibrate as _cal
from deeplearning4j_tpu.quantize.core import (INT8_MAX, dequant_epilogue,
                                              int8_dot,
                                              per_channel_scales, quantize)
from deeplearning4j_tpu.quantize.policy import PrecisionPolicy

__all__ = ["QuantizedConv1x1", "QuantizedDense", "QuantPassthrough",
           "quantize_network"]

#: rows per cache-resident tile of the chain executor — 1568×C f32
#: stays comfortably inside L2 for the channel widths the policy admits
CHAIN_TILE_ROWS = 1568


def _default_impl():
    return "dot" if jax.default_backend() == "tpu" else "chain"


# -- quantized layer confs --------------------------------------------------
class _QuantLayerBase:
    """Conf-object contract shared with nn.conf.layers.Layer — enough
    surface for the network classes, serde, and summary()."""

    updater = None
    constraints = None
    dropOut = None
    frozen = False

    def apply_defaults(self, defaults):
        return self

    def regularization_terms(self):
        return 0.0, 0.0

    def feed_forward_mask(self, mask):
        return mask

    def initialize(self, key, input_type):
        raise RuntimeError(
            f"{type(self).__name__} is produced by quantize_network() "
            "from a trained layer — it cannot initialize fresh params")


class QuantizedDense(_QuantLayerBase):
    """Dense layer served int8: y = act(int8dot(q(x), Wq)·scale + bias).

    params: Wq int8 (nIn, nOut); scale f32 (nOut,) = x_scale·w_scale;
    bias f32 (nOut,) or absent; x_scale f32 scalar (traced — a
    recalibration changes an argument, never the executable)."""

    def __init__(self, name, nIn, nOut, activation, hasBias, impl="auto"):
        self.name = name
        self.nIn, self.nOut = int(nIn), int(nOut)
        self.activation = activation
        self.hasBias = bool(hasBias)
        self.impl = impl

    def output_type(self, input_type):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        return InputType.feedForward(self.nOut)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        xq = quantize(x, params["x_scale"])
        impl = _default_impl() if self.impl == "auto" else self.impl
        if impl == "dot":
            acc = int8_dot(xq, params["Wq"])
        else:
            # exact f32 twin of the int32 accumulation (see core);
            # batched inputs (B, T, F) contract the trailing axis
            acc = xq.astype(jnp.float32) @ params["Wq"].astype(
                jnp.float32)
        y = dequant_epilogue(acc, params["scale"], params.get("bias"),
                             act=self.activation)
        return y.astype(x.dtype), state


class QuantizedConv1x1(_QuantLayerBase):
    """Pad-free 1×1 conv served int8 as a GEMM over the flattened
    spatial axis, with any following BatchNormalization folded into the
    dequant epilogue (scale ← x_scale·w_scale·γr, bias ← conv-bias·γr +
    (β − γμr)) and the BN's activation fused behind it."""

    is_pointwise = True

    def __init__(self, name, nIn, nOut, activation, stride=1,
                 impl="auto"):
        self.name = name
        self.nIn, self.nOut = int(nIn), int(nOut)
        self.activation = activation
        self.stride = int(stride)
        self.impl = impl

    def output_type(self, input_type):
        from deeplearning4j_tpu.nn.conf.inputs import InputType
        s = self.stride
        return InputType.convolutional(
            -(-input_type.height // s), -(-input_type.width // s),
            self.nOut)

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        if self.stride > 1:
            x = x[:, ::self.stride, ::self.stride, :]
        b, h, w, c = x.shape
        xf = x.reshape(b * h * w, c)
        xq = quantize(xf, params["x_scale"])
        impl = _default_impl() if self.impl == "auto" else self.impl
        if impl == "dot":
            acc = int8_dot(xq, params["Wq"])
        else:
            acc = lax.dot_general(
                xq.astype(jnp.float32), params["Wq"].astype(jnp.float32),
                (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        y = dequant_epilogue(acc, params["scale"], params.get("bias"),
                             act=self.activation)
        return y.astype(x.dtype).reshape(b, h, w, self.nOut), state


class QuantPassthrough(_QuantLayerBase):
    """Stand-in for a layer whose work was folded into the quantized
    layer before it (a BN absorbed into a conv epilogue). Keeps the
    layer list / node graph shape-stable: names, indices, preprocessor
    slots, and serialization all survive the rewrite."""

    def __init__(self, name, folded_into):
        self.name = name
        self.folded_into = folded_into
        self.activation = "identity"

    def output_type(self, input_type):
        return input_type

    def apply(self, params, state, x, train=False, rng=None, mask=None):
        return x, state


# -- weight/BN folding ------------------------------------------------------
def _fold_dense(layer, p):
    w = np.asarray(p["W"], np.float32)
    w_scale = np.asarray(per_channel_scales(w, -1))
    wq = np.asarray(quantize(jnp.asarray(w), jnp.asarray(w_scale), 1))
    out = {"Wq": jnp.asarray(wq), "w_scale": w_scale}
    if layer.hasBias and "b" in p:
        out["bias"] = jnp.asarray(np.asarray(p["b"], np.float32))
    return out


def _fold_conv_bn(conv, p_conv, bn, p_bn, s_bn):
    """int8 weights + the conv-bias/BN affine folded to ONE epilogue
    scale/bias pair (missing BN → plain conv epilogue)."""
    w = np.asarray(p_conv["W"], np.float32)
    cin, cout = w.shape[2], w.shape[3]
    w2 = w.reshape(cin, cout)
    w_scale = np.asarray(per_channel_scales(jnp.asarray(w2), -1))
    wq = np.asarray(quantize(jnp.asarray(w2), jnp.asarray(w_scale), 1))
    bias = (np.asarray(p_conv["b"], np.float32)
            if getattr(conv, "hasBias", False) and "b" in p_conv
            else np.zeros(cout, np.float32))
    a = np.ones(cout, np.float32)
    b = np.zeros(cout, np.float32)
    if bn is not None:
        mean = np.asarray(s_bn["mean"], np.float32)
        var = np.asarray(s_bn["var"], np.float32)
        gamma = np.asarray(p_bn.get("gamma", np.ones(cout)), np.float32)
        beta = np.asarray(p_bn.get("beta", np.zeros(cout)), np.float32)
        inv = 1.0 / np.sqrt(var + bn.eps)
        a = gamma * inv
        b = beta - gamma * mean * inv
    return {"Wq": jnp.asarray(wq), "w_scale": w_scale,
            "affine_a": a, "affine_b": np.asarray(a * bias + b,
                                                  np.float32)}


def _finish_params(folded, x_scale):
    """Bake the calibrated activation scale into the epilogue: scale =
    x_scale·w_scale[·γr], bias already affine-folded. x_scale rides as
    a traced scalar param so recalibration never recompiles."""
    w_scale = folded.pop("w_scale")
    a = folded.pop("affine_a", None)
    if a is not None:
        folded["scale"] = jnp.asarray(x_scale * w_scale * a, jnp.float32)
        folded["bias"] = jnp.asarray(folded.pop("affine_b"), jnp.float32)
    else:
        folded["scale"] = jnp.asarray(x_scale * w_scale, jnp.float32)
    folded["x_scale"] = jnp.asarray(x_scale, jnp.float32)
    return folded


# -- the chain executor -----------------------------------------------------
class _ChainPlan:
    """One maximal run of quantized pointwise work executed as a
    cache-resident tiled pipeline. steps: ("gemm", key, act) |
    ("add", tap_step, src_is_entry) | ("relu",). `taps`: step indices
    whose (dequantized, in-cache) outputs later adds read."""

    def __init__(self, entry, exit_, steps, keys, taps, in_key,
                 out_names):
        self.entry = entry          # upstream act feeding the run
        self.exit = exit_           # node/layer whose act the run yields
        self.steps = steps
        self.keys = keys            # param keys of the gemm steps
        self.taps = frozenset(taps)
        self.in_key = in_key        # param key supplying the entry scale
        self.out_names = out_names  # names covered (for bookkeeping)

    def run(self, params, x):
        """x: (B, H, W, C) fp activation. One int8 quantize at entry;
        after that, `lax.scan` over row tiles keeps every intermediate
        in cache — GEMM epilogues, residual adds and relus never
        round-trip RAM. Inside a tile the flow is the DEQUANTIZED f32
        value (int8 quantization error is incurred at the run entry and
        in the int8 weights; strictly less rounding than the per-layer
        "dot" impl)."""
        b, h, w, c = x.shape
        m = b * h * w
        x_scale = params[self.in_key]["x_scale"]
        xq = quantize(x.reshape(m, c), x_scale)
        bm = min(CHAIN_TILE_ROWS, m)
        pad = (-m) % bm
        if pad:
            xq = jnp.pad(xq, ((0, pad), (0, 0)))
        # int-valued f32 weights + epilogue scales, hoisted out of the
        # scan (loop-invariant); `scale` params carry x_scale·w_scale[·a]
        # for the per-layer impl — the in-cache value is already
        # dequantized, so divide the entry scale back out
        wf = [params[k]["Wq"].astype(jnp.float32) for k in self.keys]
        sc = [params[k]["scale"] / params[k]["x_scale"]
              for k in self.keys]
        bi = [params[k].get("bias") for k in self.keys]
        out_c = wf[-1].shape[1]

        def tile_body(carry, tile):
            cur = tile.astype(jnp.float32) * x_scale
            entry = cur
            saved = {}
            gi = 0
            for si, step in enumerate(self.steps):
                if step[0] == "gemm":
                    acc = lax.dot_general(
                        cur, wf[gi], (((1,), (0,)), ((), ())),
                        preferred_element_type=jnp.float32)
                    cur = acc * sc[gi]
                    if bi[gi] is not None:
                        cur = cur + bi[gi]
                    if step[2] == "relu":
                        cur = jnp.maximum(cur, 0.0)
                    gi += 1
                elif step[0] == "add":
                    src = entry if step[2] else saved[step[1]]
                    cur = cur + src
                elif step[0] == "relu":
                    cur = jnp.maximum(cur, 0.0)
                if si in self.taps:
                    saved[si] = cur
            return carry, cur

        tiles = xq.reshape(-1, bm, c)
        _, out = lax.scan(tile_body, 0, tiles)
        out = out.reshape(-1, out_c)[:m]
        return out.reshape(b, h, w, out_c).astype(x.dtype)


def _count_quant_metrics(n_int8, n_fallback):
    if _mon.enabled():
        reg = _mon.get_registry()
        if n_int8:
            reg.counter(_mon.QUANT_INT8_LAYERS,
                        help="layers rewritten to the int8 serving "
                             "path").inc(n_int8)
        if n_fallback:
            reg.counter(_mon.QUANT_DEQUANT_FALLBACKS,
                        help="weight-bearing layers the int8 rewrite "
                             "left at full precision").inc(n_fallback)


def _is_relu_or_identity(act):
    return str(act).lower() in ("relu", "identity", "linear")


def _effective_policy(layer, default):
    """A layer-level precisionPolicy (including the `.off()` opt-out
    sentinel `.precisionPolicy(None)` resolves to) shadows the
    network-level/passed one for BOTH QAT and this rewrite."""
    lp = getattr(layer, "precisionPolicy", None)
    return lp if lp is not None else default


# -- MultiLayerNetwork rewrite ----------------------------------------------
def _quantize_multilayer(net, data, policy, impl, fuse):
    from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                                   BatchNormalization,
                                                   ConvolutionLayer,
                                                   DenseLayer)
    conf = copy.deepcopy(net.conf)
    layers = conf.layers
    n = len(layers)

    # plan: which indices quantize, which BN folds into which conv
    to_quant, folds = [], {}
    i = 0
    while i < n:
        layer = net.conf.layers[i]
        if _effective_policy(layer, policy).int8_servable(layer):
            if (type(layer) is ConvolutionLayer
                    and _is_relu_or_identity(layer.activation)
                    and str(layer.activation).lower() != "relu"
                    and i + 1 < n
                    and type(net.conf.layers[i + 1]) is BatchNormalization
                    and (i + 1) not in net.conf.preprocessors):
                folds[i] = i + 1
                to_quant.append(i)
                i += 2
                continue
            to_quant.append(i)
        i += 1

    if not to_quant:
        raise ValueError(
            "quantize_network: no int8-servable layer found (policy "
            f"{policy!r}); nothing to quantize")

    # activation-scale calibration: observed (data) > upstream-BN > default
    observed = None
    if data is not None:
        def collect(x):
            xs = jnp.asarray(x)
            _, _, _, acts = net._forward(net._params, net._state, xs,
                                         False, None, collect=True)
            ins = {}
            for idx in to_quant:
                a = xs.astype(net._compute_dtype) if idx == 0 \
                    else acts[idx - 1]
                pp = net.conf.preprocessors.get(idx)
                ins[str(idx)] = pp.preProcess(a) if pp is not None else a
            return ins
        observed = _cal.observe(collect, data)
    bn_scales = {}
    for idx in to_quant:
        prev = net.conf.layers[idx - 1] if idx > 0 else None
        if type(prev) is BatchNormalization:
            bn_scales[str(idx)] = _cal.bn_param_scale(
                net._params.get(str(idx - 1), {}))
    scales = _cal.resolve_scales([str(i) for i in to_quant], observed,
                                 bn_scales)

    new_params = {}
    new_state = {}
    fallbacks = 0
    for idx in range(n):
        key = str(idx)
        layer = net.conf.layers[idx]
        if idx in to_quant:
            p = net._params.get(key, {})
            x_scale, _src = scales[key]
            if type(layer) is ConvolutionLayer:
                bn_idx = folds.get(idx)
                bn = net.conf.layers[bn_idx] if bn_idx is not None else None
                folded = _fold_conv_bn(
                    layer, p, bn,
                    net._params.get(str(bn_idx), {}) if bn else None,
                    net._state.get(str(bn_idx), {}) if bn else None)
                act = bn.activation if bn is not None else layer.activation
                layers[idx] = QuantizedConv1x1(
                    layer.name, layer.nIn, layer.nOut, act,
                    stride=layer.stride[0], impl=impl)
                if bn_idx is not None:
                    layers[bn_idx] = QuantPassthrough(
                        net.conf.layers[bn_idx].name, layer.name)
            else:
                folded = _fold_dense(layer, p)
                layers[idx] = QuantizedDense(
                    layer.name, layer.nIn, layer.nOut, layer.activation,
                    layer.hasBias, impl=impl)
            new_params[key] = _finish_params(folded, x_scale)
        elif idx in folds.values():
            pass          # folded BN: no params, no state
        else:
            if net._params.get(key):
                new_params[key] = jax.tree_util.tree_map(
                    jnp.copy, net._params[key])
            if net._state.get(key):
                new_state[key] = jax.tree_util.tree_map(
                    jnp.copy, net._state[key])
            if isinstance(layer, (DenseLayer, ConvolutionLayer)) \
                    and not hasattr(layer, "compute_loss"):
                fallbacks += 1

    _count_quant_metrics(len(to_quant), fallbacks)

    # chain plans: maximal runs of stride-1 quantized convs /
    # passthroughs / relu-identity activation layers (sequential nets
    # have no residual taps)
    plans = []
    eff_impl = _default_impl() if impl == "auto" else impl
    if fuse and eff_impl == "chain":
        plans = _plan_multilayer_chains(conf, layers)

    q = QuantizedMultiLayerNetwork(conf)
    q._params = new_params
    q._state = new_state
    q._chain_plans = {p.entry: p for p in plans}
    q._quant_stats = {"int8_layers": len(to_quant),
                      "fallbacks": fallbacks,
                      "folded_bns": len(folds),
                      "chains": len(plans),
                      "scales": {k: v for k, v in scales.items()}}
    return q


def _plan_multilayer_chains(conf, layers):
    """Runs of consecutive [QuantizedConv1x1 stride-1 | QuantPassthrough
    | ActivationLayer(relu/identity)] with >= 2 GEMMs become one
    cache-resident chain. A preprocessor on a layer breaks the run
    before it; the loss head never joins."""
    from deeplearning4j_tpu.nn.conf.layers import ActivationLayer
    plans, i, n = [], 0, len(layers)
    while i < n:
        layer = layers[i]
        if not (isinstance(layer, QuantizedConv1x1)
                and layer.stride == 1):
            i += 1
            continue
        steps, keys, run = [], [], []
        j = i
        while j < n:
            lj = layers[j]
            if conf.preprocessors.get(j) is not None and j > i:
                break
            if isinstance(lj, QuantizedConv1x1) and lj.stride == 1 \
                    and _is_relu_or_identity(lj.activation):
                steps.append(("gemm", str(j),
                              "relu" if str(lj.activation).lower()
                              == "relu" else "identity"))
                keys.append(str(j))
            elif isinstance(lj, QuantPassthrough):
                pass
            elif isinstance(lj, ActivationLayer) \
                    and _is_relu_or_identity(lj.activation):
                if str(lj.activation).lower() == "relu":
                    steps.append(("relu",))
            else:
                break
            run.append(j)
            j += 1
        if len(keys) >= 2:
            plans.append(_ChainPlan(
                entry=i, exit_=run[-1], steps=steps, keys=keys,
                taps=(), in_key=keys[0], out_names=tuple(run)))
        i = max(j, i + 1)
    return plans


class QuantizedMultiLayerNetwork:
    """Inference-only MultiLayerNetwork twin produced by
    quantize_network(). Duck-compatible with the serving stack
    (output / _forward / _params / _state / conf), refuses to train."""

    def __init__(self, conf):
        from deeplearning4j_tpu.ops.ndarray import resolve_dtype
        self.conf = conf
        self.layers = conf.layers
        self._params = None
        self._state = None
        self._chain_plans = {}
        self._compute_dtype = resolve_dtype(conf.data_type) or jnp.float32

    # -- training surface: refused ----------------------------------------
    def fit(self, *a, **kw):
        raise RuntimeError(
            "quantized networks are inference-only — train the fp "
            "model (optionally with a QAT precisionPolicy) and "
            "re-run quantize_network()")

    computeGradients = fit
    pretrain = fit

    # -- forward -----------------------------------------------------------
    def _forward(self, params, state, x, train, rng, mask=None,
                 collect=False, stop_at=None, carries=None):
        from deeplearning4j_tpu.nn.multilayer import (MultiLayerNetwork,
                                                      _apply_layer,
                                                      _hook_params)
        if train:
            raise RuntimeError("quantized networks are inference-only")
        if collect or stop_at is not None or carries is not None \
                or mask is not None or not self._chain_plans:
            return MultiLayerNetwork._forward(
                self, params, state, x, False, None, mask=mask,
                collect=collect, stop_at=stop_at, carries=carries)
        x = x.astype(self._compute_dtype)
        new_state = dict(state)
        preact = None
        n = len(self.layers)
        i = 0
        while i < n:
            plan = self._chain_plans.get(i)
            if plan is not None:
                pp = self.conf.preprocessors.get(i)
                if pp is not None:
                    x = pp.preProcess(x)
                x = plan.run(params, x)
                i = plan.exit + 1
                continue
            layer = self.layers[i]
            pp = self.conf.preprocessors.get(i)
            if pp is not None:
                x = pp.preProcess(x)
            p = _hook_params(layer, params.get(str(i), {}), False, None)
            s = state.get(str(i), {})
            if i == n - 1 and hasattr(layer, "compute_loss") \
                    and hasattr(layer, "pre_activation"):
                preact = layer.pre_activation(p, x)
                from deeplearning4j_tpu.nn.activations import \
                    get_activation
                x = get_activation(layer.activation)(preact)
            else:
                x, ns = _apply_layer(layer, p, s, x, False, None, None)
                if ns:
                    new_state[str(i)] = ns
            i += 1
        return x, preact, new_state, []

    def output(self, x, train=False, fmask=None):
        from deeplearning4j_tpu.ops.ndarray import NDArray, as_jax
        x = as_jax(x)
        fmask = None if fmask is None else as_jax(fmask)
        y, _, _, _ = self._forward(self._params, self._state, x, False,
                                   None, mask=fmask)
        return NDArray(y)

    def predict(self, x):
        out = self.output(x).numpy()
        return np.argmax(out, axis=-1)

    def summary(self):
        from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
        return MultiLayerNetwork.summary(self)

    def getnLayers(self):
        return len(self.layers)

    def getLayer(self, idx):
        return self.layers[idx]


# -- ComputationGraph rewrite -----------------------------------------------
def _quantize_graph(net, data, policy, impl, fuse):
    from deeplearning4j_tpu.nn.conf.layers import (BatchNormalization,
                                                   ConvolutionLayer,
                                                   DenseLayer)
    conf = copy.deepcopy(net.conf)
    nodes = conf.nodes
    consumers = net.conf.consumers()

    to_quant, folds = [], {}
    skip = set()
    for name in net.conf.topo_order:
        node = net.conf.nodes[name]
        if node.kind != "layer" or name in skip:
            continue
        layer = node.ref
        if not _effective_policy(layer, policy).int8_servable(layer):
            continue
        if (type(layer) is ConvolutionLayer
                and _is_relu_or_identity(layer.activation)
                and str(layer.activation).lower() != "relu"):
            outs = consumers.get(name, [])
            if (len(outs) == 1 and name not in net.conf.output_names):
                cand = net.conf.nodes[outs[0]]
                if (cand.kind == "layer"
                        and type(cand.ref) is BatchNormalization
                        and cand.preprocessor is None
                        and outs[0] not in net.conf.output_names):
                    folds[name] = outs[0]
                    skip.add(outs[0])
        to_quant.append(name)

    if not to_quant:
        raise ValueError(
            "quantize_network: no int8-servable layer node found "
            f"(policy {policy!r}); nothing to quantize")

    observed = None
    if data is not None:
        input_names = list(net.conf.input_names)

        def collect(x):
            ins = ({n: jnp.asarray(v) for n, v in x.items()}
                   if isinstance(x, dict)
                   else {input_names[0]: jnp.asarray(x)})
            acts, _, _ = net._forward(net._params, net._state, ins,
                                      False, None)
            out = {}
            for name in to_quant:
                node = net.conf.nodes[name]
                a = acts[node.inputs[0]]
                if node.preprocessor is not None:
                    a = node.preprocessor.preProcess(a)
                out[name] = a
            return out
        observed = _cal.observe(collect, data)
    bn_scales = {}
    for name in to_quant:
        parent = net.conf.nodes[net.conf.nodes[name].inputs[0]]
        if parent.kind == "layer" \
                and type(parent.ref) is BatchNormalization:
            bn_scales[name] = _cal.bn_param_scale(
                net._params.get(parent.name, {}))
    scales = _cal.resolve_scales(to_quant, observed, bn_scales)

    new_params, new_state = {}, {}
    fallbacks = 0
    folded_bns = set(folds.values())
    for name in net.conf.topo_order:
        node = net.conf.nodes[name]
        if node.kind != "layer":
            continue
        layer = node.ref
        if name in to_quant:
            p = net._params.get(name, {})
            x_scale, _src = scales[name]
            if type(layer) is ConvolutionLayer:
                bn_name = folds.get(name)
                bn = (net.conf.nodes[bn_name].ref
                      if bn_name is not None else None)
                folded = _fold_conv_bn(
                    layer, p, bn,
                    net._params.get(bn_name, {}) if bn else None,
                    net._state.get(bn_name, {}) if bn else None)
                act = bn.activation if bn is not None else layer.activation
                nodes[name].ref = QuantizedConv1x1(
                    name, layer.nIn, layer.nOut, act,
                    stride=layer.stride[0], impl=impl)
                if bn_name is not None:
                    nodes[bn_name].ref = QuantPassthrough(bn_name, name)
            else:
                folded = _fold_dense(layer, p)
                nodes[name].ref = QuantizedDense(
                    name, layer.nIn, layer.nOut, layer.activation,
                    layer.hasBias, impl=impl)
            new_params[name] = _finish_params(folded, x_scale)
        elif name in folded_bns:
            pass
        else:
            if net._params.get(name):
                new_params[name] = jax.tree_util.tree_map(
                    jnp.copy, net._params[name])
            if net._state.get(name):
                new_state[name] = jax.tree_util.tree_map(
                    jnp.copy, net._state[name])
            if isinstance(layer, (DenseLayer, ConvolutionLayer)) \
                    and not hasattr(layer, "compute_loss"):
                fallbacks += 1
    # parameterized vertices keep their params too
    for name in net.conf.topo_order:
        node = net.conf.nodes[name]
        if node.kind == "vertex" and net._params.get(name):
            new_params[name] = jax.tree_util.tree_map(
                jnp.copy, net._params[name])

    _count_quant_metrics(len(to_quant), fallbacks)

    plans = []
    eff_impl = _default_impl() if impl == "auto" else impl
    if fuse and eff_impl == "chain":
        plans = _plan_graph_chains(conf)

    q = QuantizedComputationGraph(conf)
    q._params = new_params
    q._state = new_state
    q._chain_plans = {p.exit: p for p in plans}
    q._chain_covered = {n for p in plans for n in p.out_names}
    q._quant_stats = {"int8_layers": len(to_quant),
                      "fallbacks": fallbacks,
                      "folded_bns": len(folds),
                      "chains": len(plans),
                      "scales": dict(scales)}
    return q


def _plan_graph_chains(conf):
    """Maximal single-entry/single-exit regions of chainable nodes —
    stride-1 QuantizedConv1x1, folded-BN passthroughs, relu/identity
    ActivationLayers, and ElementWiseVertex("add") whose residual
    source is the region entry or an in-region value. Each region with
    >= 2 GEMMs becomes one cache-resident tiled pipeline."""
    from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
    from deeplearning4j_tpu.nn.conf.layers import ActivationLayer
    consumers = conf.consumers()
    nodes = conf.nodes

    def chainable(name):
        node = nodes[name]
        if node.kind == "layer":
            if getattr(node, "preprocessor", None) is not None:
                return False
            ref = node.ref
            if isinstance(ref, QuantizedConv1x1):
                return ref.stride == 1 and _is_relu_or_identity(
                    ref.activation)
            if isinstance(ref, QuantPassthrough):
                return True
            return (isinstance(ref, ActivationLayer)
                    and _is_relu_or_identity(ref.activation))
        if node.kind == "vertex":
            return (isinstance(node.ref, ElementWiseVertex)
                    and getattr(node.ref, "op", None) == "add"
                    and len(node.inputs) == 2)
        return False

    assigned = set()
    plans = []
    topo = [n for n in conf.topo_order if nodes[n].kind != "input"]
    for start_i, start in enumerate(topo):
        if start in assigned or not chainable(start):
            continue
        if not isinstance(nodes[start].ref, QuantizedConv1x1):
            continue
        entry = nodes[start].inputs[0]
        region = []
        avail = {entry}
        for name in topo[start_i:]:
            if name in assigned:
                break
            if not chainable(name):
                break
            if any(p not in avail for p in nodes[name].inputs):
                break
            region.append(name)
            avail.add(name)
        # trim: every non-final node must be consumed inside the region
        while len(region) > 1:
            rset = set(region)
            bad = None
            for n in region[:-1]:
                if any(c not in rset for c in consumers.get(n, ())) \
                        or n in conf.output_names:
                    bad = n
                    break
            if bad is None and region[-1] not in conf.output_names:
                break
            region = region[:region.index(bad) + 1] if bad is not None \
                else region[:-1]
        plan = _steps_for_region(conf, region, entry)
        if plan is not None:
            plans.append(plan)
            assigned.update(plan.out_names)
    return plans


def _steps_for_region(conf, region, entry):
    """Compile a region's nodes into executor steps; None when the
    region is too small (< 2 GEMMs) or an add's source cannot be
    expressed as an in-region tap."""
    from deeplearning4j_tpu.nn.conf.layers import ActivationLayer
    nodes = conf.nodes
    steps, keys, taps = [], [], set()
    # node_step: node name -> the executor step index producing its
    # value ("entry" = the run's input). cur_step tracks the value the
    # executor's running `cur` holds — only steps advance it, aliases
    # (passthroughs, identity activations) don't.
    node_step = {entry: "entry"}
    cur_step = "entry"
    for name in region:
        ref = nodes[name].ref if nodes[name].kind == "layer" else None
        if isinstance(ref, QuantizedConv1x1):
            if node_step.get(nodes[name].inputs[0]) != cur_step:
                return None   # chain must consume the running value
            steps.append(("gemm", name,
                          "relu" if str(ref.activation).lower() == "relu"
                          else "identity"))
            keys.append(name)
            cur_step = node_step[name] = len(steps) - 1
        elif isinstance(ref, QuantPassthrough):
            node_step[name] = node_step[nodes[name].inputs[0]]
        elif isinstance(ref, ActivationLayer):
            if str(ref.activation).lower() == "relu":
                if node_step.get(nodes[name].inputs[0]) != cur_step:
                    return None
                steps.append(("relu",))
                cur_step = node_step[name] = len(steps) - 1
            else:
                node_step[name] = node_step[nodes[name].inputs[0]]
        else:   # ElementWiseVertex add
            p1, p2 = nodes[name].inputs
            s1, s2 = node_step.get(p1), node_step.get(p2)
            if s1 == cur_step:
                src = s2
            elif s2 == cur_step:
                src = s1
            else:
                return None
            if src is None:
                return None
            if src == "entry":
                steps.append(("add", None, True))
            else:
                steps.append(("add", src, False))
                taps.add(src)
            cur_step = node_step[name] = len(steps) - 1
    if len(keys) < 2:
        return None
    if node_step.get(region[-1]) != cur_step:
        return None   # exit must BE the running value
    return _ChainPlan(entry=entry, exit_=region[-1], steps=steps,
                      keys=keys, taps=taps, in_key=keys[0],
                      out_names=tuple(region))


class QuantizedComputationGraph:
    """Inference-only ComputationGraph twin produced by
    quantize_network(). Duck-compatible with the serving stack
    (output / outputSingle / _forward / conf), refuses to train."""

    def __init__(self, conf):
        from deeplearning4j_tpu.ops.ndarray import resolve_dtype
        self.conf = conf
        self.nodes = conf.nodes
        self._params = None
        self._state = None
        self._chain_plans = {}
        self._chain_covered = set()
        self._fused_pairs = {}
        self._fused_convs = set()
        self._compute_dtype = resolve_dtype(conf.data_type) or jnp.float32

    def fit(self, *a, **kw):
        raise RuntimeError(
            "quantized networks are inference-only — train the fp "
            "model (optionally with a QAT precisionPolicy) and "
            "re-run quantize_network()")

    computeGradients = fit

    # -- forward -----------------------------------------------------------
    def _forward(self, params, state, inputs, train, rng, fmasks=None,
                 want=None, carries=None):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        if train:
            raise RuntimeError("quantized networks are inference-only")
        masked = fmasks and any(m is not None for m in fmasks.values())
        if carries is not None or masked or want == "all" \
                or not self._chain_plans:
            return ComputationGraph._forward(
                self, params, state, inputs, False, None, fmasks, want,
                carries)
        acts = {name: x.astype(self._compute_dtype)
                for name, x in inputs.items()}
        preacts = {}
        new_state = dict(state)
        rng_index = self._rng_index
        for name in self.conf.topo_order:
            if self.nodes[name].kind == "input" \
                    or name in self._chain_covered:
                plan = self._chain_plans.get(name)
                if plan is not None:
                    acts[name] = plan.run(params, acts[plan.entry])
                continue
            ComputationGraph._run_node_plain(
                self, name, params, state, acts, new_state, preacts,
                None, rng_index, train=False)
        return acts, preacts, new_state

    @property
    def _rng_index(self):
        idx, li = {}, 0
        for name in self.conf.topo_order:
            if self.nodes[name].kind == "layer":
                idx[name] = li
                li += 1
        return idx

    def _as_input_dict(self, inputs):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        return ComputationGraph._as_input_dict(self, inputs)

    def output(self, *inputs, train=False, fmasks=None):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        return ComputationGraph.output(self, *inputs, train=False,
                                       fmasks=fmasks)

    def outputSingle(self, *inputs):
        out = self.output(*inputs)
        return out[0] if isinstance(out, list) else out

    def feedForward(self, inputs, train=False):
        from deeplearning4j_tpu.ops.ndarray import NDArray
        ins = self._as_input_dict(inputs)
        acts, _, _ = self._forward(self._params, self._state, ins,
                                   False, None, want="all")
        return {k: NDArray(v) for k, v in acts.items()}

    def summary(self):
        from deeplearning4j_tpu.nn.graph import ComputationGraph
        return ComputationGraph.summary(self)

    def getLayer(self, name):
        return self.nodes[name].ref


# -- entry point ------------------------------------------------------------
def quantize_network(net, data=None, policy=None, impl="auto",
                     fuse=True):
    """Rewrite a trained network for int8 serving.

    net: MultiLayerNetwork or ComputationGraph (init()ed / trained).
    data: optional iterable of feature batches (arrays, or input dicts
        for multi-input graphs) for observed-absmax calibration of
        activation scales; without it, scales come from upstream BN
        statistics where available, else a conservative default.
    policy: PrecisionPolicy (defaults to the net conf's inherited
        precisionPolicy, else PrecisionPolicy.int8()).
    impl: "auto" | "dot" | "chain" — see module docstring.
    fuse: allow the cache-resident chain executor over runs of
        quantized pointwise layers (chain impl only).

    Returns the inference-only quantized twin; the original net is
    untouched (params are copied, never aliased — the source net's
    donated train buffers stay its own)."""
    if policy is None:
        policy = (getattr(net.conf, "defaults", {}) or {}).get(
            "precisionPolicy") or PrecisionPolicy.int8()
    if impl not in ("auto", "dot", "chain"):
        raise ValueError(f"impl must be auto|dot|chain, got {impl!r}")
    if net._params is None:
        raise ValueError("quantize_network needs an init()ed network")
    if hasattr(net, "outputSingle"):
        q = _quantize_graph(net, data, policy, impl, fuse)
    else:
        q = _quantize_multilayer(net, data, policy, impl, fuse)
    if _mon.enabled():
        # the diet is observable: publish the per-model activation-
        # traffic estimate under the new precision widths. The label
        # needs a MODEL identity, not a class name — two quantized
        # nets of the same class must not overwrite each other's
        # gauge — so it carries the trace fingerprint.
        from deeplearning4j_tpu.quantize import traffic as _traffic
        from deeplearning4j_tpu.runtime.executables import \
            model_fingerprint
        _traffic.publish(
            q, model_name=(f"{type(net).__name__}:"
                           f"{model_fingerprint(q)[:8]}"))
    return q
