"""Pallas fused layer normalisation.

The reference's BatchNormalization/LayerNorm path hands the fused
normalise-scale-shift to cuDNN (deeplearning4j-cuda ::
CudnnBatchNormalizationHelper); here the fusion is a single Pallas
kernel: one HBM read and one write per element, mean/var/normalise/
affine all in VMEM. Backward is the standard closed-form layernorm
gradient in plain jnp (XLA fuses it into the surrounding step).

Operates on (..., D); rows are tiled through VMEM in blocks.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _ln_kernel(x_ref, g_ref, b_ref, o_ref, *, eps):
    x = x_ref[...].astype(jnp.float32)
    mean = jnp.mean(x, axis=-1, keepdims=True)
    xc = x - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    y = xc * inv * g_ref[...].astype(jnp.float32) + b_ref[...].astype(
        jnp.float32)
    o_ref[...] = y.astype(o_ref.dtype)


def _ln_forward(x, gamma, beta, eps, block_rows, interpret):
    orig_shape = x.shape
    d = orig_shape[-1]
    n = x.size // d
    x2 = x.reshape(n, d)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_rows = min(block_rows, n)
    pad = (-n) % block_rows
    if pad:
        x2 = jnp.pad(x2, ((0, pad), (0, 0)))
    g2 = gamma.reshape(1, d)
    b2 = beta.reshape(1, d)
    out = pl.pallas_call(
        functools.partial(_ln_kernel, eps=eps),
        grid=(x2.shape[0] // block_rows,),
        in_specs=[
            pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
            pl.BlockSpec((1, d), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_rows, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct(x2.shape, x.dtype),
        interpret=interpret,
    )(x2, g2, b2)
    return out[:n].reshape(orig_shape)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def fused_layernorm(x, gamma, beta, eps=1e-5, block_rows=128, interpret=None):
    """LayerNorm over the last axis: γ·(x−μ)/√(σ²+ε)+β, one fused kernel."""
    return _ln_forward(x, gamma, beta, eps, block_rows, interpret)


def _ln_fwd_rule(x, gamma, beta, eps, block_rows, interpret):
    # Under autodiff the residuals (xhat, inv) are needed anyway, so the
    # output is derived from them in plain jnp — XLA fuses this into the
    # surrounding train step and the input is read from HBM exactly once.
    # The Pallas kernel is the no-residual inference path.
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    xc = xf - mean
    var = jnp.mean(xc * xc, axis=-1, keepdims=True)
    inv = jax.lax.rsqrt(var + eps)
    xhat = xc * inv
    out = (xhat * gamma.astype(jnp.float32)
           + beta.astype(jnp.float32)).astype(x.dtype)
    return out, (xhat, inv, gamma)


def _ln_bwd_rule(eps, block_rows, interpret, res, g):
    xhat, inv, gamma = res
    gf = g.astype(jnp.float32)
    dg = jnp.sum(gf * xhat, axis=tuple(range(g.ndim - 1)))
    db = jnp.sum(gf, axis=tuple(range(g.ndim - 1)))
    wg = gf * gamma.astype(jnp.float32)
    dx = inv * (wg - jnp.mean(wg, axis=-1, keepdims=True)
                - xhat * jnp.mean(wg * xhat, axis=-1, keepdims=True))
    return (dx.astype(g.dtype), dg.astype(gamma.dtype), db.astype(gamma.dtype))


fused_layernorm.defvjp(_ln_fwd_rule, _ln_bwd_rule)
