"""Pallas fused 1x1-convolution + BatchNorm training kernels.

A 1x1 conv in NHWC is a GEMM over the flattened spatial axis:
y[M, N] = x[M, K] @ w[K, N] with M = B*H*W. In ResNet-class nets every
1x1 conv is immediately followed by BatchNorm, and the xplane profile of
the ResNet-50 bench step (BENCH.md) shows the step is HBM-bound with the
BN stat/grad passes around those GEMMs costing whole extra reads/writes
of the largest activations. These kernels remove the removable passes
(the reference instead hands conv+BN to cuDNN fused helpers —
deeplearning4j-cuda :: CudnnConvolutionHelper/CudnnBatchNormalizationHelper;
on TPU the fusion has to be authored, XLA will not fuse a reduction into
a conv epilogue):

- forward: ONE kernel computes y = x @ w AND accumulates per-channel
  sum(y), sum(y^2) across the sequential TPU grid — the separate BN
  stats pass over y disappears. The normalize+activation stays a plain
  XLA elementwise pass (it needs the *global* stats, which only exist
  after the full grid).
- backward: after the unavoidable dgamma/dbeta reduction (one kernel,
  reads y and dz), a SINGLE kernel streams (x, y, dz) once and emits
  BOTH conv gradients: it reconstructs the BN input-gradient
  dy = k1*dz - k2*(y - mu) - c on the fly in VMEM (relu mask folded in)
  and contracts it twice on the MXU — dX = dy @ w^T per tile and
  dW += x^T @ dy accumulated across the grid. The 3 reads + 1 write
  replace XLA's dx-elementwise pass + two separate conv-grad reads of a
  materialized dy (5 reads + 2 writes of M*N-class tensors).

Used by the ComputationGraph conv1x1+BN fusion path (nn/fused.py); exact
equality with the unfused composition is tested in tests/test_kernels.py.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _default_interpret():
    return jax.default_backend() != "tpu"


# ---------------------------------------------------------------------------
# forward: y = x @ w, plus per-channel sum / sumsq epilogue
# ---------------------------------------------------------------------------
def _fwd_kernel(x_ref, w_ref, y_ref, s1_ref, s2_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        s1_ref[...] = jnp.zeros_like(s1_ref)
        s2_ref[...] = jnp.zeros_like(s2_ref)

    x = x_ref[...]
    w = w_ref[...]
    y = jnp.dot(x, w, preferred_element_type=jnp.float32)
    y_ref[...] = y.astype(y_ref.dtype)
    # stats accumulate over the cast value actually seen downstream
    yc = y_ref[...].astype(jnp.float32)
    s1_ref[...] += jnp.sum(yc, axis=0, keepdims=True)
    s2_ref[...] += jnp.sum(yc * yc, axis=0, keepdims=True)


def matmul_stats(x, w, block_m=256, interpret=None):
    """(x @ w, sum over rows, sum of squares over rows) in one pass.

    x: (M, K), w: (K, N) -> y (M, N) in x.dtype, s1/s2 (N,) float32.
    M is padded to a block multiple internally (zero rows contribute
    nothing to either stat)."""
    if interpret is None:
        interpret = _default_interpret()
    m, k = x.shape
    k2, n = w.shape
    assert k == k2, (x.shape, w.shape)
    bm = min(block_m, m)
    pad = (-m) % bm
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
    grid = (x.shape[0] // bm,)
    y, s1, s2 = pl.pallas_call(
        _fwd_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, k), lambda i: (i, 0)),
            pl.BlockSpec((k, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((x.shape[0], n), x.dtype),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        interpret=interpret,
    )(x, w)
    return y[:m], s1[0], s2[0]


# ---------------------------------------------------------------------------
# inference epilogue fusion: affine (+ residual) (+ act) INSIDE the GEMM
# ---------------------------------------------------------------------------
# BENCH.md round 3's post-mortem of the standalone fusion attempt: a
# Pallas custom-call is a fusion BARRIER, so removing one pass by hand
# while breaking XLA's own elementwise merges was a net loss. The shape
# that does win is the epilogue — the affine/residual/activation tail
# applied to each GEMM tile while it is still in VMEM, costing zero
# extra reads and removing the separate BN-apply / residual-add passes'
# writes. These kernels are that shape for the INFERENCE path (training
# BN needs global batch stats, which only exist after the full grid —
# its stats epilogue lives in matmul_stats above).

def _make_epilogue_kernel(acc_dtype):
    """One body for both precisions: `acc_dtype` is the contraction's
    accumulator (f32 for the fp GEMM, int32 for int8×int8 on the MXU);
    the scale/bias/residual/activation tail is IDENTICAL so the fp and
    int8 inference paths can never drift apart."""
    def builder(act, has_res):
        def kernel(x_ref, w_ref, s_ref, b_ref, *rest):
            res_ref, y_ref = (rest if has_res else (None, rest[0]))
            acc = jnp.dot(x_ref[...], w_ref[...],
                          preferred_element_type=acc_dtype)
            y = acc.astype(jnp.float32) \
                * s_ref[...].astype(jnp.float32) \
                + b_ref[...].astype(jnp.float32)
            if has_res:
                y = y + res_ref[...].astype(jnp.float32)
            if act == "relu":
                y = jnp.maximum(y, 0.0)
            y_ref[...] = y.astype(y_ref.dtype)
        return kernel
    return builder


_epilogue_kernel = _make_epilogue_kernel(jnp.float32)
_int8_epilogue_kernel = _make_epilogue_kernel(jnp.int32)


def _matmul_epilogue_call(kernel_builder, x, w, scale, shift, residual,
                          act, out_dtype, block_m, interpret):
    if interpret is None:
        interpret = _default_interpret()
    if act not in ("identity", "relu"):
        raise ValueError(f"epilogue act must be identity|relu: {act!r}")
    m, k = x.shape
    n = w.shape[1]
    bm = min(block_m, m)
    pad = (-m) % bm
    has_res = residual is not None
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        if has_res:
            residual = jnp.pad(residual, ((0, pad), (0, 0)))
    grid = (x.shape[0] // bm,)
    in_specs = [
        pl.BlockSpec((bm, k), lambda i: (i, 0)),
        pl.BlockSpec((k, n), lambda i: (0, 0)),
        pl.BlockSpec((1, n), lambda i: (0, 0)),
        pl.BlockSpec((1, n), lambda i: (0, 0)),
    ]
    args = [x, w, scale.reshape(1, n), shift.reshape(1, n)]
    if has_res:
        in_specs.append(pl.BlockSpec((bm, n), lambda i: (i, 0)))
        args.append(residual)
    y = pl.pallas_call(
        kernel_builder(act, has_res),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((bm, n), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((x.shape[0], n), out_dtype),
        interpret=interpret,
    )(*args)
    return y[:m]


def matmul_epilogue(x, w, scale, shift, residual=None, act="identity",
                    out_dtype=None, block_m=256, interpret=None):
    """y = act((x @ w)·scale + shift [+ residual]) in ONE kernel: the
    affine is the folded inference-BN (scale = γ·rsqrt(var+eps),
    shift = β − γ·μ·rsqrt(var+eps)), applied per tile in VMEM — the
    separate BN-apply and residual-add passes disappear. x: (M, K),
    w: (K, N), scale/shift: (N,), residual: (M, N) or None."""
    return _matmul_epilogue_call(
        _epilogue_kernel, x, w, scale, shift, residual, act,
        out_dtype or x.dtype, block_m, interpret)


def int8_matmul_epilogue(xq, wq, scale, shift, residual=None,
                         act="identity", out_dtype=jnp.float32,
                         block_m=256, interpret=None):
    """The int8 variant: xq (M, K) int8 × wq (K, N) int8 → int32 on the
    MXU, with the dequant (scale = x_scale·w_scale[·γr]) + bias
    (+ residual) (+ act) epilogue fused into the same kernel — the
    int32 accumulator never leaves VMEM."""
    return _matmul_epilogue_call(
        _int8_epilogue_kernel, xq, wq, scale, shift, residual, act,
        out_dtype, block_m, interpret)


# ---------------------------------------------------------------------------
# backward phase 1: dgamma / dbeta reduction (reads y, dz once)
# ---------------------------------------------------------------------------
def _bwd_stats_kernel(y_ref, dz_ref, mu_ref, r_ref, dg_ref, db_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _init():
        dg_ref[...] = jnp.zeros_like(dg_ref)
        db_ref[...] = jnp.zeros_like(db_ref)

    y = y_ref[...].astype(jnp.float32)
    dz = dz_ref[...].astype(jnp.float32)
    mu = mu_ref[...].astype(jnp.float32)
    r = r_ref[...].astype(jnp.float32)
    xhat = (y - mu) * r
    db_ref[...] += jnp.sum(dz, axis=0, keepdims=True)
    dg_ref[...] += jnp.sum(dz * xhat, axis=0, keepdims=True)


def bn_grad_stats(y, dz, mu, r, block_m=256, interpret=None):
    """dgamma = sum(dz * xhat), dbeta = sum(dz) in one read of (y, dz).

    Any relu masking must already be folded into dz by the caller.
    Zero-padded rows are harmless: dz = 0 kills both sums."""
    if interpret is None:
        interpret = _default_interpret()
    m, n = y.shape
    bm = min(block_m, m)
    pad = (-m) % bm
    if pad:
        y = jnp.pad(y, ((0, pad), (0, 0)))
        dz = jnp.pad(dz, ((0, pad), (0, 0)))
    grid = (y.shape[0] // bm,)
    dg, db = pl.pallas_call(
        _bwd_stats_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, n), lambda i: (0, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
        ],
        interpret=interpret,
    )(y, dz, mu.reshape(1, n), r.reshape(1, n))
    return dg[0], db[0]


# ---------------------------------------------------------------------------
# backward phase 2: dX and dW from one streaming pass over (x, y, dz)
# ---------------------------------------------------------------------------
def _bwd_gemm_kernel(x_ref, y_ref, dz_ref, w_ref, k1_ref, k2_ref, c_ref,
                     mu_ref, dx_ref, dw_ref):
    # grid = (k_tiles, m_tiles): m is innermost, so the dw block for the
    # current k-tile accumulates over consecutive steps and flushes once
    i = pl.program_id(1)

    @pl.when(i == 0)
    def _init():
        dw_ref[...] = jnp.zeros_like(dw_ref)

    y = y_ref[...].astype(jnp.float32)
    dz = dz_ref[...].astype(jnp.float32)
    k1 = k1_ref[...].astype(jnp.float32)
    k2 = k2_ref[...].astype(jnp.float32)
    c = c_ref[...].astype(jnp.float32)
    mu = mu_ref[...].astype(jnp.float32)
    # BN input-gradient reconstructed in VMEM — never touches HBM
    dy = (k1 * dz - (y - mu) * k2 - c).astype(x_ref.dtype)
    w = w_ref[...]
    dx = jnp.dot(dy, w.T, preferred_element_type=jnp.float32)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    x = x_ref[...]
    dw_ref[...] += jnp.dot(x.T, dy, preferred_element_type=jnp.float32)


def bn_conv_grads(x, y, dz, w, k1, k2, c, mu, block_m=256, interpret=None):
    """One pass over (x, y, dz): returns (dX (M,K) in x.dtype, dW (K,N) f32)
    where dy = k1*dz - k2*(y-mu) - c is formed on the fly.

    K is tiled when the resident (w tile + f32 dW accumulator) would blow
    the ~16 MB scoped-VMEM budget (ResNet res4/res5 pairs); the k-grid is
    the OUTER dimension so each dW block still accumulates over
    consecutive m-steps. The cost of a second k-tile is one extra read of
    (y, dz) — small next to the passes the fusion removes."""
    if interpret is None:
        interpret = _default_interpret()
    m, k = x.shape
    n = y.shape[1]
    bm = min(block_m, m)
    pad = (-m) % bm
    if pad:
        x = jnp.pad(x, ((0, pad), (0, 0)))
        y = jnp.pad(y, ((0, pad), (0, 0)))
        dz = jnp.pad(dz, ((0, pad), (0, 0)))
    mp = x.shape[0]
    # per-k-tile VMEM: w bf16 (2) + dW f32 (4) per bk*n, y/dz bf16 double-
    # buffered per bm*n, x/dx per bm*bk; keep the resident set under ~10MB.
    # K tiles first (cheap: one extra (y, dz) read per extra tile); if a
    # very wide N still blows the budget, shrink the m-block too.
    bk = k

    def _vmem(bm_, bk_):
        return bk_ * n * 6 + bm_ * n * 8 + bm_ * bk_ * 4

    while bk > 128 and _vmem(bm, bk) > 10 * 2**20:
        bk //= 2
    while bm > 8 and _vmem(bm, bk) > 10 * 2**20:
        bm //= 2
    pad = (-m) % bm
    if pad != (mp - m):  # bm shrank: re-pad rows to the new block size
        x, y, dz = x[:m], y[:m], dz[:m]
        if pad:
            x = jnp.pad(x, ((0, pad), (0, 0)))
            y = jnp.pad(y, ((0, pad), (0, 0)))
            dz = jnp.pad(dz, ((0, pad), (0, 0)))
        mp = x.shape[0]
    padk = (-k) % bk
    if padk:
        x = jnp.pad(x, ((0, 0), (0, padk)))
        w = jnp.pad(w, ((0, padk), (0, 0)))
    kp = x.shape[1]
    # Zero-padded rows yield dy_pad = mu*k2 - c (nonzero: y=0 makes
    # -(y-mu)*k2 = +mu*k2), but they cannot corrupt anything: their x rows
    # are zero so x^T @ dy gets no contribution, and their dx rows are
    # sliced off below. Zero-padded k-columns only add zero rows to w /
    # zero cols to x, sliced off dx/dw below.
    dx, dw = pl.pallas_call(
        _bwd_gemm_kernel,
        grid=(kp // bk, mp // bm),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda j, i: (i, j)),
            pl.BlockSpec((bm, n), lambda j, i: (i, 0)),
            pl.BlockSpec((bm, n), lambda j, i: (i, 0)),
            pl.BlockSpec((bk, n), lambda j, i: (j, 0)),
            pl.BlockSpec((1, n), lambda j, i: (0, 0)),
            pl.BlockSpec((1, n), lambda j, i: (0, 0)),
            pl.BlockSpec((1, n), lambda j, i: (0, 0)),
            pl.BlockSpec((1, n), lambda j, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, bk), lambda j, i: (i, j)),
            pl.BlockSpec((bk, n), lambda j, i: (j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((mp, kp), x.dtype),
            jax.ShapeDtypeStruct((kp, n), jnp.float32),
        ],
        interpret=interpret,
    )(x, y, dz, w, k1.reshape(1, n), k2.reshape(1, n), c.reshape(1, n),
      mu.reshape(1, n))
    return dx[:m, :k], dw[:k]


# ---------------------------------------------------------------------------
# the fused op: z = act(bn_train(x @ w)), custom VJP
# ---------------------------------------------------------------------------
@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def fused_conv1x1_bn(x, w, gamma, beta, eps=1e-5, act="identity",
                     interpret=None):
    """z = act(batchnorm_train(x @ w)); returns (z, mu, var).

    x: (M, K) activations (M = B*H*W), w: (K, N) conv kernel reshaped,
    gamma/beta: (N,) float32. act in {"identity", "relu"}. mu/var are the
    batch statistics (for the running-average update). Gradients flow to
    x, w, gamma, beta with BN's closed-form backward fused into the conv
    gradient GEMMs."""
    z, mu, var, _ = _fused_fwd_core(x, w, gamma, beta, eps, act, interpret)
    return z, mu, var


def _fused_fwd_core(x, w, gamma, beta, eps, act, interpret):
    y, s1, s2 = matmul_stats(x, w, interpret=interpret)
    m = x.shape[0]
    mu = s1 / m
    var = jnp.maximum(s2 / m - mu * mu, 0.0)
    r = jax.lax.rsqrt(var + eps)
    a = (gamma * r).astype(y.dtype)
    b = (beta - gamma * mu * r).astype(y.dtype)
    z = y * a + b
    if act == "relu":
        z = jnp.maximum(z, 0)
    elif act != "identity":
        raise ValueError(f"fused_conv1x1_bn: unsupported act {act!r}")
    return z, mu, var, (y, r)


def _fused_fwd_rule(x, w, gamma, beta, eps, act, interpret):
    z, mu, var, (y, r) = _fused_fwd_core(x, w, gamma, beta, eps, act,
                                         interpret)
    return (z, mu, var), (x, w, gamma, y, z, mu, r)


def _fused_bwd_rule(eps, act, interpret, res, cts):
    x, w, gamma, y, z, mu, r = res
    dz, _dmu, _dvar = cts  # stats feed only the (stop-grad) running avgs
    if act == "relu":
        dz = jnp.where(z > 0, dz, 0).astype(dz.dtype)
    dgamma, dbeta = bn_grad_stats(y, dz, mu, r, interpret=interpret)
    m = y.shape[0]
    k1 = gamma * r
    k2 = gamma * r * r * dgamma / m
    c = gamma * r * dbeta / m
    dx, dw = bn_conv_grads(x, y, dz, w, k1, k2, c, mu, interpret=interpret)
    return dx, dw.astype(w.dtype), dgamma.astype(gamma.dtype), \
        dbeta.astype(gamma.dtype)


fused_conv1x1_bn.defvjp(_fused_fwd_rule, _fused_bwd_rule)
