"""Pallas TPU kernels — the hand-fused hot ops (≡ the reference's cuDNN
helper layer, rebuilt as TPU VMEM-tiled kernels; interpret-mode on CPU)."""
from deeplearning4j_tpu.kernels.flash_attention import (
    flash_attention, flash_attention_decode, flash_attention_decode_mq,
    flash_attention_decode_mq_paged, flash_attention_decode_paged,
    gather_kv_pages, gather_scale_pages)
from deeplearning4j_tpu.kernels.layernorm import fused_layernorm
from deeplearning4j_tpu.kernels.pointwise_conv import (
    int8_matmul_epilogue, matmul_epilogue)

__all__ = ["flash_attention", "flash_attention_decode",
           "flash_attention_decode_mq",
           "flash_attention_decode_mq_paged", "flash_attention_decode_paged",
           "gather_kv_pages", "gather_scale_pages",
           "fused_layernorm", "int8_matmul_epilogue", "matmul_epilogue"]
