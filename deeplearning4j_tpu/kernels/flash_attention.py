"""Pallas TPU flash attention.

The reference accelerates attention-era models by dispatching to
hand-fused cuDNN helpers (deeplearning4j-cuda :: CudnnLSTMHelper etc.);
the TPU-native equivalent of "the hand-tuned fused kernel" is a Pallas
kernel that tiles Q/K/V through VMEM and never materialises the (T, T)
score matrix: online-softmax accumulation per Q tile, MXU matmuls in
bfloat16/f32, O(T) HBM traffic.

Forward is the Pallas kernel; backward is the blockwise (lax.scan)
formulation under jax.vjp — same math, XLA-fused, O(T) memory. On
non-TPU backends the kernel runs in interpret mode so tests exercise the
identical code path.

Layout: (B, H, T, D) like parallel/ring_attention.py; the two compose —
ring attention rotates K/V shards across chips, and each local block can
use this kernel for its on-chip work.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from deeplearning4j_tpu.parallel.ring_attention import blockwise_attention

_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, acc_ref, l_ref, m_ref, *,
                      block_k, causal, scale, t_actual):
    """Grid (BH, q_tiles, k_tiles), k innermost: only one (block_k, d) K/V
    tile is VMEM-resident per step; o/l/m accumulate in VMEM scratch across
    the k dimension and the output tile is written on the last k step."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q = q_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        l_ref[...] = jnp.zeros_like(l_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
        k = k_ref[0]                              # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # (block_q, block_k)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < t_actual
        if causal:
            mask &= q_pos >= k_pos
        s = jnp.where(mask, s, _NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip k-tiles entirely above the diagonal: both MXU matmuls would
        # only produce fully-masked (p == 0) contributions
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)


def _pad_to(x, axis, mult):
    t = x.shape[axis]
    pad = (-t) % mult
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q = min(block_q, max(t, 8))
    block_k = min(block_k, max(t, 8))
    qp = _pad_to(q.reshape(b * h, t, d), 1, block_q)
    kp = _pad_to(k.reshape(b * h, t, d), 1, block_k)
    vp = _pad_to(v.reshape(b * h, t, d), 1, block_k)
    tq = qp.shape[1]
    grid = (b * h, tq // block_q, kp.shape[1] // block_k)
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale, t_actual=t)
    out = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :t, :].reshape(b, h, t, d)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, block_q=128, block_k=128,
                    interpret=None):
    """Fused attention: softmax(QKᵀ/√d)·V without materialising (T,T).

    Pallas on TPU (interpret-mode elsewhere); differentiable — backward
    runs the O(T)-memory blockwise recompute under jax.vjp.
    """
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret)


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    return _flash_forward(q, k, v, causal, block_q, block_k, interpret), \
        (q, k, v)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: blockwise_attention(
            q_, k_, v_, block_size=block_k, causal=causal), q, k, v)
    return vjp(g)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
