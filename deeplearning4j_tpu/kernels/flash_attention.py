"""Pallas TPU flash attention — forward AND backward kernels.

The reference accelerates attention-era models by dispatching to
hand-fused cuDNN helpers (deeplearning4j-cuda :: CudnnLSTMHelper etc.);
the TPU-native equivalent of "the hand-tuned fused kernel" is a Pallas
kernel that tiles Q/K/V through VMEM and never materialises the (T, T)
score matrix: online-softmax accumulation per Q tile, MXU matmuls in
bfloat16/f32, O(T) HBM traffic.

Backward (round 2; round 1 used a blockwise jax.vjp recompute) is the
standard flash-attention-2 split: the forward additionally emits the
per-row logsumexp L; backward precomputes D = rowsum(dO ∘ O), then
- a dQ kernel tiled (q_tiles × k_tiles, k innermost) recomputes
  P = exp(S − L) per tile and accumulates dQ = scale · Σ_k dS·K,
- a dK/dV kernel tiled (k_tiles × q_tiles, q innermost) accumulates
  dV = Σ_q Pᵀ·dO and dK = scale · Σ_q dSᵀ·Q,
with dS = P ∘ (dO·Vᵀ − D). No (T, T) tensor ever hits HBM in either
direction. On non-TPU backends the kernels run in interpret mode so
tests exercise the identical code path.

Layout: (B, H, T, D) like parallel/ring_attention.py; the two compose —
ring attention rotates K/V shards across chips, and each local block can
use this kernel for its on-chip work.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30


def _flash_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, l_ref,
                      m_ref, *, block_k, causal, scale, t_actual):
    """Grid (BH, q_tiles, k_tiles), k innermost: only one (block_k, d) K/V
    tile is VMEM-resident per step; o/l/m accumulate in VMEM scratch across
    the k dimension and the output tile is written on the last k step."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q = q_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        l_ref[...] = jnp.zeros_like(l_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
        k = k_ref[0]                              # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # (block_q, block_k)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < t_actual
        if causal:
            mask &= q_pos >= k_pos
        s = jnp.where(mask, s, _NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip k-tiles entirely above the diagonal: both MXU matmuls would
        # only produce fully-masked (p == 0) contributions
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)
        lse_ref[0] = (m_ref[...] +
                      jnp.log(jnp.maximum(l_ref[...], 1e-30)))[:, 0]


def _pad_to(x, axis, mult):
    t = x.shape[axis]
    pad = (-t) % mult
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _block_sizes(t, block_q, block_k):
    return min(block_q, max(t, 8)), min(block_k, max(t, 8))


def _flash_forward(q, k, v, causal, block_q, block_k, interpret):
    """Returns (out (B,H,T,D), lse (B*H, T_padded))."""
    b, h, t, d = q.shape
    scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q, block_k = _block_sizes(t, block_q, block_k)
    qp = _pad_to(q.reshape(b * h, t, d), 1, block_q)
    kp = _pad_to(k.reshape(b * h, t, d), 1, block_k)
    vp = _pad_to(v.reshape(b * h, t, d), 1, block_k)
    tq = qp.shape[1]
    grid = (b * h, tq // block_q, kp.shape[1] // block_k)
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale, t_actual=t)
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            pl.BlockSpec((1, block_q), lambda bh, i, j: (bh, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp)
    return out[:, :t, :].reshape(b, h, t, d), lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------
def _recompute_p(q_ref, k_ref, lse_ref, qi, kj, block_q, block_k, causal,
                 scale, t_actual):
    """exp(S − L) for this (q, k) tile — the fwd tile re-derived in VMEM."""
    qs = q_ref[0].astype(jnp.float32) * scale
    s = jax.lax.dot_general(
        qs, k_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # (block_q, block_k)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = k_pos < t_actual
    if causal:
        mask &= q_pos >= k_pos
    s = jnp.where(mask, s, _NEG_INF)
    return jnp.exp(s - lse_ref[0][:, None])


def _flash_bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                         dq_ref, dq_acc, *, block_k, causal, scale,
                         t_actual):
    """Grid (BH, q_tiles, k_tiles), k innermost; dq accumulates in VMEM."""
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q = q_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _compute():
        p = _recompute_p(q_ref, k_ref, lse_ref, qi, kj, block_q, block_k,
                         causal, scale, t_actual)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # dO·Vᵀ (bq, bk)
        ds = p * (dp - delta_ref[0][:, None])
        dq_acc[...] += scale * jax.lax.dot_general(
            ds, k_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                          dk_ref, dv_ref, dk_acc, dv_acc, *, block_k,
                          causal, scale, t_actual):
    """Grid (BH, k_tiles, q_tiles), q innermost; dk/dv accumulate in VMEM."""
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    block_q = q_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        p = _recompute_p(q_ref, k_ref, lse_ref, qi, kj, block_q, block_k,
                         causal, scale, t_actual)
        do = do_ref[0].astype(jnp.float32)
        dv_acc[...] += jax.lax.dot_general(
            p, do, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # Pᵀ·dO (bk, d)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0][:, None])
        dk_acc[...] += scale * jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # dSᵀ·Q (bk, d)

    if causal:
        # q-tiles strictly above the diagonal contribute nothing
        pl.when(qi * block_q + block_q - 1 >= kj * block_k)(_compute)
    else:
        _compute()

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, o, lse, g, causal, block_q, block_k, interpret):
    b, h, t, d = q.shape
    scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q, block_k = _block_sizes(t, block_q, block_k)

    # D = rowsum(dO ∘ O) — one fused elementwise pass, O(T·D) traffic
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    qp = _pad_to(q.reshape(b * h, t, d), 1, block_q)
    dop = _pad_to(g.reshape(b * h, t, d), 1, block_q)
    deltap = _pad_to(delta.reshape(b * h, t), 1, block_q)
    kp = _pad_to(k.reshape(b * h, t, d), 1, block_k)
    vp = _pad_to(v.reshape(b * h, t, d), 1, block_k)
    tq, tk = qp.shape[1], kp.shape[1]
    # lse comes back from forward already padded to the q tiling
    lsep = lse if lse.shape[1] == tq else _pad_to(lse, 1, block_q)

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0))
    row_spec = pl.BlockSpec((1, block_q), lambda bh, i, j: (bh, i))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale, t_actual=t),
        grid=(b * h, tq // block_q, tk // block_k),
        in_specs=[q_spec, k_spec, k_spec, q_spec, row_spec, row_spec],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    # dk/dv: swap the roles — k tiles outer, q tiles innermost
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda bh, j, i: (bh, i, 0))
    k_spec2 = pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0))
    row_spec2 = pl.BlockSpec((1, block_q), lambda bh, j, i: (bh, i))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_k=block_k,
                          causal=causal, scale=scale, t_actual=t),
        grid=(b * h, tk // block_k, tq // block_q),
        in_specs=[q_spec2, k_spec2, k_spec2, q_spec2, row_spec2, row_spec2],
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, tk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, deltap)

    dq = dq[:, :t, :].reshape(b, h, t, d)
    dk = dk[:, :t, :].reshape(b, h, t, d)
    dv = dv[:, :t, :].reshape(b, h, t, d)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal=False, block_q=128, block_k=128,
                    interpret=None):
    """Fused attention: softmax(QKᵀ/√d)·V without materialising (T,T).

    Pallas on TPU (interpret-mode elsewhere); differentiable — backward is
    the Pallas dQ / dK-dV kernel pair (flash-attention-2 style recompute
    from the saved logsumexp), O(T) HBM in both directions.
    """
    out, _ = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out


def _flash_fwd_rule(q, k, v, causal, block_q, block_k, interpret):
    out, lse = _flash_forward(q, k, v, causal, block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v, o, lse = res
    return _flash_backward(q, k, v, o, lse, g, causal, block_q, block_k,
                           interpret)


flash_attention.defvjp(_flash_fwd_rule, _flash_bwd_rule)
