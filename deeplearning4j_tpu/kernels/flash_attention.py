"""Pallas TPU flash attention — forward AND backward kernels.

The reference accelerates attention-era models by dispatching to
hand-fused cuDNN helpers (deeplearning4j-cuda :: CudnnLSTMHelper etc.);
the TPU-native equivalent of "the hand-tuned fused kernel" is a Pallas
kernel that tiles Q/K/V through VMEM and never materialises the (T, T)
score matrix: online-softmax accumulation per Q tile, MXU matmuls in
bfloat16/f32, O(T) HBM traffic.

Backward (round 2; round 1 used a blockwise jax.vjp recompute) is the
standard flash-attention-2 split: the forward additionally emits the
per-row logsumexp L; backward precomputes D = rowsum(dO ∘ O), then
- a dQ kernel tiled (q_tiles × k_tiles, k innermost) recomputes
  P = exp(S − L) per tile and accumulates dQ = scale · Σ_k dS·K,
- a dK/dV kernel tiled (k_tiles × q_tiles, q innermost) accumulates
  dV = Σ_q Pᵀ·dO and dK = scale · Σ_q dSᵀ·Q,
with dS = P ∘ (dO·Vᵀ − D). No (T, T) tensor ever hits HBM in either
direction. On non-TPU backends the kernels run in interpret mode so
tests exercise the identical code path.

Layout: (B, H, T, D) like parallel/ring_attention.py; the two compose —
ring attention rotates K/V shards across chips, and each local block can
use this kernel for its on-chip work.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# version compat: newer jax renamed TPUCompilerParams -> CompilerParams
_CompilerParams = getattr(pltpu, "CompilerParams", None) \
    or pltpu.TPUCompilerParams


_NEG_INF = -1e30


def _flash_fwd_kernel(*refs, block_k, causal, scale, tk_actual, has_mask):
    """Grid (BH, q_tiles, k_tiles), k innermost: only one (block_k, d) K/V
    tile is VMEM-resident per step; o/l/m accumulate in VMEM scratch across
    the k dimension and the output tile is written on the last k step.
    The q and k tilings are independent, so Tq ≠ Tk (cross-attention)
    falls out of the same kernel.

    With has_mask, an extra (1, block_k) int32 KEY-validity tile (from the
    per-example (B, Tk) padding mask) masks scores; invalid QUERY rows are
    handled outside the kernel (outputs zeroed, lse forced to +inf so the
    backward recompute sees p == 0)."""
    if has_mask:
        q_ref, k_ref, v_ref, km_ref, o_ref, lse_ref, acc_ref, l_ref, m_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref, lse_ref, acc_ref, l_ref, m_ref = refs
        km_ref = None
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q = q_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)
        l_ref[...] = jnp.zeros_like(l_ref)
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)

    def _compute():
        q = q_ref[0].astype(jnp.float32) * scale  # (block_q, d)
        k = k_ref[0]                              # (block_k, d)
        v = v_ref[0]
        s = jax.lax.dot_general(
            q, k.astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)   # (block_q, block_k)
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0)
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1)
        mask = k_pos < tk_actual
        if causal:
            mask &= q_pos >= k_pos
        if has_mask:
            mask &= km_ref[0] > 0            # (1, block_k) broadcasts
        s = jnp.where(mask, s, _NEG_INF)
        m_prev, l_prev = m_ref[...], l_ref[...]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_ref[...] = l_prev * alpha + jnp.sum(p, axis=-1, keepdims=True)
        m_ref[...] = m_new
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        # skip k-tiles entirely above the diagonal: both MXU matmuls would
        # only produce fully-masked (p == 0) contributions
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        o_ref[0] = (acc_ref[...] /
                    jnp.maximum(l_ref[...], 1e-30)).astype(o_ref.dtype)
        lse_ref[0, 0] = (m_ref[...] +
                         jnp.log(jnp.maximum(l_ref[...], 1e-30)))[:, 0]


def _pad_to(x, axis, mult):
    t = x.shape[axis]
    pad = (-t) % mult
    if not pad:
        return x
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths)


def _block_sizes(tq, tk, block_q, block_k):
    return min(block_q, max(tq, 8)), min(block_k, max(tk, 8))


def _prep_mask(mask, block_k):
    """(B, T) truthy mask → int32 (B, 1, T_padded) for (1, 1, block_k)
    tiles (zero padding = invalid keys, matching the padded K/V rows)."""
    return _pad_to(mask.astype(jnp.int32), 1, block_k)[:, None, :]


def _flash_forward(q, k, v, q_mask, kv_mask, causal, block_q, block_k,
                   interpret):
    """Returns (out (B,H,Tq,D), lse (B*H, Tq_padded)). `kv_mask` is an
    optional (B, Tk) KEY-validity mask; `q_mask` an optional (B, Tq)
    QUERY-validity mask — invalid q rows come back zeroed with
    lse = +1e30 so the backward kernels recompute p == 0 for them.
    Self-attention passes the same (B, T) mask for both."""
    b, h, tq_a, d = q.shape
    tk_a = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q, block_k = _block_sizes(tq_a, tk_a, block_q, block_k)
    qp = _pad_to(q.reshape(b * h, tq_a, d), 1, block_q)
    kp = _pad_to(k.reshape(b * h, tk_a, d), 1, block_k)
    vp = _pad_to(v.reshape(b * h, tk_a, d), 1, block_k)
    tq = qp.shape[1]
    grid = (b * h, tq // block_q, kp.shape[1] // block_k)
    kernel = functools.partial(_flash_fwd_kernel, block_k=block_k,
                               causal=causal, scale=scale, tk_actual=tk_a,
                               has_mask=kv_mask is not None)
    in_specs = [
        pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
        pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0)),
    ]
    operands = [qp, kp, vp]
    if kv_mask is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda bh, i, j: (bh // h, 0, j)))
        operands.append(_prep_mask(kv_mask, block_k))
    out, lse = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0)),
            # row vectors ride as (N, 1, T) with (1, 1, block) tiles:
            # a 2-D (1, block) tile violates the Mosaic (8, 128) minimum
            # unless the block covers the full array dim
            pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
            jax.ShapeDtypeStruct((b * h, 1, tq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, d), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)
    lse = lse[:, 0]
    out = out[:, :tq_a, :].reshape(b, h, tq_a, d)
    if q_mask is not None or kv_mask is not None:
        qvalid = (jnp.ones((b, tq_a), bool) if q_mask is None
                  else q_mask.astype(bool))             # (B, Tq)
        if kv_mask is not None:
            # an example with NO valid keys has no defined softmax: its
            # query rows come back zeroed, and the lse = +1e30 sentinel
            # makes the backward recompute p == 0 (no dk/dv leak into
            # fully-padded K/V)
            qvalid &= kv_mask.astype(bool).any(axis=1)[:, None]
        out = jnp.where(qvalid[:, None, :, None], out, 0)
        lse_valid = _pad_to(qvalid, 1, block_q)[:, None, :]  # (B, 1, tq)
        lse = jnp.where(
            jnp.broadcast_to(lse_valid, (b, h, tq)).reshape(b * h, tq),
            lse, 1e30)
    return out, lse


# ---------------------------------------------------------------------------
# backward kernels
# ---------------------------------------------------------------------------
def _recompute_p(q_ref, k_ref, lse_ref, km_ref, qi, kj, block_q, block_k,
                 causal, scale, tk_actual):
    """exp(S − L) for this (q, k) tile — the fwd tile re-derived in VMEM.
    Invalid q rows carry lse == +1e30 (set by the forward wrapper), so
    exp(finite − 1e30) underflows to exactly 0 without a q-side mask."""
    qs = q_ref[0].astype(jnp.float32) * scale
    s = jax.lax.dot_general(
        qs, k_ref[0].astype(jnp.float32),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)  # (block_q, block_k)
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0)
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1)
    mask = k_pos < tk_actual
    if causal:
        mask &= q_pos >= k_pos
    if km_ref is not None:
        mask &= km_ref[0] > 0
    s = jnp.where(mask, s, _NEG_INF)
    return jnp.exp(s - lse_ref[0, 0][:, None])


def _flash_bwd_dq_kernel(*refs, block_k, causal, scale, tk_actual, has_mask):
    """Grid (BH, q_tiles, k_tiles), k innermost; dq accumulates in VMEM."""
    if has_mask:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, km_ref,
         dq_ref, dq_acc) = refs
    else:
        q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref, dq_acc = refs
        km_ref = None
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    block_q = q_ref.shape[1]

    @pl.when(kj == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    def _compute():
        p = _recompute_p(q_ref, k_ref, lse_ref, km_ref, qi, kj, block_q,
                         block_k, causal, scale, tk_actual)
        do = do_ref[0].astype(jnp.float32)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)     # dO·Vᵀ (bq, bk)
        ds = p * (dp - delta_ref[0, 0][:, None])
        dq_acc[...] += scale * jax.lax.dot_general(
            ds, k_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        pl.when(kj * block_k <= qi * block_q + block_q - 1)(_compute)
    else:
        _compute()

    @pl.when(kj == pl.num_programs(2) - 1)
    def _finalize():
        dq_ref[0] = dq_acc[...].astype(dq_ref.dtype)


def _flash_bwd_dkv_kernel(*refs, block_k, causal, scale, tk_actual,
                          has_mask):
    """Grid (BH, k_tiles, q_tiles), q innermost; dk/dv accumulate in VMEM."""
    if has_mask:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, km_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
    else:
        (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
         dk_ref, dv_ref, dk_acc, dv_acc) = refs
        km_ref = None
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    block_q = q_ref.shape[1]

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    def _compute():
        p = _recompute_p(q_ref, k_ref, lse_ref, km_ref, qi, kj, block_q,
                         block_k, causal, scale, tk_actual)
        do = do_ref[0].astype(jnp.float32)
        dv_acc[...] += jax.lax.dot_general(
            p, do, dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # Pᵀ·dO (bk, d)
        dp = jax.lax.dot_general(
            do, v_ref[0].astype(jnp.float32),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0, 0][:, None])
        dk_acc[...] += scale * jax.lax.dot_general(
            ds, q_ref[0].astype(jnp.float32),
            dimension_numbers=(((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)     # dSᵀ·Q (bk, d)

    if causal:
        # q-tiles strictly above the diagonal contribute nothing
        pl.when(qi * block_q + block_q - 1 >= kj * block_k)(_compute)
    else:
        _compute()

    @pl.when(qi == pl.num_programs(2) - 1)
    def _finalize():
        dk_ref[0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[...].astype(dv_ref.dtype)


def _flash_backward(q, k, v, q_mask, kv_mask, o, lse, g, causal, block_q,
                    block_k, interpret):
    b, h, tq_a, d = q.shape
    tk_a = k.shape[2]
    scale = 1.0 / (d ** 0.5)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    block_q, block_k = _block_sizes(tq_a, tk_a, block_q, block_k)
    has_mask = kv_mask is not None

    # D = rowsum(dO ∘ O) — one fused elementwise pass, O(T·D) traffic
    delta = jnp.sum(g.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)

    qp = _pad_to(q.reshape(b * h, tq_a, d), 1, block_q)
    dop = _pad_to(g.reshape(b * h, tq_a, d), 1, block_q)
    deltap = _pad_to(delta.reshape(b * h, tq_a), 1, block_q)[:, None, :]
    kp = _pad_to(k.reshape(b * h, tk_a, d), 1, block_k)
    vp = _pad_to(v.reshape(b * h, tk_a, d), 1, block_k)
    tq, tk = qp.shape[1], kp.shape[1]
    # lse comes back from forward already padded to the q tiling
    lsep = (lse if lse.shape[1] == tq
            else _pad_to(lse, 1, block_q))[:, None, :]

    q_spec = pl.BlockSpec((1, block_q, d), lambda bh, i, j: (bh, i, 0))
    k_spec = pl.BlockSpec((1, block_k, d), lambda bh, i, j: (bh, j, 0))
    row_spec = pl.BlockSpec((1, 1, block_q), lambda bh, i, j: (bh, 0, i))

    kmp = _prep_mask(kv_mask, block_k) if has_mask else None
    operands = [qp, kp, vp, dop, lsep, deltap]
    in_specs = [q_spec, k_spec, k_spec, q_spec, row_spec, row_spec]
    if has_mask:
        operands.append(kmp)
        in_specs.append(
            pl.BlockSpec((1, 1, block_k), lambda bh, i, j: (bh // h, 0, j)))

    dq = pl.pallas_call(
        functools.partial(_flash_bwd_dq_kernel, block_k=block_k,
                          causal=causal, scale=scale, tk_actual=tk_a,
                          has_mask=has_mask),
        grid=(b * h, tq // block_q, tk // block_k),
        in_specs=in_specs,
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct((b * h, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*operands)

    # dk/dv: swap the roles — k tiles outer, q tiles innermost
    q_spec2 = pl.BlockSpec((1, block_q, d), lambda bh, j, i: (bh, i, 0))
    k_spec2 = pl.BlockSpec((1, block_k, d), lambda bh, j, i: (bh, j, 0))
    row_spec2 = pl.BlockSpec((1, 1, block_q), lambda bh, j, i: (bh, 0, i))
    operands2 = [qp, kp, vp, dop, lsep, deltap]
    in_specs2 = [q_spec2, k_spec2, k_spec2, q_spec2, row_spec2, row_spec2]
    if has_mask:
        operands2.append(kmp)
        in_specs2.append(
            pl.BlockSpec((1, 1, block_k), lambda bh, j, i: (bh // h, 0, j)))
    dk, dv = pl.pallas_call(
        functools.partial(_flash_bwd_dkv_kernel, block_k=block_k,
                          causal=causal, scale=scale, tk_actual=tk_a,
                          has_mask=has_mask),
        grid=(b * h, tk // block_k, tq // block_q),
        in_specs=in_specs2,
        out_specs=[k_spec2, k_spec2],
        out_shape=[jax.ShapeDtypeStruct((b * h, tk, d), k.dtype),
                   jax.ShapeDtypeStruct((b * h, tk, d), v.dtype)],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")),
        interpret=interpret,
    )(*operands2)

    dq = dq[:, :tq_a, :].reshape(b, h, tq_a, d)
    dk = dk[:, :tk_a, :].reshape(b, h, tk_a, d)
    dv = dv[:, :tk_a, :].reshape(b, h, tk_a, d)
    return dq, dk, dv


def _zero_mask_cotangent(mask):
    if mask is None:
        return None
    if jnp.issubdtype(mask.dtype, jnp.inexact):
        # float masks (e.g. 0/1 float32 from DataSet masks) need a real
        # zero cotangent — float0 is only valid for int/bool primals
        return jnp.zeros(mask.shape, mask.dtype)
    import numpy as np
    return np.zeros(mask.shape, dtype=jax.dtypes.float0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash_attention_vjp(q, k, v, q_mask, kv_mask, causal, block_q, block_k,
                         interpret):
    out, _ = _flash_forward(q, k, v, q_mask, kv_mask, causal, block_q,
                            block_k, interpret)
    return out


def _flash_fwd_rule(q, k, v, q_mask, kv_mask, causal, block_q, block_k,
                    interpret):
    out, lse = _flash_forward(q, k, v, q_mask, kv_mask, causal, block_q,
                              block_k, interpret)
    return out, (q, k, v, q_mask, kv_mask, out, lse)


def _flash_bwd_rule(causal, block_q, block_k, interpret, res, g):
    q, k, v, q_mask, kv_mask, o, lse = res
    dq, dk, dv = _flash_backward(q, k, v, q_mask, kv_mask, o, lse, g,
                                 causal, block_q, block_k, interpret)
    return (dq, dk, dv, _zero_mask_cotangent(q_mask),
            _zero_mask_cotangent(kv_mask))


_flash_attention_vjp.defvjp(_flash_fwd_rule, _flash_bwd_rule)


def flash_attention(q, k, v, causal=False, block_q=128, block_k=128,
                    interpret=None, mask=None, kv_mask=None):
    """Fused attention: softmax(QKᵀ/√d)·V without materialising (Tq,Tk).

    Pallas on TPU (interpret-mode elsewhere); differentiable — backward is
    the Pallas dQ / dK-dV kernel pair (flash-attention-2 style recompute
    from the saved logsumexp), O(T) HBM in both directions. The q and k
    tilings are independent, so CROSS-attention (Tq ≠ Tk) uses the same
    kernels.

    Masks for padded batches:
    - self-attention: pass `mask` (B, T) — a False position is invalid as
      both key and query; its keys are excluded from every softmax and its
      output rows come back as zeros, matching a masked dense attention
      whose padded rows are zeroed.
    - cross-attention: pass `kv_mask` (B, Tk) for key/value padding and
      optionally `mask` (B, Tq) for query-row padding.
    Gradients flow to q/k/v only at valid positions.
    """
    tq, tk = q.shape[2], k.shape[2]
    if causal and tq != tk:
        raise ValueError(
            f"causal flash attention requires Tq == Tk, got {tq} != {tk}")
    if mask is not None and mask.ndim != 2:
        raise ValueError(f"mask must be (batch, seq), got {mask.shape}")
    if kv_mask is not None and kv_mask.ndim != 2:
        raise ValueError(
            f"kv_mask must be (batch, kv_seq), got {kv_mask.shape}")
    if kv_mask is None:
        if mask is not None and tq != tk:
            raise ValueError(
                "a single (B, T) mask implies self-attention (Tq == Tk); "
                f"got Tq={tq}, Tk={tk} — pass kv_mask for cross-attention")
        kv_mask = mask
    if mask is not None and mask.shape[1] != tq:
        raise ValueError(
            f"query mask length {mask.shape[1]} != Tq {tq}")
    if kv_mask is not None and kv_mask.shape[1] != tk:
        raise ValueError(
            f"kv_mask length {kv_mask.shape[1]} != Tk {tk}")
    return _flash_attention_vjp(q, k, v, mask, kv_mask, causal, block_q,
                                block_k, interpret)


# ---------------------------------------------------------------------------
# decode kernel: one query token against a cached K/V
# ---------------------------------------------------------------------------
def _decode_reference(q, k_cache, v_cache, cache_mask):
    """Einsum oracle for the decode path — softmax(q·Kᵀ/√d)·V over the
    VALID cache rows only. Fully-invalid rows (no cached keys) come back
    zeroed, matching the Pallas kernel's empty-softmax convention."""
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhcd->bhqc", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) * scale
    valid = cache_mask.astype(bool)
    s = jnp.where(valid[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqc,bhcd->bhqd", p,
                     v_cache.astype(jnp.float32)).astype(q.dtype)
    any_valid = valid.any(axis=-1)
    return jnp.where(any_valid[:, None, None, None], out, 0)


def _decode_reference_quantized(q, k_cache, v_cache, cache_mask,
                                k_scale, v_scale):
    """Decode attention over an int8-quantized cache with the dequant
    FUSED into the contractions: the per-row key scale multiplies the
    score logits (s·(k_row·ks) = (s·k_row)·ks), the per-row value scale
    folds onto the softmax weights before the value pass — no
    dequantized fp cache copy ever materializes; the cache reads stay
    int8 (quantize/kvcache.py's traffic argument)."""
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhcd->bhqc", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) * scale
    s = s * k_scale[:, :, None, :].astype(jnp.float32)
    valid = cache_mask.astype(bool)
    s = jnp.where(valid[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    pv = p * v_scale[:, :, None, :].astype(jnp.float32)
    out = jnp.einsum("bhqc,bhcd->bhqd", pv,
                     v_cache.astype(jnp.float32)).astype(q.dtype)
    any_valid = valid.any(axis=-1)
    return jnp.where(any_valid[:, None, None, None], out, 0)


def flash_attention_decode_mq(q, k_cache, v_cache, q_mask, impl="auto"):
    """Multi-query decode attention: a DRAFT block of queries per
    sequence attends the cached K/V under a per-query validity mask.

    The greedy-drafting verification primitive (generation/): the host
    proposes `d-1` draft tokens, the decode loop runs the q-block
    `[current, draft_0, ..., draft_{d-2}]` through the model in ONE
    dispatch, and each query j may only see cache rows written at or
    before its own position — a causal pattern offset into the cache,
    expressed as the explicit per-query mask `q_mask[b, j, c]` (row c
    valid for query j). Amortizes the per-token dispatch exactly like
    the superstep, but with the verification semantics drafting needs.

    - q: (B, H, Tq, D) — the draft-block queries (Tq = block length)
    - k_cache / v_cache: (B, H, C, D)
    - q_mask: (B, Tq, C) truthy — valid cache rows PER QUERY (ragged
      slots and the intra-block causal offset in one mask)
    - impl: 'auto'/'dense' run the einsum contraction; 'pallas' is
      rejected — the streaming-softmax kernel has no per-query ragged
      mask slot yet, and the draft block is tiny (d ≤ ~8), so the
      (B, H, d, C) score tensor is far below kernel-worthy size.
    Forward-only. Queries with NO valid cache row return zeros
    (matching `flash_attention_decode`'s empty-softmax convention).
    """
    if q.ndim != 4:
        raise ValueError(f"q must be (B, H, Tq, D), got {q.shape}")
    if k_cache.shape != v_cache.shape or k_cache.ndim != 4:
        raise ValueError(
            f"k_cache/v_cache must match as (B, H, C, D): "
            f"{k_cache.shape} vs {v_cache.shape}")
    expect = (q.shape[0], q.shape[2], k_cache.shape[2])
    if tuple(q_mask.shape) != expect:
        raise ValueError(
            f"q_mask must be (B, Tq, C) = {expect}, got {q_mask.shape}")
    if impl == "pallas":
        raise ValueError(
            "impl='pallas' has no multi-query ragged-mask variant — "
            "the draft q-block runs the einsum path on every backend")
    if impl not in ("auto", "dense"):
        raise ValueError(
            f"unknown decode impl {impl!r}; expected 'auto', 'pallas' "
            "or 'dense'")
    d = q.shape[-1]
    scale = 1.0 / (d ** 0.5)
    s = jnp.einsum("bhqd,bhcd->bhqc", q.astype(jnp.float32),
                   k_cache.astype(jnp.float32)) * scale
    valid = q_mask.astype(bool)                       # (B, Tq, C)
    s = jnp.where(valid[:, None, :, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    out = jnp.einsum("bhqc,bhcd->bhqd", p,
                     v_cache.astype(jnp.float32)).astype(q.dtype)
    any_valid = valid.any(axis=-1)                    # (B, Tq)
    return jnp.where(any_valid[:, None, :, None], out, 0)


def flash_attention_decode(q1, k_cache, v_cache, cache_mask, impl="auto",
                           block_k=128, interpret=None, k_scale=None,
                           v_scale=None):
    """Incremental-decode attention: a SINGLE query block per sequence
    attends over that sequence's cached K/V under a cache-validity mask.

    The KV-cache serving hot path (generation/): at decode step t the
    cache holds keys/values for positions 0..t (the current token's K/V
    already written), `cache_mask` marks which cache rows are real
    (ragged per sequence — slots in a continuous batch sit at different
    positions), and the query is the current token only. O(C·D) HBM
    per step instead of the O(T²) full-sequence re-forward.

    - q1: (B, H, D) or (B, H, 1, D) — current-token query
    - k_cache / v_cache: (B, H, C, D) — rolling caches (C = cache rung)
    - cache_mask: (B, C) truthy — valid cache rows (ragged lengths)
    - impl: 'auto' (Pallas kernel on TPU, einsum elsewhere), 'pallas'
      (force kernel; interpret-mode off-TPU), or 'dense'
    - k_scale / v_scale: (B, H, C) float32 per-head row scales of an
      int8-quantized cache (quantize/kvcache.py). When given, the
      dequant happens INSIDE the attention contractions — the single-
      query decode pass is a bandwidth-bound GEMV, so reading the
      cache at int8 width is the point; a materializing dequant would
      give the traffic straight back. (The quantized path is einsum-
      based on every backend: the scales fold onto logits/softmax
      weights, which the Pallas fp kernel's streaming-softmax layout
      has no slot for yet.)
    Forward-only (decode never backprops). Rows whose mask has NO valid
    cache entry return zeros. Returns the same rank as q1.
    """
    squeeze = q1.ndim == 3
    q = q1[:, :, None, :] if squeeze else q1
    if q.ndim != 4 or q.shape[2] != 1:
        raise ValueError(
            f"q1 must be (B, H, D) or (B, H, 1, D), got {q1.shape}")
    if k_cache.shape != v_cache.shape or k_cache.ndim != 4:
        raise ValueError(
            f"k_cache/v_cache must match as (B, H, C, D): "
            f"{k_cache.shape} vs {v_cache.shape}")
    if cache_mask.shape != (q.shape[0], k_cache.shape[2]):
        raise ValueError(
            f"cache_mask must be (B, C) = "
            f"{(q.shape[0], k_cache.shape[2])}, got {cache_mask.shape}")
    if impl not in ("auto", "pallas", "dense"):
        raise ValueError(
            f"unknown decode impl {impl!r}; expected 'auto', 'pallas' "
            "or 'dense'")
    if (k_scale is None) != (v_scale is None):
        raise ValueError("k_scale and v_scale must be given together")
    if k_scale is not None:
        if impl == "pallas":
            raise ValueError(
                "impl='pallas' has no int8-cache variant (the "
                "streaming-softmax kernel has no slot for per-row "
                "scales yet) — use 'auto' or 'dense' with a "
                "quantized cache")
        expect = (q.shape[0], q.shape[1], k_cache.shape[2])
        if tuple(k_scale.shape) != expect \
                or tuple(v_scale.shape) != expect:
            raise ValueError(
                f"k_scale/v_scale must be (B, H, C) = {expect}, got "
                f"{k_scale.shape} / {v_scale.shape}")
        out = _decode_reference_quantized(q, k_cache, v_cache,
                                          cache_mask, k_scale, v_scale)
        return out[:, :, 0, :] if squeeze else out
    if impl == "auto":
        impl = "pallas" if jax.default_backend() == "tpu" else "dense"
    if impl == "pallas":
        out, _ = _flash_forward(q, k_cache, v_cache, None, cache_mask,
                                causal=False, block_q=128, block_k=block_k,
                                interpret=interpret)
    elif impl == "dense":
        out = _decode_reference(q, k_cache, v_cache, cache_mask)
    else:
        raise ValueError(
            f"unknown decode impl {impl!r}; expected 'auto', 'pallas' "
            "or 'dense'")
    return out[:, :, 0, :] if squeeze else out


# ---------------------------------------------------------------------------
# paged decode: attention reading a pooled KV through a per-slot page index
# ---------------------------------------------------------------------------
def gather_kv_pages(pool, page_table):
    """Materialize the per-slot contiguous cache VIEW from a paged pool.

    - pool: (P, H, ps, D) — one layer's KV page pool (P physical pages
      of `ps` rows each; page 0 is the null/scratch page by convention)
    - page_table: (B, n) int32 — physical page id per (slot, logical
      page); unmapped entries point at page 0 and are hidden by the
      caller's cache mask
    Returns (B, H, n·ps, D) — bit-identical to the slot-contiguous
    cache layout, so the existing masked-softmax decode arithmetic
    (and therefore token streams) carries over unchanged.
    """
    if pool.ndim != 4:
        raise ValueError(f"pool must be (P, H, ps, D), got {pool.shape}")
    if page_table.ndim != 2:
        raise ValueError(
            f"page_table must be (B, n_pages), got {page_table.shape}")
    b, n = page_table.shape
    _, h, ps, d = pool.shape
    g = jnp.take(pool, page_table, axis=0)      # (B, n, H, ps, D)
    return g.transpose(0, 2, 1, 3, 4).reshape(b, h, n * ps, d)


def gather_scale_pages(scale_pool, page_table):
    """Per-row scale twin of `gather_kv_pages` for the int8 pool.

    - scale_pool: (P, H, ps) float32 — per-row quantization scales
    - page_table: (B, n) int32
    Returns (B, H, n·ps) ready for the scale-folding einsum path.
    """
    if scale_pool.ndim != 3:
        raise ValueError(
            f"scale_pool must be (P, H, ps), got {scale_pool.shape}")
    b, n = page_table.shape
    _, h, ps = scale_pool.shape
    g = jnp.take(scale_pool, page_table, axis=0)  # (B, n, H, ps)
    return g.transpose(0, 2, 1, 3).reshape(b, h, n * ps)


def flash_attention_decode_paged(q1, k_pool, v_pool, page_table,
                                 cache_mask, impl="auto", block_k=128,
                                 interpret=None, k_scale_pool=None,
                                 v_scale_pool=None):
    """`flash_attention_decode` generalized to gather-by-page: the query
    attends a (B, H, C, D) view gathered from a device-resident page
    pool through the per-slot page index, C = n_pages·ps.

    Pages let ragged sequences pay for the rows they use instead of a
    worst-case rung (µ-cuDNN's fixed-block thesis applied to cache
    memory), and let identical prompt prefixes share physical pages.
    The gather feeds the UNCHANGED masked-softmax machinery — einsum
    reference, Pallas kernel, and the int8 scale-folding path all see
    the same (B, H, C, D) operands as the slot-contiguous layout, so
    streams stay bit-identical.

    - q1: (B, H, D) or (B, H, 1, D)
    - k_pool / v_pool: (P, H, ps, D) — pooled pages (int8 under
      `kv_dtype="int8"`, halving page bytes)
    - page_table: (B, n_pages) int32 physical page ids
    - cache_mask: (B, n_pages·ps) — valid ROWS of the gathered view
    - k_scale_pool / v_scale_pool: (P, H, ps) float32 scales of an
      int8 pool; folded inside the contractions as in the contiguous
      path
    """
    if k_pool.shape != v_pool.shape or k_pool.ndim != 4:
        raise ValueError(
            f"k_pool/v_pool must match as (P, H, ps, D): "
            f"{k_pool.shape} vs {v_pool.shape}")
    if (k_scale_pool is None) != (v_scale_pool is None):
        raise ValueError(
            "k_scale_pool and v_scale_pool must be given together")
    kc = gather_kv_pages(k_pool, page_table)
    vc = gather_kv_pages(v_pool, page_table)
    ks = vs = None
    if k_scale_pool is not None:
        ks = gather_scale_pages(k_scale_pool, page_table)
        vs = gather_scale_pages(v_scale_pool, page_table)
    return flash_attention_decode(q1, kc, vc, cache_mask, impl=impl,
                                  block_k=block_k, interpret=interpret,
                                  k_scale=ks, v_scale=vs)


def flash_attention_decode_mq_paged(q, k_pool, v_pool, page_table,
                                    q_mask, impl="auto"):
    """`flash_attention_decode_mq` through the page index: the drafting
    verify dispatch reads the SAME paged pool as the superstep scan, so
    every decode mode inherits paging from one gather. Operands as in
    `flash_attention_decode_mq` with (k_pool, v_pool, page_table) in
    place of the contiguous caches."""
    if k_pool.shape != v_pool.shape or k_pool.ndim != 4:
        raise ValueError(
            f"k_pool/v_pool must match as (P, H, ps, D): "
            f"{k_pool.shape} vs {v_pool.shape}")
    kc = gather_kv_pages(k_pool, page_table)
    vc = gather_kv_pages(v_pool, page_table)
    return flash_attention_decode_mq(q, kc, vc, q_mask, impl=impl)
