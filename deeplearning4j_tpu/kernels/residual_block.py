"""Pallas fused ResNet bottleneck block — the round-5 pass-removal
experiment (VERDICT r4 weak #3 / BENCH.md "remaining headroom").

Hypothesis: the measured ResNet-50 step runs at ~95% of the HBM bound for
the graph XLA BUILT, but that graph still round-trips every intermediate
activation of each bottleneck block through HBM. One kernel that keeps
the whole block's intermediates in VMEM — batch-tiled, weights resident —
reads x once and writes the output once:

    h1 = relu(x @ W1 + b1)            (1x1 reduce,  C -> M)
    h2 = relu(conv3x3(h1, W2) + b2)   (9 shifted GEMMs, M -> M)
    y  = relu(h2 @ W3 + b3 + x)       (1x1 expand,  M -> C, residual)

HBM traffic per block ≈ |x| + |y| + |W| instead of XLA's
|x|·2 + |h1|·2 + |h2|·2 + |y| (+ the residual re-read) — roughly 2x less
for the 14x14x1024/256 stage shape. BN is assumed FOLDED into the conv
scale/bias (inference form — the standard deployment transform); the
training-step integration would additionally need the custom-VJP
treatment pointwise_conv.py gives the 1x1+BN pair.

The on-chip A/B against the identical XLA composition is exp_tpu_r5.py;
correctness (exact equality vs the XLA reference) is
tests/test_kernels.py on the interpret path.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl


def _default_interpret():
    return jax.default_backend() != "tpu"


def _block_kernel(x_ref, w1_ref, b1_ref, w2_ref, b2_ref, w3_ref, b3_ref,
                  o_ref):
    x = x_ref[...]                                  # (bt, H, W, C)
    bt, h, w, c = x.shape
    mid = w1_ref.shape[1]
    xf = x.reshape(bt * h * w, c)
    h1 = jnp.maximum(
        jnp.dot(xf, w1_ref[...], preferred_element_type=jnp.float32)
        + b1_ref[...], 0.0).astype(x.dtype)
    h1 = h1.reshape(bt, h, w, mid)
    # SAME padding via concatenate (maps onto Mosaic more reliably than
    # the pad primitive)
    zrow = jnp.zeros((bt, 1, w, mid), h1.dtype)
    h1p = jnp.concatenate([zrow, h1, zrow], axis=1)
    zcol = jnp.zeros((bt, h + 2, 1, mid), h1.dtype)
    h1p = jnp.concatenate([zcol, h1p, zcol], axis=2)
    acc = jnp.zeros((bt * h * w, mid), jnp.float32)
    for dy in range(3):                             # 9 shifted GEMMs ==
        for dx in range(3):                         # SAME 3x3 conv
            win = h1p[:, dy:dy + h, dx:dx + w, :].reshape(bt * h * w, mid)
            acc += jnp.dot(win, w2_ref[dy, dx],
                           preferred_element_type=jnp.float32)
    h2 = jnp.maximum(acc + b2_ref[...], 0.0).astype(x.dtype)
    h3 = jnp.dot(h2, w3_ref[...], preferred_element_type=jnp.float32) \
        + b3_ref[...]
    y = jnp.maximum(h3.reshape(bt, h, w, c) + x.astype(jnp.float32), 0.0)
    o_ref[...] = y.astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block_b", "interpret"))
def _run(x, w1, b1, w2, b2, w3, b3, block_b, interpret):
    b, h, w, c = x.shape
    mid = w1.shape[1]
    grid = (b // block_b,)
    return pl.pallas_call(
        _block_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_b, h, w, c), lambda i: (i, 0, 0, 0)),
            pl.BlockSpec((c, mid), lambda i: (0, 0)),
            pl.BlockSpec((1, mid), lambda i: (0, 0)),
            pl.BlockSpec((3, 3, mid, mid), lambda i: (0, 0, 0, 0)),
            pl.BlockSpec((1, mid), lambda i: (0, 0)),
            pl.BlockSpec((mid, c), lambda i: (0, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_b, h, w, c), lambda i: (i, 0, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b, h, w, c), x.dtype),
        interpret=interpret,
    )(x, w1, b1, w2, b2, w3, b3)


def bottleneck_block(x, w1, b1, w2, b2, w3, b3, block_b=8, interpret=None):
    """Fused bottleneck forward. x (B,H,W,C) NHWC; w1 (C,M), w2 (3,3,M,M),
    w3 (M,C); biases (M,)/(M,)/(C,) — BN folded. B % block_b == 0."""
    if interpret is None:
        interpret = _default_interpret()
    b = x.shape[0]
    if b % block_b:
        raise ValueError(f"batch {b} not divisible by block_b={block_b}")
    return _run(x, w1, b1.reshape(1, -1), w2, b2.reshape(1, -1), w3,
                b3.reshape(1, -1), block_b, interpret)


def bottleneck_block_xla(x, w1, b1, w2, b2, w3, b3):
    """The identical math as plain XLA ops (the A/B baseline and the
    correctness oracle)."""
    dn = ("NHWC", "HWIO", "NHWC")
    h1 = jax.nn.relu(
        jax.lax.conv_general_dilated(
            x, w1[None, None].astype(x.dtype), (1, 1), "SAME",
            dimension_numbers=dn,
            preferred_element_type=jnp.float32) + b1)
    h1 = h1.astype(x.dtype)
    h2 = jax.nn.relu(
        jax.lax.conv_general_dilated(
            h1, w2.astype(x.dtype), (1, 1), "SAME", dimension_numbers=dn,
            preferred_element_type=jnp.float32) + b2)
    h2 = h2.astype(x.dtype)
    h3 = jax.lax.conv_general_dilated(
        h2, w3[None, None].astype(x.dtype), (1, 1), "SAME",
        dimension_numbers=dn, preferred_element_type=jnp.float32) + b3
    return jax.nn.relu(h3 + x.astype(jnp.float32)).astype(x.dtype)
