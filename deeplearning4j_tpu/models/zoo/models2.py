"""Model zoo, part 2 (≡ deeplearning4j-zoo :: org.deeplearning4j.zoo.model.
Darknet19, VGG19, SqueezeNet, Xception, InceptionResNetV1).

Same TPU-first conventions as models.py: NHWC, bf16-friendly, built
through the public config DSL.
"""
from __future__ import annotations

from deeplearning4j_tpu.models.zoo.models import ZooModel
from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import (ElementWiseVertex,
                                                       MergeVertex,
                                                       ScaleVertex)
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               ConvolutionLayer, DenseLayer,
                                               DropoutLayer,
                                               GlobalPoolingLayer,
                                               OutputLayer,
                                               SeparableConvolution2D,
                                               SubsamplingLayer)
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs


class Darknet19(ZooModel):
    """≡ zoo.model.Darknet19 — the YOLO9000 classifier backbone:
    3×3/1×1 conv stacks with BN+leakyrelu, five maxpools, 1×1×classes
    conv head + global average pooling."""

    DEFAULT_INPUT = (224, 224, 3)

    def conf(self):
        h, w, c = self.inputShape
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Nesterovs(1e-3, 0.9))
             .weightInit("relu")
             .l2(5e-4)
             .dataType(self.dataType)
             .list())

        def conv_bn(n_out, k):
            b.layer(ConvolutionLayer(kernelSize=(k, k), nOut=n_out,
                                     convolutionMode="same", hasBias=False,
                                     activation="identity"))
            b.layer(BatchNormalization(activation="leakyrelu"))

        def pool():
            b.layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))

        conv_bn(32, 3); pool()
        conv_bn(64, 3); pool()
        conv_bn(128, 3); conv_bn(64, 1); conv_bn(128, 3); pool()
        conv_bn(256, 3); conv_bn(128, 1); conv_bn(256, 3); pool()
        conv_bn(512, 3); conv_bn(256, 1); conv_bn(512, 3)
        conv_bn(256, 1); conv_bn(512, 3); pool()
        conv_bn(1024, 3); conv_bn(512, 1); conv_bn(1024, 3)
        conv_bn(512, 1); conv_bn(1024, 3)
        b.layer(ConvolutionLayer(kernelSize=(1, 1), nOut=self.numClasses,
                                 convolutionMode="same",
                                 activation="identity"))
        b.layer(GlobalPoolingLayer(poolingType="avg"))
        b.layer(OutputLayer(lossFunction="mcxent", nOut=self.numClasses,
                            activation="softmax"))
        return b.setInputType(InputType.convolutional(h, w, c)).build()


class VGG19(ZooModel):
    """≡ zoo.model.VGG19 — VGG16 with the 4-conv deep stages."""

    def conf(self):
        h, w, c = self.inputShape
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Nesterovs(1e-2, 0.9))
             .weightInit("relu")
             .activation("relu")
             .dataType(self.dataType)
             .list())
        for n_out, reps in [(64, 2), (128, 2), (256, 4), (512, 4), (512, 4)]:
            for _ in range(reps):
                b.layer(ConvolutionLayer(kernelSize=(3, 3), nOut=n_out,
                                         convolutionMode="same"))
            b.layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
        return (b.layer(DenseLayer(nOut=4096, dropOut=0.5))
                 .layer(DenseLayer(nOut=4096, dropOut=0.5))
                 .layer(OutputLayer(lossFunction="mcxent",
                                    nOut=self.numClasses,
                                    activation="softmax"))
                 .setInputType(InputType.convolutional(h, w, c))
                 .build())


class SqueezeNet(ZooModel):
    """≡ zoo.model.SqueezeNet (v1.1) — fire modules: 1×1 squeeze then
    parallel 1×1/3×3 expands concatenated (MergeVertex)."""

    def conf(self):
        h, w, c = self.inputShape
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Adam(1e-3))
             .weightInit("relu")
             .dataType(self.dataType)
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def fire(name, inp, squeeze, expand):
            g.addLayer(f"{name}_sq", ConvolutionLayer(
                kernelSize=(1, 1), nOut=squeeze, activation="relu",
                convolutionMode="same"), inp)
            g.addLayer(f"{name}_e1", ConvolutionLayer(
                kernelSize=(1, 1), nOut=expand, activation="relu",
                convolutionMode="same"), f"{name}_sq")
            g.addLayer(f"{name}_e3", ConvolutionLayer(
                kernelSize=(3, 3), nOut=expand, activation="relu",
                convolutionMode="same"), f"{name}_sq")
            g.addVertex(f"{name}_cat", MergeVertex(),
                        f"{name}_e1", f"{name}_e3")
            return f"{name}_cat"

        g.addLayer("conv1", ConvolutionLayer(kernelSize=(3, 3),
                                             stride=(2, 2), nOut=64,
                                             activation="relu",
                                             convolutionMode="same"),
                   "input")
        g.addLayer("pool1", SubsamplingLayer(kernelSize=(3, 3),
                                             stride=(2, 2),
                                             convolutionMode="same"),
                   "conv1")
        x = fire("fire2", "pool1", 16, 64)
        x = fire("fire3", x, 16, 64)
        g.addLayer("pool3", SubsamplingLayer(kernelSize=(3, 3),
                                             stride=(2, 2),
                                             convolutionMode="same"), x)
        x = fire("fire4", "pool3", 32, 128)
        x = fire("fire5", x, 32, 128)
        g.addLayer("pool5", SubsamplingLayer(kernelSize=(3, 3),
                                             stride=(2, 2),
                                             convolutionMode="same"), x)
        x = fire("fire6", "pool5", 48, 192)
        x = fire("fire7", x, 48, 192)
        x = fire("fire8", x, 64, 256)
        x = fire("fire9", x, 64, 256)
        g.addLayer("drop", DropoutLayer(dropOut=0.5), x)
        g.addLayer("conv10", ConvolutionLayer(kernelSize=(1, 1),
                                              nOut=self.numClasses,
                                              activation="relu",
                                              convolutionMode="same"),
                   "drop")
        g.addLayer("gap", GlobalPoolingLayer(poolingType="avg"), "conv10")
        g.addLayer("out", OutputLayer(lossFunction="mcxent",
                                      nOut=self.numClasses,
                                      activation="softmax"), "gap")
        g.setOutputs("out")
        return g.build()


class Xception(ZooModel):
    """≡ zoo.model.Xception — depthwise-separable conv stacks with
    linear residual shortcuts (entry/middle/exit flow, middle depth
    configurable to keep CPU tests tractable)."""

    def __init__(self, middleFlowBlocks=8, **kw):
        super().__init__(**kw)
        self.middleFlowBlocks = middleFlowBlocks

    DEFAULT_INPUT = (299, 299, 3)

    def conf(self):
        h, w, c = self.inputShape
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Nesterovs(0.045, 0.9))
             .weightInit("relu")
             .dataType(self.dataType)
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv_bn(name, inp, n_out, k, s, act="relu"):
            g.addLayer(f"{name}_c", ConvolutionLayer(
                kernelSize=k, stride=s, nOut=n_out, hasBias=False,
                convolutionMode="same", activation="identity"), inp)
            g.addLayer(f"{name}_bn", BatchNormalization(activation=act),
                       f"{name}_c")
            return f"{name}_bn"

        def sep_bn(name, inp, n_out, act="relu"):
            g.addLayer(f"{name}_s", SeparableConvolution2D(
                kernelSize=(3, 3), nOut=n_out, hasBias=False,
                convolutionMode="same", activation="identity"), inp)
            g.addLayer(f"{name}_bn", BatchNormalization(activation=act),
                       f"{name}_s")
            return f"{name}_bn"

        def xception_block(name, inp, n_out, relu_first=True):
            """two sep convs + stride-2 pool, 1×1 stride-2 residual."""
            x = inp
            if relu_first:
                g.addLayer(f"{name}_pre", ActivationLayer(
                    activation="relu"), x)
                x = f"{name}_pre"
            x = sep_bn(f"{name}_s1", x, n_out)
            x = sep_bn(f"{name}_s2", x, n_out, act="identity")
            g.addLayer(f"{name}_pool", SubsamplingLayer(
                kernelSize=(3, 3), stride=(2, 2), convolutionMode="same"), x)
            sc = conv_bn(f"{name}_sc", inp, n_out, (1, 1), (2, 2),
                         act="identity")
            g.addVertex(f"{name}_add", ElementWiseVertex("add"),
                        f"{name}_pool", sc)
            return f"{name}_add"

        x = conv_bn("stem1", "input", 32, (3, 3), (2, 2))
        x = conv_bn("stem2", x, 64, (3, 3), (1, 1))
        x = xception_block("entry1", x, 128, relu_first=False)
        x = xception_block("entry2", x, 256)
        x = xception_block("entry3", x, 728)
        for i in range(self.middleFlowBlocks):
            inp = x
            y = inp
            for j in range(3):
                g.addLayer(f"mid{i}_relu{j}", ActivationLayer(
                    activation="relu"), y)
                y = sep_bn(f"mid{i}_s{j}", f"mid{i}_relu{j}", 728,
                           act="identity")
            g.addVertex(f"mid{i}_add", ElementWiseVertex("add"), y, inp)
            x = f"mid{i}_add"
        x = xception_block("exit1", x, 1024)
        x = sep_bn("exit2", x, 1536)
        x = sep_bn("exit3", x, 2048)
        g.addLayer("gap", GlobalPoolingLayer(poolingType="avg"), x)
        g.addLayer("out", OutputLayer(lossFunction="mcxent",
                                      nOut=self.numClasses,
                                      activation="softmax"), "gap")
        g.setOutputs("out")
        return g.build()


class InceptionResNetV1(ZooModel):
    """≡ zoo.model.InceptionResNetV1 — inception branches merged then
    1×1-projected, residual-added with a ScaleVertex(0.17/0.10) exactly
    as the reference scales its residual summands. Block counts are
    configurable (defaults are the paper's 5/10/5)."""

    def __init__(self, blocks=(5, 10, 5), **kw):
        super().__init__(**kw)
        self.blocks = blocks

    DEFAULT_INPUT = (160, 160, 3)

    def conf(self):
        h, w, c = self.inputShape
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Adam(1e-3))
             .weightInit("relu")
             .dataType(self.dataType)
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv_bn(name, inp, n_out, k, s=(1, 1), act="relu"):
            g.addLayer(f"{name}_c", ConvolutionLayer(
                kernelSize=k, stride=s, nOut=n_out, hasBias=False,
                convolutionMode="same", activation="identity"), inp)
            g.addLayer(f"{name}_bn", BatchNormalization(activation=act),
                       f"{name}_c")
            return f"{name}_bn"

        def block35(name, inp, width):
            """Inception-ResNet-A: 1×1 / 1×1-3×3 / 1×1-3×3-3×3 branches."""
            b0 = conv_bn(f"{name}_b0", inp, 32, (1, 1))
            b1 = conv_bn(f"{name}_b1a", inp, 32, (1, 1))
            b1 = conv_bn(f"{name}_b1b", b1, 32, (3, 3))
            b2 = conv_bn(f"{name}_b2a", inp, 32, (1, 1))
            b2 = conv_bn(f"{name}_b2b", b2, 32, (3, 3))
            b2 = conv_bn(f"{name}_b2c", b2, 32, (3, 3))
            g.addVertex(f"{name}_cat", MergeVertex(), b0, b1, b2)
            g.addLayer(f"{name}_proj", ConvolutionLayer(
                kernelSize=(1, 1), nOut=width, convolutionMode="same",
                activation="identity"), f"{name}_cat")
            g.addVertex(f"{name}_scale", ScaleVertex(0.17), f"{name}_proj")
            g.addVertex(f"{name}_add", ElementWiseVertex("add"), inp,
                        f"{name}_scale")
            g.addLayer(f"{name}_relu", ActivationLayer(activation="relu"),
                       f"{name}_add")
            return f"{name}_relu"

        def block17(name, inp, width):
            b0 = conv_bn(f"{name}_b0", inp, 128, (1, 1))
            b1 = conv_bn(f"{name}_b1a", inp, 128, (1, 1))
            b1 = conv_bn(f"{name}_b1b", b1, 128, (1, 7))
            b1 = conv_bn(f"{name}_b1c", b1, 128, (7, 1))
            g.addVertex(f"{name}_cat", MergeVertex(), b0, b1)
            g.addLayer(f"{name}_proj", ConvolutionLayer(
                kernelSize=(1, 1), nOut=width, convolutionMode="same",
                activation="identity"), f"{name}_cat")
            g.addVertex(f"{name}_scale", ScaleVertex(0.10), f"{name}_proj")
            g.addVertex(f"{name}_add", ElementWiseVertex("add"), inp,
                        f"{name}_scale")
            g.addLayer(f"{name}_relu", ActivationLayer(activation="relu"),
                       f"{name}_add")
            return f"{name}_relu"

        # stem
        x = conv_bn("stem1", "input", 32, (3, 3), (2, 2))
        x = conv_bn("stem2", x, 64, (3, 3))
        g.addLayer("stem_pool", SubsamplingLayer(
            kernelSize=(3, 3), stride=(2, 2), convolutionMode="same"), x)
        x = conv_bn("stem3", "stem_pool", 128, (1, 1))
        x = conv_bn("stem4", x, 192, (3, 3))
        x = conv_bn("stem5", x, 256, (3, 3), (2, 2))
        for i in range(self.blocks[0]):
            x = block35(f"a{i}", x, 256)
        x = conv_bn("redA", x, 512, (3, 3), (2, 2))
        for i in range(self.blocks[1]):
            x = block17(f"b{i}", x, 512)
        x = conv_bn("redB", x, 896, (3, 3), (2, 2))
        g.addLayer("gap", GlobalPoolingLayer(poolingType="avg"), x)
        g.addLayer("drop", DropoutLayer(dropOut=0.8), "gap")
        g.addLayer("bottleneck", DenseLayer(nOut=128,
                                            activation="identity"), "drop")
        g.addLayer("out", OutputLayer(lossFunction="mcxent",
                                      nOut=self.numClasses,
                                      activation="softmax"), "bottleneck")
        g.setOutputs("out")
        return g.build()


class YOLO2(ZooModel):
    """≡ zoo.model.YOLO2 — Darknet19 backbone + space-to-depth
    passthrough (the 'reorg' route) + Yolo2OutputLayer with the
    reference's COCO box priors."""

    DEFAULT_INPUT = (416, 416, 3)
    PRIORS = [[0.57273, 0.677385], [1.87446, 2.06253], [3.33843, 5.47434],
              [7.88282, 3.52778], [9.77052, 9.16828]]

    def __init__(self, numClasses=80, boxes=None, **kw):
        super().__init__(numClasses=numClasses, **kw)
        from deeplearning4j_tpu.models.zoo.models import _resolve_priors
        self.priors = _resolve_priors(boxes, self.PRIORS)

    def conf(self):
        from deeplearning4j_tpu.nn.conf.graph_vertices import \
            SpaceToDepthVertex
        from deeplearning4j_tpu.nn.conf.objdetect import Yolo2OutputLayer
        h, w, c = self.inputShape
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Adam(1e-3))
             .weightInit("relu")
             .l2(5e-4)
             .dataType(self.dataType)
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv_bn(name, inp, n_out, k):
            g.addLayer(f"{name}_c", ConvolutionLayer(
                kernelSize=(k, k), nOut=n_out, convolutionMode="same",
                hasBias=False, activation="identity"), inp)
            g.addLayer(f"{name}_bn",
                       BatchNormalization(activation="leakyrelu"),
                       f"{name}_c")
            return f"{name}_bn"

        def pool(name, inp):
            g.addLayer(name, SubsamplingLayer(kernelSize=(2, 2),
                                              stride=(2, 2)), inp)
            return name

        x = conv_bn("c1", "input", 32, 3); x = pool("p1", x)
        x = conv_bn("c2", x, 64, 3); x = pool("p2", x)
        x = conv_bn("c3", x, 128, 3)
        x = conv_bn("c4", x, 64, 1)
        x = conv_bn("c5", x, 128, 3); x = pool("p3", x)
        x = conv_bn("c6", x, 256, 3)
        x = conv_bn("c7", x, 128, 1)
        x = conv_bn("c8", x, 256, 3); x = pool("p4", x)
        for i, (n, k) in enumerate([(512, 3), (256, 1), (512, 3),
                                    (256, 1), (512, 3)]):
            x = conv_bn(f"c9_{i}", x, n, k)
        route = x                       # 26×26×512 passthrough source
        x = pool("p5", x)
        for i, (n, k) in enumerate([(1024, 3), (512, 1), (1024, 3),
                                    (512, 1), (1024, 3)]):
            x = conv_bn(f"c10_{i}", x, n, k)
        x = conv_bn("c11", x, 1024, 3)
        x = conv_bn("c12", x, 1024, 3)
        g.addVertex("reorg", SpaceToDepthVertex(2), route)   # → 13×13×2048
        g.addVertex("route_cat", MergeVertex(), "reorg", x)
        x = conv_bn("c13", "route_cat", 1024, 3)
        head = len(self.priors) * (5 + self.numClasses)
        g.addLayer("head", ConvolutionLayer(kernelSize=(1, 1), nOut=head,
                                            convolutionMode="same",
                                            activation="identity"), x)
        g.addLayer("out", Yolo2OutputLayer(boundingBoxes=self.priors),
                   "head")
        g.setOutputs("out")
        return g.build()


class FaceNetNN4Small2(ZooModel):
    """≡ zoo.model.FaceNetNN4Small2 — nn4.small2-style inception embedding
    net: stem + inception(3a/3b/4a/4e/5a/5b)-like modules, 128-d
    L2-bottleneck, CenterLossOutputLayer head (the reference's center-loss
    FaceNet training setup)."""

    DEFAULT_INPUT = (96, 96, 3)

    def __init__(self, numClasses=10, embeddingSize=128, **kw):
        super().__init__(numClasses=numClasses, **kw)
        self.embeddingSize = int(embeddingSize)

    def conf(self):
        from deeplearning4j_tpu.nn.conf.special_layers import \
            CenterLossOutputLayer
        h, w, c = self.inputShape
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Adam(1e-3))
             .weightInit("relu")
             .dataType(self.dataType)
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv_bn(name, inp, n_out, k, s=(1, 1)):
            g.addLayer(f"{name}_c", ConvolutionLayer(
                kernelSize=k, stride=s, nOut=n_out, hasBias=False,
                convolutionMode="same", activation="identity"), inp)
            g.addLayer(f"{name}_bn", BatchNormalization(activation="relu"),
                       f"{name}_c")
            return f"{name}_bn"

        def inception(name, inp, n1, n3r, n3, n5r, n5, pp):
            b1 = conv_bn(f"{name}_1", inp, n1, (1, 1))
            b3 = conv_bn(f"{name}_3r", inp, n3r, (1, 1))
            b3 = conv_bn(f"{name}_3", b3, n3, (3, 3))
            b5 = conv_bn(f"{name}_5r", inp, n5r, (1, 1))
            b5 = conv_bn(f"{name}_5", b5, n5, (5, 5))
            g.addLayer(f"{name}_pool", SubsamplingLayer(
                kernelSize=(3, 3), stride=(1, 1), convolutionMode="same"),
                inp)
            bp = conv_bn(f"{name}_pp", f"{name}_pool", pp, (1, 1))
            g.addVertex(f"{name}_cat", MergeVertex(), b1, b3, b5, bp)
            return f"{name}_cat"

        x = conv_bn("stem1", "input", 64, (7, 7), (2, 2))
        g.addLayer("stem_pool", SubsamplingLayer(
            kernelSize=(3, 3), stride=(2, 2), convolutionMode="same"), x)
        x = conv_bn("stem2", "stem_pool", 64, (1, 1))
        x = conv_bn("stem3", x, 192, (3, 3))
        g.addLayer("stem_pool2", SubsamplingLayer(
            kernelSize=(3, 3), stride=(2, 2), convolutionMode="same"), x)
        x = inception("i3a", "stem_pool2", 64, 96, 128, 16, 32, 32)
        x = inception("i3b", x, 64, 96, 128, 32, 64, 64)
        g.addLayer("pool3", SubsamplingLayer(
            kernelSize=(3, 3), stride=(2, 2), convolutionMode="same"), x)
        x = inception("i4a", "pool3", 256, 96, 192, 32, 64, 128)
        x = inception("i4e", x, 256, 160, 256, 64, 128, 128)
        g.addLayer("pool4", SubsamplingLayer(
            kernelSize=(3, 3), stride=(2, 2), convolutionMode="same"), x)
        x = inception("i5a", "pool4", 256, 96, 384, 32, 64, 96)
        g.addLayer("gap", GlobalPoolingLayer(poolingType="avg"), x)
        g.addLayer("bottleneck", DenseLayer(nOut=self.embeddingSize,
                                            activation="identity"), "gap")
        from deeplearning4j_tpu.nn.conf.graph_vertices import \
            L2NormalizeVertex
        g.addVertex("embeddings", L2NormalizeVertex(), "bottleneck")
        g.addLayer("out", CenterLossOutputLayer(
            lambda_=2e-4, alpha=0.9, nOut=self.numClasses,
            activation="softmax"), "embeddings")
        g.setOutputs("out")
        return g.build()


class NASNet(ZooModel):
    """≡ zoo.model.NASNet (NASNet-A mobile shape) — stem + alternating
    normal/reduction cells built from separable-conv branch combinations
    concatenated per cell. Cell counts/penultimate filters configurable
    (defaults follow the mobile variant scaled by `filters`)."""

    DEFAULT_INPUT = (224, 224, 3)

    def __init__(self, numBlocks=2, filters=44, stemFilters=32, **kw):
        super().__init__(**kw)
        self.numBlocks = int(numBlocks)
        self.filters = int(filters)
        self.stemFilters = int(stemFilters)

    def conf(self):
        h, w, c = self.inputShape
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Adam(1e-3))
             .weightInit("relu")
             .l2(5e-5)
             .dataType(self.dataType)
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv_bn(name, inp, n_out, k, s=(1, 1), act="relu"):
            g.addLayer(f"{name}_c", ConvolutionLayer(
                kernelSize=k, stride=s, nOut=n_out, hasBias=False,
                convolutionMode="same", activation="identity"), inp)
            g.addLayer(f"{name}_bn", BatchNormalization(activation=act),
                       f"{name}_c")
            return f"{name}_bn"

        def sep_bn(name, inp, n_out, k, s=(1, 1)):
            g.addLayer(f"{name}_s", SeparableConvolution2D(
                kernelSize=k, stride=s, nOut=n_out, hasBias=False,
                convolutionMode="same", activation="identity"), inp)
            g.addLayer(f"{name}_bn", BatchNormalization(activation="relu"),
                       f"{name}_s")
            return f"{name}_bn"

        def normal_cell(name, inp, filters):
            """NASNet-A normal cell (single-input simplification of the
            two-hidden-state wiring): sep3/sep5/pool/identity branches
            summed pairwise, outputs concatenated."""
            p = conv_bn(f"{name}_sq", inp, filters, (1, 1))
            b1a = sep_bn(f"{name}_b1a", p, filters, (5, 5))
            b1b = sep_bn(f"{name}_b1b", p, filters, (3, 3))
            g.addVertex(f"{name}_a1", ElementWiseVertex("add"), b1a, b1b)
            g.addLayer(f"{name}_pool", SubsamplingLayer(
                poolingType="avg", kernelSize=(3, 3), stride=(1, 1),
                convolutionMode="same"), p)
            g.addVertex(f"{name}_a2", ElementWiseVertex("add"),
                        f"{name}_pool", p)
            b3a = sep_bn(f"{name}_b3a", p, filters, (3, 3))
            g.addVertex(f"{name}_a3", ElementWiseVertex("add"), b3a, p)
            g.addVertex(f"{name}_cat", MergeVertex(),
                        f"{name}_a1", f"{name}_a2", f"{name}_a3")
            return f"{name}_cat"

        def reduction_cell(name, inp, filters):
            p = conv_bn(f"{name}_sq", inp, filters, (1, 1))
            b1 = sep_bn(f"{name}_b1", p, filters, (5, 5), (2, 2))
            b2 = sep_bn(f"{name}_b2", p, filters, (7, 7), (2, 2))
            g.addVertex(f"{name}_a1", ElementWiseVertex("add"), b1, b2)
            g.addLayer(f"{name}_mp", SubsamplingLayer(
                poolingType="max", kernelSize=(3, 3), stride=(2, 2),
                convolutionMode="same"), p)
            b3 = sep_bn(f"{name}_b3", p, filters, (3, 3), (2, 2))
            g.addVertex(f"{name}_a2", ElementWiseVertex("add"),
                        f"{name}_mp", b3)
            g.addVertex(f"{name}_cat", MergeVertex(),
                        f"{name}_a1", f"{name}_a2")
            return f"{name}_cat"

        x = conv_bn("stem", "input", self.stemFilters, (3, 3), (2, 2))
        f = self.filters
        for i in range(self.numBlocks):
            x = normal_cell(f"n1_{i}", x, f)
        x = reduction_cell("r1", x, f * 2)
        for i in range(self.numBlocks):
            x = normal_cell(f"n2_{i}", x, f * 2)
        x = reduction_cell("r2", x, f * 4)
        for i in range(self.numBlocks):
            x = normal_cell(f"n3_{i}", x, f * 4)
        g.addLayer("relu_out", ActivationLayer(activation="relu"), x)
        g.addLayer("gap", GlobalPoolingLayer(poolingType="avg"),
                   "relu_out")
        g.addLayer("drop", DropoutLayer(dropOut=0.5), "gap")
        g.addLayer("out", OutputLayer(lossFunction="mcxent",
                                      nOut=self.numClasses,
                                      activation="softmax"), "drop")
        g.setOutputs("out")
        return g.build()


class EfficientNet(ZooModel):
    """≡ zoo.model.EfficientNet (1.0.0-M1 zoo addition) — MBConv stacks
    with squeeze-and-excitation, swish activations, and compound
    width/depth/resolution scaling (variants B0-B7).

    TPU-first notes: depthwise convs use the grouped-conv MXU path
    (DepthwiseConvolution2D), SE channel gating is a broadcasted
    ElementWiseVertex product against a (1, 1, C) ReshapeVertex output
    (XLA fuses the gap→dense→dense→scale chain into the block), and the
    whole network remains one jitted program like every zoo model."""

    #: variant -> (width_mult, depth_mult, default resolution, dropout)
    VARIANTS = {"B0": (1.0, 1.0, 224, 0.2), "B1": (1.0, 1.1, 240, 0.2),
                "B2": (1.1, 1.2, 260, 0.3), "B3": (1.2, 1.4, 300, 0.3),
                "B4": (1.4, 1.8, 380, 0.4), "B5": (1.6, 2.2, 456, 0.4),
                "B6": (1.8, 2.6, 528, 0.5), "B7": (2.0, 3.1, 600, 0.5)}

    #: base (B0) stage spec: expand, channels, repeats, stride, kernel
    STAGES = ((1, 16, 1, 1, 3), (6, 24, 2, 2, 3), (6, 40, 2, 2, 5),
              (6, 80, 3, 2, 3), (6, 112, 3, 1, 5), (6, 192, 4, 2, 5),
              (6, 320, 1, 1, 3))

    def __init__(self, variant="B0", **kw):
        if variant not in self.VARIANTS:
            raise ValueError(f"unknown EfficientNet variant {variant!r}; "
                             f"pick one of {sorted(self.VARIANTS)}")
        self.variant = variant
        w, d, res, drop = self.VARIANTS[variant]
        self.width_mult, self.depth_mult = w, d
        self.dropout_rate = drop   # reference scales dropout with size
        self.DEFAULT_INPUT = (res, res, 3)
        super().__init__(**kw)

    @staticmethod
    def _round_filters(filters, width_mult, divisor=8):
        """Reference filter rounding: scale, snap to divisor, never drop
        below 90% of the scaled value."""
        f = filters * width_mult
        new = max(divisor, int(f + divisor / 2) // divisor * divisor)
        if new < 0.9 * f:
            new += divisor
        return int(new)

    @staticmethod
    def _round_repeats(repeats, depth_mult):
        import math
        return int(math.ceil(repeats * depth_mult))

    def conf(self):
        from deeplearning4j_tpu.nn.conf.graph_vertices import ReshapeVertex
        from deeplearning4j_tpu.nn.conf.layers import \
            DepthwiseConvolution2D
        h, w, c = self.inputShape
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Adam(1e-3))
             .weightInit("relu")
             .dataType(self.dataType)
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv_bn(name, inp, n_out, k, s, act="swish", groups_dw=False):
            layer = (DepthwiseConvolution2D(
                         kernelSize=k, stride=s, hasBias=False,
                         convolutionMode="same", activation="identity")
                     if groups_dw else
                     ConvolutionLayer(
                         kernelSize=k, stride=s, nOut=n_out, hasBias=False,
                         convolutionMode="same", activation="identity"))
            g.addLayer(f"{name}_c", layer, inp)
            g.addLayer(f"{name}_bn", BatchNormalization(activation=act),
                       f"{name}_c")
            return f"{name}_bn"

        def mbconv(name, inp, cin, cout, expand, k, stride):
            cexp = cin * expand
            x = inp
            if expand != 1:
                x = conv_bn(f"{name}_e", x, cexp, (1, 1), (1, 1))
            x = conv_bn(f"{name}_d", x, cexp, (k, k), (stride, stride),
                        groups_dw=True)
            # squeeze-and-excitation: ratio 0.25 of the block INPUT chans
            se_ch = max(1, int(cin * 0.25))
            g.addLayer(f"{name}_se_gap",
                       GlobalPoolingLayer(poolingType="avg"), x)
            g.addLayer(f"{name}_se_r", DenseLayer(
                nOut=se_ch, activation="swish"), f"{name}_se_gap")
            g.addLayer(f"{name}_se_x", DenseLayer(
                nOut=cexp, activation="sigmoid"), f"{name}_se_r")
            g.addVertex(f"{name}_se_rs", ReshapeVertex(-1, 1, 1, cexp),
                        f"{name}_se_x")
            g.addVertex(f"{name}_se_mul", ElementWiseVertex("product"),
                        x, f"{name}_se_rs")
            x = conv_bn(f"{name}_p", f"{name}_se_mul", cout, (1, 1), (1, 1),
                        act="identity")
            if stride == 1 and cin == cout:
                g.addVertex(f"{name}_add", ElementWiseVertex("add"), x, inp)
                return f"{name}_add", cout
            return x, cout

        stem_ch = self._round_filters(32, self.width_mult)
        x = conv_bn("stem", "input", stem_ch, (3, 3), (2, 2))
        cin = stem_ch
        for si, (expand, ch, reps, stride, k) in enumerate(self.STAGES):
            cout = self._round_filters(ch, self.width_mult)
            for r in range(self._round_repeats(reps, self.depth_mult)):
                x, cin = mbconv(f"s{si}r{r}", x, cin, cout, expand, k,
                                stride if r == 0 else 1)
        head_ch = self._round_filters(1280, self.width_mult)
        x = conv_bn("head", x, head_ch, (1, 1), (1, 1))
        g.addLayer("gap", GlobalPoolingLayer(poolingType="avg"), x)
        g.addLayer("drop", DropoutLayer(dropOut=1.0 - self.dropout_rate),
                   "gap")
        g.addLayer("out", OutputLayer(lossFunction="mcxent",
                                      nOut=self.numClasses,
                                      activation="softmax"), "drop")
        g.setOutputs("out")
        return g.build()
