"""Model zoo (≡ deeplearning4j-zoo :: org.deeplearning4j.zoo.model.*:
LeNet, AlexNet, VGG16, ResNet50, SimpleCNN, UNet, TinyYOLO,
TextGenerationLSTM).

All models build through the SAME public config DSL a user would write —
they are living examples of the builder API. TPU-first choices: NHWC
layouts, bf16-friendly (pass dataType="bfloat16"), identity-shortcut
ResNet with fused BN, big matmuls in classifier heads.

ZooModel surface parity: `ResNet50(numClasses=...).init()` returns the
network; `initPretrained()` is gated (zero-egress environment, documented).
"""
from __future__ import annotations

from deeplearning4j_tpu.nn.conf.builders import NeuralNetConfiguration
from deeplearning4j_tpu.nn.conf.graph_vertices import ElementWiseVertex
from deeplearning4j_tpu.nn.conf.inputs import InputType
from deeplearning4j_tpu.nn.conf.layers import (ActivationLayer,
                                               BatchNormalization,
                                               ConvolutionLayer, DenseLayer,
                                               DropoutLayer,
                                               GlobalPoolingLayer, LossLayer,
                                               OutputLayer, SubsamplingLayer,
                                               Upsampling2D, ZeroPaddingLayer)
from deeplearning4j_tpu.nn.conf.recurrent import LSTM, RnnOutputLayer
from deeplearning4j_tpu.nn.graph import ComputationGraph
from deeplearning4j_tpu.nn.multilayer import MultiLayerNetwork
from deeplearning4j_tpu.nn.updaters import Adam, Nesterovs


class ZooModel:
    """Base surface (≡ org.deeplearning4j.zoo.ZooModel)."""

    def __init__(self, numClasses=1000, seed=123, inputShape=None,
                 updater=None, dataType="float32"):
        self.numClasses = int(numClasses)
        self.seed = int(seed)
        self.inputShape = inputShape or self.DEFAULT_INPUT
        self.updater = updater
        self.dataType = dataType

    DEFAULT_INPUT = (224, 224, 3)

    def conf(self):
        raise NotImplementedError

    def init(self):
        conf = self.conf()
        from deeplearning4j_tpu.nn.conf.graph_builder import \
            ComputationGraphConfiguration
        if isinstance(conf, ComputationGraphConfiguration):
            return ComputationGraph(conf).init()
        return MultiLayerNetwork(conf).init()

    #: directory scanned for local pretrained checkpoints
    #: (`<modelname>_<dataset>.zip` — ModelSerializer layout — or
    #: `<modelname>_<dataset>.h5` — Keras weights)
    PRETRAINED_DIR_ENV = "DL4J_TPU_PRETRAINED_DIR"

    def _pretrained_path(self, dataset):
        import os
        d = os.environ.get(self.PRETRAINED_DIR_ENV, "")
        if not d:
            return None
        name = type(self).__name__.lower()
        for ext in (".zip", ".h5"):
            p = os.path.join(d, f"{name}_{str(dataset).lower()}{ext}")
            if os.path.exists(p):
                return p
        return None

    def initPretrained(self, dataset="imagenet", path=None):
        """Initialize with REAL trained weights from a LOCAL checkpoint
        (≡ ZooModel.initPretrained; the reference downloads from its zoo
        bucket — this environment has no egress, so the file must already
        exist: pass `path=` or set $DL4J_TPU_PRETRAINED_DIR).

        Supports our ModelSerializer zip (config + params npz: returns the
        checkpointed network whole, like the reference's restore) and
        Keras .h5 weight files (name-mapped onto this zoo config's layers;
        conv kernels are HWIO in both stacks — no layout transpose)."""
        path = path or self._pretrained_path(dataset)
        if path is None:
            raise RuntimeError(
                f"No local pretrained checkpoint for "
                f"{type(self).__name__}/{dataset}: pass path= or put "
                f"<model>_<dataset>.zip/.h5 under "
                f"${self.PRETRAINED_DIR_ENV} (no network egress).")
        if str(path).endswith(".h5"):
            net = self.init()
            from deeplearning4j_tpu.keras_import.keras_import import (
                _load_h5_weights_graph, _load_h5_weights_multilayer)
            if isinstance(net, ComputationGraph):
                net = _load_h5_weights_graph(net, path)
            else:
                net = _load_h5_weights_multilayer(net, path)
            if getattr(net, "_h5_layers_loaded", 0) == 0:
                raise RuntimeError(
                    f"{path}: no layer names in the .h5 match this "
                    f"{type(self).__name__} config — refusing to return a "
                    f"random-init network as 'pretrained'. (Our layers are "
                    f"named layer0..layerN unless set explicitly.)")
            return net
        from deeplearning4j_tpu.util.model_serializer import ModelSerializer
        return ModelSerializer.restoreModel(path)

    def pretrainedAvailable(self, dataset="imagenet"):
        return self._pretrained_path(dataset) is not None


class LeNet(ZooModel):
    """≡ zoo.model.LeNet — the classic MNIST CNN."""

    DEFAULT_INPUT = (28, 28, 1)

    def conf(self):
        h, w, c = self.inputShape
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(self.updater or Nesterovs(0.01, 0.9))
                .weightInit("xavier")
                .dataType(self.dataType)
                .list()
                .layer(ConvolutionLayer(kernelSize=(5, 5), stride=(1, 1),
                                        nOut=20, activation="identity",
                                        convolutionMode="same"))
                .layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                        stride=(2, 2)))
                .layer(ConvolutionLayer(kernelSize=(5, 5), stride=(1, 1),
                                        nOut=50, activation="identity",
                                        convolutionMode="same"))
                .layer(SubsamplingLayer(poolingType="max", kernelSize=(2, 2),
                                        stride=(2, 2)))
                .layer(DenseLayer(nOut=500, activation="relu"))
                .layer(OutputLayer(lossFunction="negativeloglikelihood",
                                   nOut=self.numClasses,
                                   activation="softmax"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class SimpleCNN(ZooModel):
    """≡ zoo.model.SimpleCNN."""

    DEFAULT_INPUT = (48, 48, 3)

    def conf(self):
        h, w, c = self.inputShape
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(self.updater or Adam(1e-3))
                .weightInit("relu")
                .activation("relu")
                .dataType(self.dataType)
                .list()
                .layer(ConvolutionLayer(kernelSize=(7, 7), nOut=16,
                                        convolutionMode="same"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(kernelSize=(5, 5), nOut=32,
                                        convolutionMode="same"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
                .layer(ConvolutionLayer(kernelSize=(3, 3), nOut=64,
                                        convolutionMode="same"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
                .layer(DenseLayer(nOut=128))
                .layer(DropoutLayer(dropOut=0.5))
                .layer(OutputLayer(lossFunction="mcxent",
                                   nOut=self.numClasses,
                                   activation="softmax"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class AlexNet(ZooModel):
    """≡ zoo.model.AlexNet (one-tower variant)."""

    def conf(self):
        h, w, c = self.inputShape
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(self.updater or Nesterovs(1e-2, 0.9))
                .weightInit("relu")
                .activation("relu")
                .l2(5e-4)
                .dataType(self.dataType)
                .list()
                .layer(ConvolutionLayer(kernelSize=(11, 11), stride=(4, 4),
                                        nOut=96, convolutionMode="same"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(kernelSize=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(kernelSize=(5, 5), nOut=256,
                                        convolutionMode="same"))
                .layer(BatchNormalization())
                .layer(SubsamplingLayer(kernelSize=(3, 3), stride=(2, 2)))
                .layer(ConvolutionLayer(kernelSize=(3, 3), nOut=384,
                                        convolutionMode="same"))
                .layer(ConvolutionLayer(kernelSize=(3, 3), nOut=384,
                                        convolutionMode="same"))
                .layer(ConvolutionLayer(kernelSize=(3, 3), nOut=256,
                                        convolutionMode="same"))
                .layer(SubsamplingLayer(kernelSize=(3, 3), stride=(2, 2)))
                .layer(DenseLayer(nOut=4096, dropOut=0.5))
                .layer(DenseLayer(nOut=4096, dropOut=0.5))
                .layer(OutputLayer(lossFunction="mcxent",
                                   nOut=self.numClasses,
                                   activation="softmax"))
                .setInputType(InputType.convolutional(h, w, c))
                .build())


class VGG16(ZooModel):
    """≡ zoo.model.VGG16."""

    def conf(self):
        h, w, c = self.inputShape
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Nesterovs(1e-2, 0.9))
             .weightInit("relu")
             .activation("relu")
             .dataType(self.dataType)
             .list())
        plan = [(64, 2), (128, 2), (256, 3), (512, 3), (512, 3)]
        for n_out, reps in plan:
            for _ in range(reps):
                b.layer(ConvolutionLayer(kernelSize=(3, 3), nOut=n_out,
                                         convolutionMode="same"))
            b.layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
        return (b.layer(DenseLayer(nOut=4096, dropOut=0.5))
                 .layer(DenseLayer(nOut=4096, dropOut=0.5))
                 .layer(OutputLayer(lossFunction="mcxent",
                                    nOut=self.numClasses,
                                    activation="softmax"))
                 .setInputType(InputType.convolutional(h, w, c))
                 .build())


class ResNet50(ZooModel):
    """≡ zoo.model.ResNet50 — bottleneck-v1 residual graph, built on the
    ComputationGraph DSL with ElementWiseVertex(Add) shortcuts. NHWC +
    identity shortcuts keep every conv MXU-shaped; bf16 via dataType."""

    def conf(self):
        h, w, c = self.inputShape
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Nesterovs(1e-1, 0.9))
             .weightInit("relu")
             .dataType(self.dataType)
             .l2(1e-4)
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def conv_bn(name, inp, n_out, k, s, act="relu", s2d=1):
            g.addLayer(f"{name}_conv",
                       ConvolutionLayer(kernelSize=k, stride=s, nOut=n_out,
                                        convolutionMode="same",
                                        hasBias=False, spaceToDepth=s2d,
                                        activation="identity"), inp)
            g.addLayer(f"{name}_bn",
                       BatchNormalization(activation=act), f"{name}_conv")
            return f"{name}_bn"

        def bottleneck(name, inp, filters, stride, downsample):
            f1, f2, f3 = filters
            x = conv_bn(f"{name}_a", inp, f1, (1, 1), stride)
            x = conv_bn(f"{name}_b", x, f2, (3, 3), (1, 1))
            x = conv_bn(f"{name}_c", x, f3, (1, 1), (1, 1), act="identity")
            if downsample:
                sc = conv_bn(f"{name}_sc", inp, f3, (1, 1), stride,
                             act="identity")
            else:
                sc = inp
            g.addVertex(f"{name}_add", ElementWiseVertex("add"), x, sc)
            g.addLayer(f"{name}_relu", ActivationLayer(activation="relu"),
                       f"{name}_add")
            return f"{name}_relu"

        # Stem in space-to-depth form: 3 input channels starve the MXU's
        # contraction lanes; folding 2x2 blocks gives an identical conv
        # over 12 channels (the standard TPU conv0 optimization).
        x = conv_bn("stem", "input", 64, (7, 7), (2, 2),
                    s2d=2 if h % 2 == 0 and w % 2 == 0 else 1)
        g.addLayer("stem_pool",
                   SubsamplingLayer(poolingType="max", kernelSize=(3, 3),
                                    stride=(2, 2), convolutionMode="same"), x)
        x = "stem_pool"
        stages = [
            ("res2", (64, 64, 256), 3, (1, 1)),
            ("res3", (128, 128, 512), 4, (2, 2)),
            ("res4", (256, 256, 1024), 6, (2, 2)),
            ("res5", (512, 512, 2048), 3, (2, 2)),
        ]
        for sname, filters, blocks, stride in stages:
            x = bottleneck(f"{sname}_0", x, filters, stride, True)
            for i in range(1, blocks):
                x = bottleneck(f"{sname}_{i}", x, filters, (1, 1), False)
        g.addLayer("avgpool", GlobalPoolingLayer(poolingType="avg"), x)
        g.addLayer("fc", OutputLayer(lossFunction="mcxent",
                                     nOut=self.numClasses,
                                     activation="softmax"), "avgpool")
        g.setOutputs("fc")
        return g.build()


class UNet(ZooModel):
    """≡ zoo.model.UNet — encoder/decoder with skip connections
    (MergeVertex concat), sigmoid pixel output."""

    DEFAULT_INPUT = (128, 128, 3)

    def conf(self):
        from deeplearning4j_tpu.nn.conf.graph_vertices import MergeVertex
        h, w, c = self.inputShape
        g = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Adam(1e-3))
             .weightInit("relu")
             .activation("relu")
             .dataType(self.dataType)
             .graphBuilder()
             .addInputs("input")
             .setInputTypes(InputType.convolutional(h, w, c)))

        def double_conv(name, inp, n_out):
            g.addLayer(f"{name}_c1", ConvolutionLayer(
                kernelSize=(3, 3), nOut=n_out, convolutionMode="same"), inp)
            g.addLayer(f"{name}_c2", ConvolutionLayer(
                kernelSize=(3, 3), nOut=n_out, convolutionMode="same"),
                f"{name}_c1")
            return f"{name}_c2"

        d1 = double_conv("down1", "input", 32)
        g.addLayer("pool1", SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)), d1)
        d2 = double_conv("down2", "pool1", 64)
        g.addLayer("pool2", SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)), d2)
        mid = double_conv("mid", "pool2", 128)
        g.addLayer("up2", Upsampling2D(size=2), mid)
        g.addVertex("cat2", MergeVertex(), "up2", d2)
        u2 = double_conv("dec2", "cat2", 64)
        g.addLayer("up1", Upsampling2D(size=2), u2)
        g.addVertex("cat1", MergeVertex(), "up1", d1)
        u1 = double_conv("dec1", "cat1", 32)
        g.addLayer("outconv", ConvolutionLayer(kernelSize=(1, 1), nOut=1,
                                               activation="identity",
                                               convolutionMode="same"), u1)
        g.addLayer("out", LossLayer(lossFunction="xent",
                                    activation="sigmoid"), "outconv")
        g.setOutputs("out")
        return g.build()


def _resolve_priors(boxes, defaults):
    """boxes: None → all default priors; int n → first n defaults;
    list of [w, h] → explicit priors."""
    if boxes is None:
        return defaults
    if isinstance(boxes, int):
        if not 1 <= boxes <= len(defaults):
            raise ValueError(
                f"boxes={boxes}: pass 1..{len(defaults)} to subset the "
                "default priors, or an explicit [[w, h], ...] list")
        return defaults[:boxes]
    return [list(map(float, b)) for b in boxes]


class TinyYOLO(ZooModel):
    """≡ zoo.model.TinyYOLO — Darknet-style backbone + Yolo2OutputLayer
    (anchor-box YOLOv2 loss) with the reference's VOC box priors."""

    DEFAULT_INPUT = (416, 416, 3)
    PRIORS = [[1.08, 1.19], [3.42, 4.41], [6.63, 11.38],
              [9.42, 5.11], [16.62, 10.52]]

    def __init__(self, numClasses=20, boxes=None, **kw):
        super().__init__(numClasses=numClasses, **kw)
        self.priors = _resolve_priors(boxes, self.PRIORS)
        self.boxes = len(self.priors)

    def conf(self):
        h, w, c = self.inputShape
        b = (NeuralNetConfiguration.Builder()
             .seed(self.seed)
             .updater(self.updater or Adam(1e-3))
             .weightInit("relu")
             .dataType(self.dataType)
             .list())
        n_out = 16
        for i in range(5):
            b.layer(ConvolutionLayer(kernelSize=(3, 3), nOut=n_out,
                                     convolutionMode="same", hasBias=False,
                                     activation="identity"))
            b.layer(BatchNormalization(activation="leakyrelu"))
            b.layer(SubsamplingLayer(kernelSize=(2, 2), stride=(2, 2)))
            n_out *= 2
        b.layer(ConvolutionLayer(kernelSize=(3, 3), nOut=512,
                                 convolutionMode="same", hasBias=False,
                                 activation="identity"))
        b.layer(BatchNormalization(activation="leakyrelu"))
        b.layer(ConvolutionLayer(kernelSize=(3, 3), nOut=1024,
                                 convolutionMode="same", hasBias=False,
                                 activation="identity"))
        b.layer(BatchNormalization(activation="leakyrelu"))
        head_out = self.boxes * (5 + self.numClasses)
        b.layer(ConvolutionLayer(kernelSize=(1, 1), nOut=head_out,
                                 convolutionMode="same",
                                 activation="identity"))
        from deeplearning4j_tpu.nn.conf.objdetect import Yolo2OutputLayer
        b.layer(Yolo2OutputLayer(boundingBoxes=self.priors))
        return (b.setInputType(InputType.convolutional(h, w, c)).build())


class TextGenerationLSTM(ZooModel):
    """≡ zoo.model.TextGenerationLSTM — char-RNN: stacked LSTMs +
    per-timestep softmax (the GravesLSTM char-modelling baseline config)."""

    def __init__(self, numClasses=77, lstmLayerSize=256, scanUnroll=1,
                 **kw):
        kw.setdefault("inputShape", (None, numClasses))
        super().__init__(numClasses=numClasses, **kw)
        self.lstmLayerSize = lstmLayerSize
        self.scanUnroll = int(scanUnroll)   # lax.scan unroll (TPU perf)

    DEFAULT_INPUT = (None, 77)

    def conf(self):
        return (NeuralNetConfiguration.Builder()
                .seed(self.seed)
                .updater(self.updater or Adam(1e-2))
                .weightInit("xavier")
                .dataType(self.dataType)
                .list()
                .layer(LSTM(nOut=self.lstmLayerSize, activation="tanh",
                            scanUnroll=self.scanUnroll))
                .layer(LSTM(nOut=self.lstmLayerSize, activation="tanh",
                            scanUnroll=self.scanUnroll))
                .layer(RnnOutputLayer(lossFunction="mcxent",
                                      nOut=self.numClasses,
                                      activation="softmax"))
                .setInputType(InputType.recurrent(self.numClasses))
                .build())

    def generationServer(self, net=None, **kw):
        """Serve this char-RNN autoregressively through the
        KV/carry-cache decode stack (generation/GenerationServer):
        incremental per-token decode with continuous-batching
        admission instead of a full re-forward per character.

            srv = TextGenerationLSTM(numClasses=77).generationServer(
                slots=8, cache_lengths=[512], method="top_k", top_k=5)
            srv.warmup()
            chars = srv.generate(seed_ids, max_new_tokens=200)

        Pass a trained `net` (from `.init()` + fit) to serve real
        weights; omitting it serves a fresh init (useful for shape
        warmup). Remaining kwargs go to GenerationServer."""
        from deeplearning4j_tpu.generation import GenerationServer
        return GenerationServer(net if net is not None else self.init(),
                                **kw)
