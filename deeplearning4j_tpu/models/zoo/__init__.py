from deeplearning4j_tpu.models.zoo.models import (AlexNet, LeNet, ResNet50,
                                                  SimpleCNN,
                                                  TextGenerationLSTM,
                                                  TinyYOLO, UNet, VGG16,
                                                  ZooModel)

__all__ = ["AlexNet", "LeNet", "ResNet50", "SimpleCNN",
           "TextGenerationLSTM", "TinyYOLO", "UNet", "VGG16", "ZooModel"]
