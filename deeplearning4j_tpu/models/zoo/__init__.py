from deeplearning4j_tpu.models.zoo.models import (AlexNet, LeNet, ResNet50,
                                                  SimpleCNN,
                                                  TextGenerationLSTM,
                                                  TinyYOLO, UNet, VGG16,
                                                  ZooModel)
from deeplearning4j_tpu.models.zoo.models2 import (Darknet19,
                                                   EfficientNet,
                                                   FaceNetNN4Small2,
                                                   InceptionResNetV1,
                                                   NASNet, SqueezeNet, VGG19,
                                                   Xception, YOLO2)

__all__ = ["AlexNet", "LeNet", "ResNet50", "SimpleCNN",
           "TextGenerationLSTM", "TinyYOLO", "UNet", "VGG16", "ZooModel",
           "Darknet19", "InceptionResNetV1", "SqueezeNet", "VGG19",
           "Xception", "YOLO2", "FaceNetNN4Small2", "NASNet",
           "EfficientNet"]
