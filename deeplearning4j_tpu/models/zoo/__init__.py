from deeplearning4j_tpu.models.zoo.models import (AlexNet, LeNet, ResNet50,
                                                  SimpleCNN,
                                                  TextGenerationLSTM,
                                                  TinyYOLO, UNet, VGG16,
                                                  ZooModel)
from deeplearning4j_tpu.models.zoo.models2 import (Darknet19,
                                                   InceptionResNetV1,
                                                   SqueezeNet, VGG19,
                                                   Xception)

__all__ = ["AlexNet", "LeNet", "ResNet50", "SimpleCNN",
           "TextGenerationLSTM", "TinyYOLO", "UNet", "VGG16", "ZooModel",
           "Darknet19", "InceptionResNetV1", "SqueezeNet", "VGG19",
           "Xception"]
