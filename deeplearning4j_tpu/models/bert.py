"""BERT-style transformer encoder — the framework's flagship model.

Parity target: the reference's "SameDiff BERT-base fine-tune (TF-import →
SameDiff graph)" baseline config (BASELINE.json). Rather than importing a
TF graph, the encoder is built natively as a pure-functional JAX model and
compiled whole into one XLA executable — the same end-state the reference
reaches after import+SameDiff compilation, minus the import machinery
(keras_import handles config-level import).

TPU-first design:
- bf16 activations / fp32 master params (`dtype` arg)
- fused QKV projection (one MXU matmul), big FFN matmuls
- tensor parallel: column-parallel QKV/FFN-up, row-parallel proj/FFN-down,
  annotated via PartitionSpec trees (sharding_rules) — XLA inserts the
  psum on the row-parallel outputs over `tp`
- sequence parallel: ring attention over `sp` (parallel/ring_attention.py)
- expert parallel: optional MoE FFN layers, experts sharded over `ep`
- remat (`jax.checkpoint`) per encoder layer to trade FLOPs for HBM
"""
from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from deeplearning4j_tpu.parallel.ring_attention import (blockwise_attention,
                                                        dense_attention,
                                                        make_ring_attention)


@dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_layers: int = 12
    num_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    num_labels: int = 2           # fine-tune classifier head
    dropout: float = 0.1
    layer_norm_eps: float = 1e-12
    dtype: str = "float32"        # compute dtype ("bfloat16" on TPU)
    remat: bool = False
    # MoE (expert parallel): layers listed here use a mixture-of-experts FFN
    moe_layers: tuple = ()
    num_experts: int = 8

    @property
    def head_dim(self):
        return self.hidden_size // self.num_heads

    @property
    def compute_dtype(self):
        return jnp.bfloat16 if self.dtype == "bfloat16" else jnp.float32


def _init(key, shape, scale=0.02):
    return (scale * jax.random.normal(key, shape)).astype(jnp.float32)


def init_bert_params(cfg: BertConfig, key=None):
    key = key if key is not None else jax.random.PRNGKey(0)
    keys = iter(jax.random.split(key, 16 + 16 * cfg.num_layers))
    H, I, V = cfg.hidden_size, cfg.intermediate_size, cfg.vocab_size
    p = {
        "embeddings": {
            "word": _init(next(keys), (V, H)),
            "position": _init(next(keys), (cfg.max_position_embeddings, H)),
            "token_type": _init(next(keys), (cfg.type_vocab_size, H)),
            "ln_scale": jnp.ones((H,), jnp.float32),
            "ln_bias": jnp.zeros((H,), jnp.float32),
        },
        "layers": [],
        "pooler": {"W": _init(next(keys), (H, H)),
                   "b": jnp.zeros((H,), jnp.float32)},
        "classifier": {"W": _init(next(keys), (H, cfg.num_labels)),
                       "b": jnp.zeros((cfg.num_labels,), jnp.float32)},
        "mlm_head": {"W": _init(next(keys), (H, H)),
                     "b": jnp.zeros((H,), jnp.float32),
                     "ln_scale": jnp.ones((H,), jnp.float32),
                     "ln_bias": jnp.zeros((H,), jnp.float32),
                     "out_bias": jnp.zeros((V,), jnp.float32)},
    }
    for li in range(cfg.num_layers):
        layer = {
            "qkv_W": _init(next(keys), (H, 3 * H)),
            "qkv_b": jnp.zeros((3 * H,), jnp.float32),
            "proj_W": _init(next(keys), (H, H)),
            "proj_b": jnp.zeros((H,), jnp.float32),
            "ln1_scale": jnp.ones((H,), jnp.float32),
            "ln1_bias": jnp.zeros((H,), jnp.float32),
            "ln2_scale": jnp.ones((H,), jnp.float32),
            "ln2_bias": jnp.zeros((H,), jnp.float32),
        }
        if li in cfg.moe_layers:
            E = cfg.num_experts
            layer["moe"] = {
                "router_W": _init(next(keys), (H, E)),
                "up_W": _init(next(keys), (E, H, I)),
                "up_b": jnp.zeros((E, I), jnp.float32),
                "down_W": _init(next(keys), (E, I, H)),
                "down_b": jnp.zeros((E, H), jnp.float32),
            }
        else:
            layer["ffn"] = {
                "up_W": _init(next(keys), (H, I)),
                "up_b": jnp.zeros((I,), jnp.float32),
                "down_W": _init(next(keys), (I, H)),
                "down_b": jnp.zeros((H,), jnp.float32),
            }
        p["layers"].append(layer)
    return p


def _layer_norm(x, scale, bias, eps):
    mu = jnp.mean(x.astype(jnp.float32), axis=-1, keepdims=True)
    var = jnp.var(x.astype(jnp.float32), axis=-1, keepdims=True)
    y = (x.astype(jnp.float32) - mu) * jax.lax.rsqrt(var + eps)
    return (y * scale + bias).astype(x.dtype)


def _dropout(x, rate, train, rng):
    if not train or rate <= 0.0 or rng is None:
        return x
    keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
    return jnp.where(keep, x / (1.0 - rate), 0.0).astype(x.dtype)


def _attention(cfg, layer, x, attn_mask, train, rng, attn_impl,
               causal=False):
    B, T, H = x.shape
    nh, hd = cfg.num_heads, cfg.head_dim
    dt = x.dtype
    qkv = x @ layer["qkv_W"].astype(dt) + layer["qkv_b"].astype(dt)
    q, k, v = jnp.split(qkv, 3, axis=-1)

    def heads(t):
        return t.reshape(B, T, nh, hd).transpose(0, 2, 1, 3)

    q, k, v = heads(q), heads(k), heads(v)
    if attn_impl == "auto":
        # TPU default: the Pallas flash kernel (fwd + bwd, O(T) HBM) —
        # padded batches route the (B, T) mask into the kernel's masked
        # path (per-example key/query validity in VMEM)
        attn_impl = "flash" if jax.default_backend() == "tpu" else "dense"
    if causal and callable(attn_impl):
        raise ValueError(
            "causal attention is only wired through the built-in "
            "'dense'/'flash' impls; custom attn_impl callables do not "
            "declare a causal parameter")
    if causal and attn_impl == "blockwise":
        raise ValueError("'blockwise' attn_impl has no causal path; "
                         "use flash or dense for causal encoding")
    if callable(attn_impl):
        if attn_mask is None:
            ctx = attn_impl(q, k, v)
        else:
            # a padded batch must never silently attend to padding: the
            # custom impl has to DECLARE the mask — a 4th positional
            # slot or an explicit 'mask'/'attn_mask'/'kv_mask' keyword
            # parameter, passed by whichever convention the signature
            # supports. Bare *args/**kwargs catch-alls are rejected: a
            # kwargs-swallowing impl would pass an arity bind() and drop
            # the mask silently (ADVICE r5). Non-introspectable
            # signatures are refused too — wrap them to declare the mask.
            from deeplearning4j_tpu.util.introspect import \
                explicit_mask_param
            conv = explicit_mask_param(attn_impl, positional_slot=4)
            if conv is None:
                raise ValueError(
                    "attn_impl callable does not explicitly declare a "
                    "mask parameter (bare *args/**kwargs or a "
                    "non-introspectable signature does not count) but "
                    "the batch carries attention_mask — use a masked "
                    "impl (flash/dense) or an attn_impl(q, k, v, mask)")
            if conv[0] == "positional":
                ctx = attn_impl(q, k, v, attn_mask)
            else:
                ctx = attn_impl(q, k, v, **{conv[1]: attn_mask})
    elif attn_impl in ("blockwise", "flash"):
        if attn_impl == "flash":
            from deeplearning4j_tpu.kernels import flash_attention
            ctx = flash_attention(q, k, v, causal=causal, mask=attn_mask)
        else:
            if attn_mask is not None:
                raise ValueError("'blockwise' attn_impl has no padding-mask "
                                 "path; use flash or dense for masked batches")
            ctx = blockwise_attention(q, k, v, block_size=max(128, T // 4))
    elif attn_impl == "dense":
        mask = None
        if attn_mask is not None:
            mask = attn_mask[:, None, None, :] > 0
        ctx = dense_attention(q, k, v, causal=causal, mask=mask)
    else:
        raise ValueError(f"unknown attn_impl {attn_impl!r}; expected "
                         "'dense', 'blockwise', 'flash', or a callable")
    ctx = ctx.transpose(0, 2, 1, 3).reshape(B, T, H)
    out = ctx @ layer["proj_W"].astype(dt) + layer["proj_b"].astype(dt)
    return _dropout(out, cfg.dropout, train, rng)


def _ffn(cfg, layer, x, train, rng):
    dt = x.dtype
    f = layer["ffn"]
    h = jax.nn.gelu(x @ f["up_W"].astype(dt) + f["up_b"].astype(dt))
    out = h @ f["down_W"].astype(dt) + f["down_b"].astype(dt)
    return _dropout(out, cfg.dropout, train, rng)


def _moe_ffn(cfg, layer, x, train, rng):
    """Top-1 switch MoE. Dense dispatch via one-hot einsum — jit-friendly
    static shapes; experts shard over `ep` through sharding_rules on the
    leading expert dim."""
    dt = x.dtype
    m = layer["moe"]
    B, T, H = x.shape
    logits = x @ m["router_W"].astype(dt)                 # (B,T,E)
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    top = jnp.argmax(probs, axis=-1)                      # (B,T)
    gate = jnp.max(probs, axis=-1).astype(dt)             # (B,T)
    onehot = jax.nn.one_hot(top, cfg.num_experts, dtype=dt)  # (B,T,E)
    # per-expert FFN on all tokens, gathered by one-hot (dense-dispatch)
    up = jnp.einsum("bth,ehi->beti", x, m["up_W"].astype(dt)) \
        + m["up_b"].astype(dt)[None, :, None, :]
    act = jax.nn.gelu(up)
    down = jnp.einsum("beti,eih->beth", act, m["down_W"].astype(dt)) \
        + m["down_b"].astype(dt)[None, :, None, :]
    out = jnp.einsum("beth,bte->bth", down, onehot) * gate[..., None]
    return _dropout(out, cfg.dropout, train, rng)


def _encoder_layer(cfg, layer, x, attn_mask, train, rng, attn_impl,
                   causal=False):
    # the incremental-decode path (generation/decode.py BertDecoder)
    # mirrors this block's exact arithmetic against its K/V cache —
    # changing norm placement / bias handling here must keep
    # tests/test_generation.py::test_bert_kv_decode_matches_full_forward
    # green (it pins decode == this forward to <= 1e-5)
    r1 = r2 = None
    if rng is not None:
        rng, r1, r2 = jax.random.split(rng, 3)
    a = _attention(cfg, layer, x, attn_mask, train, r1, attn_impl, causal)
    x = _layer_norm(x + a, layer["ln1_scale"], layer["ln1_bias"],
                    cfg.layer_norm_eps)
    if "moe" in layer:
        f = _moe_ffn(cfg, layer, x, train, r2)
    else:
        f = _ffn(cfg, layer, x, train, r2)
    return _layer_norm(x + f, layer["ln2_scale"], layer["ln2_bias"],
                       cfg.layer_norm_eps)


def bert_encode(cfg, params, input_ids, token_type_ids=None, attn_mask=None,
                train=False, rng=None, attn_impl="auto", causal=False):
    """(B, T) int ids -> (B, T, H) hidden states.

    `causal=True` masks attention to past-and-present positions only —
    the full-sequence reference for the autoregressive decode path
    (generation/): KV-cache decode logits must match this forward."""
    dt = cfg.compute_dtype
    B, T = input_ids.shape
    emb = params["embeddings"]
    x = jnp.take(emb["word"], input_ids, axis=0) \
        + emb["position"][None, :T, :]
    if token_type_ids is not None:
        x = x + jnp.take(emb["token_type"], token_type_ids, axis=0)
    x = _layer_norm(x.astype(dt), emb["ln_scale"], emb["ln_bias"],
                    cfg.layer_norm_eps)
    r = None
    if rng is not None:
        rng, r = jax.random.split(rng)
    x = _dropout(x, cfg.dropout, train, r)
    block = _encoder_layer
    if cfg.remat:
        block = jax.checkpoint(_encoder_layer,
                               static_argnums=(0, 4, 6, 7))
    for li, layer in enumerate(params["layers"]):
        lr = None
        if rng is not None:
            lr = jax.random.fold_in(rng, li)
        x = block(cfg, layer, x, attn_mask, train, lr, attn_impl, causal)
    return x


def bert_pooled(cfg, params, hidden):
    cls = hidden[:, 0, :]
    pool = jnp.tanh(cls @ params["pooler"]["W"].astype(cls.dtype)
                    + params["pooler"]["b"].astype(cls.dtype))
    return pool


def bert_classify(cfg, params, input_ids, token_type_ids=None, attn_mask=None,
                  train=False, rng=None, attn_impl="auto"):
    """Fine-tune head: (B,T) -> (B, num_labels) logits (≡ the reference's
    BERT fine-tune SameDiff graph output)."""
    hidden = bert_encode(cfg, params, input_ids, token_type_ids, attn_mask,
                         train, rng, attn_impl)
    pooled = bert_pooled(cfg, params, hidden)
    c = params["classifier"]
    return (pooled @ c["W"].astype(pooled.dtype) + c["b"].astype(pooled.dtype)
            ).astype(jnp.float32)


def bert_mlm_logits(cfg, params, hidden):
    """Masked-LM head with tied word embeddings."""
    m = params["mlm_head"]
    dt = hidden.dtype
    h = jax.nn.gelu(hidden @ m["W"].astype(dt) + m["b"].astype(dt))
    h = _layer_norm(h, m["ln_scale"], m["ln_bias"], 1e-12)
    logits = h @ params["embeddings"]["word"].T.astype(dt) \
        + m["out_bias"].astype(dt)
    return logits.astype(jnp.float32)


def classification_loss(cfg, params, batch, train=True, rng=None,
                        attn_impl="auto"):
    logits = bert_classify(cfg, params, batch["input_ids"],
                           batch.get("token_type_ids"),
                           batch.get("attention_mask"), train, rng, attn_impl)
    labels = batch["labels"]
    logp = jax.nn.log_softmax(logits, axis=-1)
    onehot = jax.nn.one_hot(labels, cfg.num_labels)
    return -jnp.mean(jnp.sum(onehot * logp, axis=-1))


# -- sharding rules (scaling-book style annotate-and-let-XLA) ------------
def sharding_rules(cfg: BertConfig, mesh, dp="dp", tp="tp", ep=None):
    """PartitionSpec tree matching init_bert_params structure. Column-
    parallel: last dim over tp. Row-parallel: first dim over tp (XLA adds
    the psum). Embedding vocab dim over tp. MoE expert dim over ep."""
    H = None  # readability

    def ns(*spec):
        return NamedSharding(mesh, P(*spec))

    rep = ns()
    rules = {
        "embeddings": {"word": ns(tp, None), "position": rep,
                       "token_type": rep, "ln_scale": rep, "ln_bias": rep},
        "pooler": {"W": rep, "b": rep},
        "classifier": {"W": rep, "b": rep},
        "mlm_head": {"W": rep, "b": rep, "ln_scale": rep, "ln_bias": rep,
                     "out_bias": rep},
        "layers": [],
    }
    for li in range(cfg.num_layers):
        layer = {
            "qkv_W": ns(None, tp), "qkv_b": ns(tp),
            "proj_W": ns(tp, None), "proj_b": rep,
            "ln1_scale": rep, "ln1_bias": rep,
            "ln2_scale": rep, "ln2_bias": rep,
        }
        if li in cfg.moe_layers:
            e = ep or tp
            layer["moe"] = {"router_W": rep,
                            "up_W": ns(e, None, None),
                            "up_b": ns(e, None),
                            "down_W": ns(e, None, None),
                            "down_b": ns(e, None)}
        else:
            layer["ffn"] = {"up_W": ns(None, tp), "up_b": ns(tp),
                            "down_W": ns(tp, None), "down_b": rep}
        rules["layers"].append(layer)
    return rules


def bert_base(**overrides):
    return BertConfig(**overrides)


def bert_tiny(**overrides):
    """Test/dryrun-sized config."""
    d = dict(vocab_size=128, hidden_size=32, num_layers=2, num_heads=4,
             intermediate_size=64, max_position_embeddings=64,
             type_vocab_size=2, num_labels=3)
    d.update(overrides)
    return BertConfig(**d)
