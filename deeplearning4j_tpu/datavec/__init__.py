from deeplearning4j_tpu.datavec.image_records import (
    ColorConversionTransform, CropImageTransform, FlipImageTransform,
    ImageRecordDataSetIterator, ImageRecordReader, ParentPathLabelGenerator,
    PipelineImageTransform, RandomCropTransform, ResizeImageTransform,
    RotateImageTransform)
from deeplearning4j_tpu.datavec.sequence import (
    AnalyzeLocal, CollectionSequenceRecordReader, CSVSequenceRecordReader,
    DataAnalysis, Join, SequenceRecordReader,
    SequenceRecordReaderDataSetIterator)
from deeplearning4j_tpu.datavec.records import (CollectionRecordReader,
                                                CSVRecordReader,
                                                LineRecordReader,
                                                RecordReader,
                                                RecordReaderDataSetIterator,
                                                Schema, TransformProcess)

__all__ = [
    "AnalyzeLocal", "CollectionSequenceRecordReader",
    "CSVSequenceRecordReader", "DataAnalysis", "Join",
    "SequenceRecordReader", "SequenceRecordReaderDataSetIterator","CollectionRecordReader", "CSVRecordReader", "LineRecordReader",
           "RecordReader", "RecordReaderDataSetIterator", "Schema",
           "TransformProcess", "FlipImageTransform", "ImageRecordDataSetIterator",
           "ImageRecordReader", "ParentPathLabelGenerator",
           "PipelineImageTransform", "ResizeImageTransform",
           "ColorConversionTransform", "CropImageTransform",
           "RandomCropTransform", "RotateImageTransform"]
