from deeplearning4j_tpu.datavec.image_records import (
    FlipImageTransform, ImageRecordDataSetIterator, ImageRecordReader,
    ParentPathLabelGenerator, PipelineImageTransform, ResizeImageTransform)
from deeplearning4j_tpu.datavec.records import (CollectionRecordReader,
                                                CSVRecordReader,
                                                LineRecordReader,
                                                RecordReader,
                                                RecordReaderDataSetIterator,
                                                Schema, TransformProcess)

__all__ = ["CollectionRecordReader", "CSVRecordReader", "LineRecordReader",
           "RecordReader", "RecordReaderDataSetIterator", "Schema",
           "TransformProcess", "FlipImageTransform", "ImageRecordDataSetIterator",
           "ImageRecordReader", "ParentPathLabelGenerator",
           "PipelineImageTransform", "ResizeImageTransform"]
