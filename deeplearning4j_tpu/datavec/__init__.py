from deeplearning4j_tpu.datavec.records import (CollectionRecordReader,
                                                CSVRecordReader,
                                                LineRecordReader,
                                                RecordReader,
                                                RecordReaderDataSetIterator,
                                                Schema, TransformProcess)

__all__ = ["CollectionRecordReader", "CSVRecordReader", "LineRecordReader",
           "RecordReader", "RecordReaderDataSetIterator", "Schema",
           "TransformProcess"]
