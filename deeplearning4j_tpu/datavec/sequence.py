"""Sequence DataVec breadth (round-3 VERDICT item 10: ≡ datavec-api ::
records.reader.impl.csv.CSVSequenceRecordReader, deeplearning4j ::
SequenceRecordReaderDataSetIterator, datavec transform.join.Join,
AnalyzeLocal column analysis).

Host-side ETL; ragged sequences pad to the batch maximum with (B, T)
masks — exactly the mask convention the recurrent layers consume."""
from __future__ import annotations

import csv
import io
import os

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator
from deeplearning4j_tpu.datavec.records import RecordReader, Schema


class SequenceRecordReader(RecordReader):
    """A reader whose next() yields one SEQUENCE: a list of timestep rows."""

    def nextSequence(self):
        return self.next()


class CSVSequenceRecordReader(SequenceRecordReader):
    """≡ CSVSequenceRecordReader(skipNumLines, delimiter) — ONE SEQUENCE PER
    FILE: each CSV file (or text blob) is a whole time-series, one timestep
    per line. initialize() takes a list of paths/texts (or a single one)."""

    def __init__(self, skipNumLines=0, delimiter=","):
        self.skip = int(skipNumLines)
        self.delimiter = delimiter
        self._seqs = []
        self._i = 0

    def _parse(self, path_or_text):
        if isinstance(path_or_text, str) and os.path.exists(path_or_text):
            with open(path_or_text, newline="") as f:
                rows = list(csv.reader(f, delimiter=self.delimiter))
        else:
            rows = list(csv.reader(io.StringIO(path_or_text),
                                   delimiter=self.delimiter))
        return [[c.strip() for c in r] for r in rows[self.skip:] if r]

    def initialize(self, sources):
        if isinstance(sources, str):
            sources = [sources]
        self._seqs = [self._parse(s) for s in sources]
        self._i = 0
        return self

    def hasNext(self):
        return self._i < len(self._seqs)

    def next(self):
        s = self._seqs[self._i]
        self._i += 1
        return [list(r) for r in s]

    def reset(self):
        self._i = 0


class CollectionSequenceRecordReader(SequenceRecordReader):
    """In-memory sequences: list of list-of-timestep-rows
    (≡ CollectionSequenceRecordReader)."""

    def __init__(self, sequences):
        self._seqs = [[list(r) for r in s] for s in sequences]
        self._i = 0

    def initialize(self, split=None):
        self.reset()
        return self

    def hasNext(self):
        return self._i < len(self._seqs)

    def next(self):
        s = self._seqs[self._i]
        self._i += 1
        return [list(r) for r in s]

    def reset(self):
        self._i = 0


class SequenceRecordReaderDataSetIterator(DataSetIterator):
    """≡ deeplearning4j SequenceRecordReaderDataSetIterator.

    Two modes:
    - two readers (features, labels): aligned sequences, same lengths;
    - one reader + labelIndex: the label column is split out per timestep.

    Ragged sequences pad to the batch max length; featuresMask/labelsMask
    carry the per-example valid lengths. Classification labels one-hot to
    (B, T, numClasses); regression keeps (B, T, 1). alignmentMode
    'equal_length' (default) or 'align_end' (labels at sequence ends,
    e.g. seq-to-one)."""

    def __init__(self, featureReader, labelReaderOrBatch=None, batch_size=None,
                 numClasses=None, regression=False, labelIndex=None,
                 alignmentMode="equal_length"):
        if isinstance(labelReaderOrBatch, int):
            label_reader, batch_size = None, labelReaderOrBatch
        else:
            label_reader = labelReaderOrBatch
        super().__init__(batch_size or 1)
        self.numClasses = numClasses
        self.regression = regression
        self.alignmentMode = alignmentMode
        fseqs = [s for s in featureReader]
        if label_reader is not None:
            lseqs = [s for s in label_reader]
            if len(lseqs) != len(fseqs):
                raise ValueError(
                    f"feature reader has {len(fseqs)} sequences, label "
                    f"reader {len(lseqs)}")
            self._feats = [np.asarray(s, np.float32) for s in fseqs]
            self._labels = [np.asarray(s, np.float32) for s in lseqs]
        elif labelIndex is not None:
            self._feats, self._labels = [], []
            for s in fseqs:
                arr = np.asarray(s, np.float32)
                self._feats.append(np.delete(arr, labelIndex, axis=1))
                self._labels.append(arr[:, labelIndex:labelIndex + 1])
        else:
            self._feats = [np.asarray(s, np.float32) for s in fseqs]
            self._labels = [np.zeros((len(s), 0), np.float32) for s in fseqs]

    def numExamples(self):
        return len(self._feats)

    def inputColumns(self):
        return int(self._feats[0].shape[-1]) if self._feats else 0

    def totalOutcomes(self):
        if self.regression or self.numClasses is None:
            return int(self._labels[0].shape[-1]) if self._labels else 0
        return int(self.numClasses)

    def _onehot(self, lab):
        """(T, 1) class ids -> (T, C)."""
        t = lab.shape[0]
        out = np.zeros((t, int(self.numClasses)), np.float32)
        out[np.arange(t), lab[:, 0].astype(np.int64)] = 1.0
        return out

    def next(self, num=None):
        self._check_has_next()
        n = num or self._batch
        feats = self._feats[self._cursor:self._cursor + n]
        labs = self._labels[self._cursor:self._cursor + n]
        self._cursor += len(feats)
        if not self.regression and self.numClasses is not None:
            labs = [self._onehot(l) for l in labs]
        tmax = max(f.shape[0] for f in feats)
        ltmax = max(l.shape[0] for l in labs)
        b = len(feats)
        fdim, ldim = feats[0].shape[1], labs[0].shape[1]
        f_arr = np.zeros((b, tmax, fdim), np.float32)
        l_arr = np.zeros((b, ltmax, ldim), np.float32)
        f_mask = np.zeros((b, tmax), np.float32)
        l_mask = np.zeros((b, ltmax), np.float32)
        for i, (f, l) in enumerate(zip(feats, labs)):
            f_arr[i, :f.shape[0]] = f
            f_mask[i, :f.shape[0]] = 1.0
            if self.alignmentMode == "align_end":
                # labels packed at the END of the padded window (seq-to-one
                # alignment: the label scores against the last valid step)
                l_arr[i, ltmax - l.shape[0]:] = l
                l_mask[i, ltmax - l.shape[0]:] = 1.0
            else:
                l_arr[i, :l.shape[0]] = l
                l_mask[i, :l.shape[0]] = 1.0
        ds = DataSet(f_arr, l_arr)
        ds.featuresMask = f_mask
        ds.labelsMask = l_mask
        return self._maybe_preprocess(ds)


# -- joins ----------------------------------------------------------------
class Join:
    """≡ datavec transform.join.Join — key-equality join of two record
    collections. Builder mirror: Join.Builder(type).setJoinColumns(...)
    .setSchemas(left, right).build(); execute(left_rows, right_rows)."""

    INNER, LEFT_OUTER, RIGHT_OUTER, FULL_OUTER = (
        "inner", "leftouter", "rightouter", "fullouter")

    def __init__(self, join_type, key_columns, left_schema, right_schema):
        self.join_type = str(join_type).lower().replace("_", "")
        self.keys = list(key_columns)
        self.left_schema = left_schema
        self.right_schema = right_schema

    class Builder:
        def __init__(self, joinType="inner"):
            self._type = joinType
            self._keys = []
            self._ls = self._rs = None

        def setJoinColumns(self, *names):
            self._keys = list(names)
            return self

        def setSchemas(self, left, right):
            self._ls, self._rs = left, right
            return self

        def build(self):
            if not self._keys or self._ls is None or self._rs is None:
                raise ValueError("Join needs join columns and both schemas")
            return Join(self._type, self._keys, self._ls, self._rs)

    def outSchema(self):
        right_extra = [c for c in self.right_schema.columns
                       if c[0] not in self.keys]
        return Schema(list(self.left_schema.columns) + right_extra)

    def execute(self, left_rows, right_rows):
        lnames = self.left_schema.names()
        rnames = self.right_schema.names()
        lkey = [lnames.index(k) for k in self.keys]
        rkey = [rnames.index(k) for k in self.keys]
        r_extra_idx = [i for i, n in enumerate(rnames) if n not in self.keys]
        index = {}
        for r in right_rows:
            index.setdefault(tuple(r[i] for i in rkey), []).append(r)
        out, matched_right = [], set()
        n_right_extra = len(r_extra_idx)
        for l in left_rows:
            key = tuple(l[i] for i in lkey)
            matches = index.get(key, [])
            if matches:
                matched_right.add(key)
                for r in matches:
                    out.append(list(l) + [r[i] for i in r_extra_idx])
            elif self.join_type in ("leftouter", "fullouter"):
                out.append(list(l) + [None] * n_right_extra)
        if self.join_type in ("rightouter", "fullouter"):
            lnone = [None] * len(lnames)
            for key, rows in index.items():
                if key in matched_right:
                    continue
                for r in rows:
                    row = list(lnone)
                    for ki, i in enumerate(lkey):
                        row[i] = r[rkey[ki]]
                    out.append(row + [r[i] for i in r_extra_idx])
        return out


# -- analysis -------------------------------------------------------------
class ColumnAnalysis:
    def __init__(self, **kw):
        self.__dict__.update(kw)

    def __repr__(self):
        body = ", ".join(f"{k}={v}" for k, v in self.__dict__.items())
        return f"ColumnAnalysis({body})"


class DataAnalysis:
    def __init__(self, schema, columns):
        self.schema = schema
        self._cols = columns  # name -> ColumnAnalysis

    def getColumnAnalysis(self, name):
        return self._cols[name]

    def __str__(self):
        lines = [f"{'Column':<18}{'Type':<12}Analysis"]
        for n, _, _ in self.schema.columns:
            lines.append(f"{n:<18}{self.schema.kind(n):<12}{self._cols[n]}")
        return "\n".join(lines)


class AnalyzeLocal:
    """≡ datavec-local :: AnalyzeLocal.analyze(schema, reader) — single-pass
    per-column summary statistics on the host."""

    @staticmethod
    def analyze(schema, reader_or_rows):
        rows = [r for r in reader_or_rows]
        cols = {}
        for idx, (name, kind, meta) in enumerate(schema.columns):
            values = [r[idx] for r in rows]
            missing = sum(1 for v in values
                          if v is None or (isinstance(v, str) and not v))
            present = [v for v in values
                       if not (v is None or (isinstance(v, str) and not v))]
            if kind in ("double", "integer"):
                arr = np.asarray([float(v) for v in present], np.float64)
                cols[name] = ColumnAnalysis(
                    count=len(present), countMissing=missing,
                    min=float(arr.min()) if arr.size else None,
                    max=float(arr.max()) if arr.size else None,
                    mean=float(arr.mean()) if arr.size else None,
                    sampleStdev=float(arr.std(ddof=1)) if arr.size > 1
                    else 0.0,
                    countZero=int(np.sum(arr == 0.0)),
                    countNegative=int(np.sum(arr < 0)),
                    countPositive=int(np.sum(arr > 0)))
            elif kind == "categorical":
                counts = {}
                for v in present:
                    counts[v] = counts.get(v, 0) + 1
                cols[name] = ColumnAnalysis(
                    count=len(present), countMissing=missing,
                    uniqueCount=len(counts), categoryCounts=counts)
            else:  # string
                lens = [len(str(v)) for v in present]
                cols[name] = ColumnAnalysis(
                    count=len(present), countMissing=missing,
                    uniqueCount=len(set(map(str, present))),
                    minLength=min(lens) if lens else 0,
                    maxLength=max(lens) if lens else 0,
                    meanLength=float(np.mean(lens)) if lens else 0.0)
        return DataAnalysis(schema, cols)
