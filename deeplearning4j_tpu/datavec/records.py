"""DataVec equivalents (≡ datavec-api :: records.reader.RecordReader,
CSVRecordReader, transform.TransformProcess, and the
RecordReaderDataSetIterator bridge in deeplearning4j-datavec-iterators).

Schema-driven columnar ETL on the host; the accelerator never sees this
code (same division of labor as the reference)."""
from __future__ import annotations

import csv
import io
import os

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.datasets.iterators import DataSetIterator


# -- readers -------------------------------------------------------------
class RecordReader:
    def initialize(self, split):
        raise NotImplementedError

    def hasNext(self):
        raise NotImplementedError

    def next(self):
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()


class CollectionRecordReader(RecordReader):
    """In-memory list of records (≡ CollectionRecordReader)."""

    def __init__(self, records):
        self._records = [list(r) for r in records]
        self._i = 0

    def initialize(self, split=None):
        self.reset()

    def hasNext(self):
        return self._i < len(self._records)

    def next(self):
        r = self._records[self._i]
        self._i += 1
        return list(r)

    def reset(self):
        self._i = 0


class CSVRecordReader(RecordReader):
    """≡ datavec CSVRecordReader(skipLines, delimiter).

    All-numeric files additionally parse through the native C++ runtime in
    one pass (runtime/native :: dl4j_csv_parse — the hot path the
    reference keeps in datavec's native loaders); `numeric_matrix()` then
    hands the whole float32 table to RecordReaderDataSetIterator without
    the per-field Python float() loop. Record-level semantics (lists of
    stripped strings from `next()`) are unchanged."""

    def __init__(self, skipNumLines=0, delimiter=","):
        self.skip = int(skipNumLines)
        self.delimiter = delimiter
        self._text = ""
        self._rows = None    # parsed lazily: the bulk path never needs them
        self._i = 0
        self._matrix = None

    def initialize(self, path_or_text):
        if isinstance(path_or_text, str) and os.path.exists(path_or_text):
            with open(path_or_text, newline="") as f:
                text = f.read()
        else:
            text = path_or_text
        self._text = text
        self._rows = None
        self._i = 0
        self._matrix = None
        # native bulk parse is only trusted when it provably matches the
        # record-level view: no interior blank lines after the skip (the
        # native pass drops them; csv.reader yields [] rows), every field
        # numeric (a single NaN falls back to the Python path)
        body = text.split("\n")[self.skip:]
        while body and not body[-1].strip():
            body.pop()
        # every row must have the SAME column count: the native parser
        # truncates long rows / NaN-pads short ones, but the Python path
        # raises on ragged tables — ragged input must take the strict path
        widths = {l.count(self.delimiter) for l in body}
        if body and len(widths) == 1 and all(l.strip() for l in body):
            try:
                from deeplearning4j_tpu.runtime.native_lib import \
                    csv_to_floats
                import numpy as _np
                m = csv_to_floats(text.encode(), self.delimiter, self.skip)
                if (m is not None and m.size and m.shape[0] == len(body)
                        and not _np.isnan(m).any()):
                    self._matrix = m
            except Exception:
                self._matrix = None
        return self

    def _ensure_rows(self):
        if self._rows is None:
            self._rows = list(csv.reader(
                io.StringIO(self._text),
                delimiter=self.delimiter))[self.skip:]
        return self._rows

    def numeric_matrix(self):
        """float32 (rows, cols) for all-numeric files, else None. Only
        valid on an unconsumed reader — after any next() the bulk view
        would disagree with the remaining records."""
        return self._matrix if self._i == 0 else None

    def hasNext(self):
        return self._i < len(self._ensure_rows())

    def next(self):
        r = self._ensure_rows()[self._i]
        self._i += 1
        return [c.strip() for c in r]

    def reset(self):
        self._i = 0


class LineRecordReader(RecordReader):
    def __init__(self):
        self._lines = []
        self._i = 0

    def initialize(self, path_or_text):
        if isinstance(path_or_text, str) and os.path.exists(path_or_text):
            with open(path_or_text) as f:
                self._lines = [l.rstrip("\n") for l in f]
        else:
            self._lines = path_or_text.splitlines()
        self._i = 0
        return self

    def hasNext(self):
        return self._i < len(self._lines)

    def next(self):
        l = self._lines[self._i]
        self._i += 1
        return [l]

    def reset(self):
        self._i = 0


# -- schema & transforms -------------------------------------------------
class Schema:
    """≡ datavec transform.schema.Schema.Builder."""

    def __init__(self, columns=None):
        self.columns = list(columns or [])  # [(name, kind, meta)]

    class Builder:
        def __init__(self):
            self._cols = []

        def addColumnDouble(self, name):
            self._cols.append((name, "double", None))
            return self

        def addColumnsDouble(self, *names):
            for n in names:
                self.addColumnDouble(n)
            return self

        def addColumnInteger(self, name):
            self._cols.append((name, "integer", None))
            return self

        def addColumnString(self, name):
            self._cols.append((name, "string", None))
            return self

        def addColumnCategorical(self, name, *categories):
            if len(categories) == 1 and isinstance(categories[0], (list, tuple)):
                categories = categories[0]
            self._cols.append((name, "categorical", list(categories)))
            return self

        def build(self):
            return Schema(self._cols)

    def names(self):
        return [c[0] for c in self.columns]

    def indexOf(self, name):
        return self.names().index(name)

    def kind(self, name):
        return self.columns[self.indexOf(name)][1]

    def meta(self, name):
        return self.columns[self.indexOf(name)][2]


class TransformProcess:
    """≡ datavec transform.TransformProcess.Builder — an ordered pipeline of
    schema-aware column transforms executed on host records."""

    def __init__(self, schema, steps):
        self.initial_schema = schema
        self.steps = steps

    class Builder:
        def __init__(self, schema):
            self._schema = schema
            self._steps = []

        def removeColumns(self, *names):
            self._steps.append(("remove", names))
            return self

        def removeAllColumnsExceptFor(self, *names):
            self._steps.append(("keep", names))
            return self

        def filter(self, predicate):
            """predicate(record_dict) -> True to DROP (≡ ConditionFilter)."""
            self._steps.append(("filter", predicate))
            return self

        def categoricalToInteger(self, *names):
            self._steps.append(("cat2int", names))
            return self

        def categoricalToOneHot(self, *names):
            self._steps.append(("cat2onehot", names))
            return self

        def integerToCategorical(self, name, categories):
            self._steps.append(("int2cat", (name, list(categories))))
            return self

        def stringToCategorical(self, name, categories):
            self._steps.append(("str2cat", (name, list(categories))))
            return self

        def doubleMathOp(self, name, op, value):
            self._steps.append(("math", (name, op, float(value))))
            return self

        def normalize(self, name, kind, *stats):
            self._steps.append(("normalize", (name, kind, stats)))
            return self

        def renameColumn(self, old, new):
            self._steps.append(("rename", (old, new)))
            return self

        def build(self):
            return TransformProcess(self._schema, self._steps)

    # -- execution -------------------------------------------------------
    def execute(self, records):
        """records: list of lists (strings or numbers) matching the initial
        schema. Returns (new_records, final_schema)."""
        schema = self.initial_schema
        rows = [list(r) for r in records]
        for kind, arg in self.steps:
            rows, schema = self._apply(kind, arg, rows, schema)
        return rows, schema

    @staticmethod
    def _apply(kind, arg, rows, schema):
        names = schema.names()
        if kind == "remove":
            keep_idx = [i for i, n in enumerate(names) if n not in arg]
            new_cols = [schema.columns[i] for i in keep_idx]
            return ([[r[i] for i in keep_idx] for r in rows],
                    Schema(new_cols))
        if kind == "keep":
            keep_idx = [i for i, n in enumerate(names) if n in arg]
            new_cols = [schema.columns[i] for i in keep_idx]
            return ([[r[i] for i in keep_idx] for r in rows],
                    Schema(new_cols))
        if kind == "filter":
            pred = arg
            kept = [r for r in rows
                    if not pred(dict(zip(names, r)))]
            return kept, schema
        if kind == "rename":
            old, new = arg
            cols = [(new if n == old else n, k, m)
                    for n, k, m in schema.columns]
            return rows, Schema(cols)
        if kind == "cat2int":
            out_cols = list(schema.columns)
            for name in arg:
                i = schema.indexOf(name)
                cats = schema.meta(name)
                for r in rows:
                    r[i] = cats.index(r[i])
                out_cols[i] = (name, "integer", None)
            return rows, Schema(out_cols)
        if kind == "cat2onehot":
            for name in arg:
                i = schema.indexOf(name)
                cats = schema.meta(name)
                new_cols = (schema.columns[:i]
                            + [(f"{name}[{c}]", "double", None) for c in cats]
                            + schema.columns[i + 1:])
                new_rows = []
                for r in rows:
                    onehot = [1.0 if r[i] == c else 0.0 for c in cats]
                    new_rows.append(r[:i] + onehot + r[i + 1:])
                rows, schema = new_rows, Schema(new_cols)
            return rows, schema
        if kind == "int2cat":
            name, cats = arg
            i = schema.indexOf(name)
            for r in rows:
                r[i] = cats[int(r[i])]
            cols = list(schema.columns)
            cols[i] = (name, "categorical", cats)
            return rows, Schema(cols)
        if kind == "str2cat":
            name, cats = arg
            i = schema.indexOf(name)
            cols = list(schema.columns)
            cols[i] = (name, "categorical", cats)
            return rows, Schema(cols)
        if kind == "math":
            name, op, val = arg
            i = schema.indexOf(name)
            import operator
            ops = {"add": operator.add, "subtract": operator.sub,
                   "multiply": operator.mul, "divide": operator.truediv}
            f = ops[op.lower()]
            for r in rows:
                r[i] = f(float(r[i]), val)
            return rows, schema
        if kind == "normalize":
            name, norm_kind, stats = arg
            i = schema.indexOf(name)
            vals = np.array([float(r[i]) for r in rows])
            if norm_kind == "minmax":
                lo, hi = (stats if stats else (vals.min(), vals.max()))
                rng = max(hi - lo, 1e-12)
                for r in rows:
                    r[i] = (float(r[i]) - lo) / rng
            elif norm_kind == "standardize":
                mu, sd = (stats if stats else (vals.mean(), vals.std() or 1.0))
                for r in rows:
                    r[i] = (float(r[i]) - mu) / sd
            return rows, schema
        raise ValueError(f"Unknown transform {kind}")


class RecordReaderDataSetIterator(DataSetIterator):
    """≡ deeplearning4j RecordReaderDataSetIterator(reader, batch,
    labelIndex, numClasses) — bridges DataVec records to DataSets."""

    def __init__(self, reader, batch_size, labelIndex=None, numClasses=None,
                 regression=False):
        super().__init__(batch_size)
        mat = getattr(reader, "numeric_matrix", lambda: None)()
        if mat is not None and mat.size:
            # native bulk path: one C++ pass + numpy slicing, no per-field
            # Python float() loop
            if labelIndex is None:
                feats, labels = mat, []
            else:
                feats = np.delete(mat, labelIndex, axis=1)
                labels = mat[:, labelIndex].tolist()
            self.features = np.ascontiguousarray(feats, np.float32)
        else:
            rows = [r for r in reader]
            feats, labels = [], []
            for r in rows:
                vals = [float(v) for v in r]
                if labelIndex is None:
                    feats.append(vals)
                else:
                    feats.append(vals[:labelIndex] + vals[labelIndex + 1:])
                    labels.append(vals[labelIndex])
            self.features = np.asarray(feats, np.float32)
        if labelIndex is None:
            self.labels = np.zeros((len(feats), 0), np.float32)
        elif regression:
            self.labels = np.asarray(labels, np.float32)[:, None]
        else:
            lab = np.asarray(labels, np.int64)
            self.labels = np.zeros((len(lab), numClasses), np.float32)
            self.labels[np.arange(len(lab)), lab] = 1.0

    def numExamples(self):
        return len(self.features)

    def totalOutcomes(self):
        return int(self.labels.shape[-1])

    def inputColumns(self):
        return int(self.features.shape[-1])

    def next(self, num=None):
        n = num or self._batch
        f = self.features[self._cursor:self._cursor + n]
        l = self.labels[self._cursor:self._cursor + n]
        self._cursor += len(f)
        return self._maybe_preprocess(DataSet(f, l))
