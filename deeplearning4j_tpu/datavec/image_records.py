"""Image record reading (≡ datavec-data-image ::
org.datavec.image.recordreader.ImageRecordReader +
loader.NativeImageLoader + transform.ImageTransform family +
api.io.labels.ParentPathLabelGenerator).

PIL decodes (present in this environment — the reference used JavaCV);
output is NHWC float32 batches, the layout every conv in this framework
consumes directly (no NCHW permute step like the reference's loader).
"""
from __future__ import annotations

import os

import numpy as np


class ParentPathLabelGenerator:
    """≡ ParentPathLabelGenerator — label = parent directory name."""

    def getLabelForPath(self, path):
        return os.path.basename(os.path.dirname(os.path.abspath(path)))


class ImageTransform:
    def transform(self, img_array, rng):
        raise NotImplementedError


class ResizeImageTransform(ImageTransform):
    def __init__(self, newHeight, newWidth):
        self.h, self.w = int(newHeight), int(newWidth)

    def transform(self, img, rng):
        from PIL import Image
        squeeze = img.ndim == 3 and img.shape[2] == 1
        src = img[:, :, 0] if squeeze else img   # PIL: gray is 2-D
        pil = Image.fromarray(src.astype(np.uint8))
        out = np.asarray(pil.resize((self.w, self.h)), np.float32)
        return out[:, :, None] if squeeze else out


class FlipImageTransform(ImageTransform):
    """Random horizontal flip (p=0.5)."""

    def transform(self, img, rng):
        return img[:, ::-1] if rng.random() < 0.5 else img


class CropImageTransform(ImageTransform):
    """Random crop by up to `crop` pixels per edge, then pad back."""

    def __init__(self, crop):
        self.crop = int(crop)

    def transform(self, img, rng):
        c = self.crop
        if c <= 0:
            return img
        top = rng.integers(0, c + 1)
        left = rng.integers(0, c + 1)
        h, w = img.shape[:2]
        out = img[top:h - (c - top) or h, left:w - (c - left) or w]
        pad = [(top, c - top), (left, c - left)] + \
            [(0, 0)] * (img.ndim - 2)
        return np.pad(out, pad, mode="edge")


class RotateImageTransform(ImageTransform):
    """≡ transform.RotateImageTransform(angle): rotate by a uniform random
    angle in [-angle, +angle] degrees about the image center (bilinear,
    same output size, edge value 0 — the reference's warpAffine
    default)."""

    def __init__(self, angle):
        self.angle = float(angle)

    def transform(self, img, rng):
        from PIL import Image
        deg = float(rng.uniform(-self.angle, self.angle))
        squeeze = img.ndim == 3 and img.shape[2] == 1
        src = img[:, :, 0] if squeeze else img
        pil = Image.fromarray(src.astype(np.uint8))
        out = np.asarray(pil.rotate(deg, resample=Image.BILINEAR,
                                    expand=False, fillcolor=0), np.float32)
        return out[:, :, None] if squeeze else out


class RandomCropTransform(ImageTransform):
    """≡ transform.RandomCropTransform(height, width): crop a random
    (height, width) window — the output is SMALLER than the input (the
    augmentation form of cropping, unlike CropImageTransform's
    crop-and-pad)."""

    def __init__(self, height, width):
        self.h, self.w = int(height), int(width)

    def transform(self, img, rng):
        h, w = img.shape[:2]
        if self.h > h or self.w > w:
            raise ValueError(
                f"RandomCropTransform({self.h}, {self.w}): crop larger "
                f"than the {h}x{w} input")
        top = int(rng.integers(0, h - self.h + 1))
        left = int(rng.integers(0, w - self.w + 1))
        return img[top:top + self.h, left:left + self.w]


class ColorConversionTransform(ImageTransform):
    """≡ transform.ColorConversionTransform: the common conversions by
    name instead of OpenCV integer codes — 'RGB2GRAY' (1 channel),
    'BGR2RGB'/'RGB2BGR' (channel reversal), 'RGB2HSV'/'HSV2RGB'."""

    _ITU_R = np.array([0.299, 0.587, 0.114], np.float32)  # BT.601 luma

    def __init__(self, conversion="RGB2GRAY"):
        conv = str(conversion).upper()
        if conv not in ("RGB2GRAY", "BGR2RGB", "RGB2BGR", "RGB2HSV",
                        "HSV2RGB"):
            raise ValueError(f"unsupported conversion {conversion!r}")
        self.conversion = conv

    def transform(self, img, rng):
        if img.ndim == 2:
            img = img[:, :, None]
        if self.conversion == "RGB2GRAY":
            if img.shape[2] == 1:
                return img
            if img.shape[2] < 3:
                raise ValueError(
                    f"RGB2GRAY needs 1 or >=3 channels, got "
                    f"{img.shape[2]}")
            return (img[:, :, :3] @ self._ITU_R)[:, :, None]
        if img.shape[2] != 3:
            # exactly 3: silently reversing RGBA would move alpha into a
            # color plane, and PIL's HSV path would die cryptically
            raise ValueError(
                f"{self.conversion} needs exactly 3 channels, got "
                f"{img.shape[2]} (slice [:, :, :3] first)")
        if self.conversion in ("BGR2RGB", "RGB2BGR"):
            return img[:, :, ::-1]
        from PIL import Image
        mode_in, mode_out = (("RGB", "HSV")
                             if self.conversion == "RGB2HSV"
                             else ("HSV", "RGB"))
        pil = Image.fromarray(img.astype(np.uint8), mode=mode_in)
        return np.asarray(pil.convert(mode_out), np.float32)


class PipelineImageTransform(ImageTransform):
    """≡ transform.PipelineImageTransform: a chain of transforms, each
    optionally gated by a probability — pass plain transforms or
    (transform, probability) pairs; shuffle=True applies them in a random
    order per image (the reference's shuffle flag)."""

    def __init__(self, *transforms, shuffle=False):
        self.transforms = [t if isinstance(t, tuple) else (t, 1.0)
                           for t in transforms]
        self.shuffle = bool(shuffle)

    def transform(self, img, rng):
        order = list(range(len(self.transforms)))
        if self.shuffle:
            rng.shuffle(order)
        for i in order:
            t, prob = self.transforms[i]
            if prob >= 1.0 or rng.random() < prob:
                img = t.transform(img, rng)
        return img


_IMG_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".gif", ".ppm"}


class ImageRecordReader:
    """≡ ImageRecordReader(height, width, channels, labelGenerator).

    initialize() walks a directory tree; next() yields
    [image (H,W,C) float32 0-255, label index].
    """

    def __init__(self, height, width, channels=3, labelGenerator=None,
                 imageTransform=None, seed=0, nativeLoader=False):
        self.height, self.width = int(height), int(width)
        self.channels = int(channels)
        self.labelGenerator = labelGenerator or ParentPathLabelGenerator()
        self.imageTransform = imageTransform
        self.nativeLoader = bool(nativeLoader)   # C++ bilinear resize
        self._rng = np.random.default_rng(seed)
        self._paths = []
        self._labels = []
        self._label_names = []
        self._idx = 0

    def initialize(self, path_or_split, shuffle=False):
        root = getattr(path_or_split, "rootDir", path_or_split)
        paths = []
        for dirpath, _, files in sorted(os.walk(str(root))):
            for fn in sorted(files):
                if os.path.splitext(fn)[1].lower() in _IMG_EXTS:
                    paths.append(os.path.join(dirpath, fn))
        if not paths:
            raise FileNotFoundError(f"no images under {root}")
        names = sorted({self.labelGenerator.getLabelForPath(p)
                        for p in paths})
        self._label_names = names
        lookup = {n: i for i, n in enumerate(names)}
        if shuffle:
            self._rng.shuffle(paths)
        self._paths = paths
        self._labels = [lookup[self.labelGenerator.getLabelForPath(p)]
                        for p in paths]
        self._idx = 0
        return self

    def getLabels(self):
        return list(self._label_names)

    def numExamples(self):
        return len(self._paths)

    def _load(self, path):
        if self.nativeLoader:
            arr = NativeImageLoader(self.height, self.width,
                                    self.channels).asMatrix(path)[0]
        else:
            from PIL import Image
            decoded = _pil_decode(path, self.channels)
            img = Image.fromarray(decoded).resize(
                (self.width, self.height))
            arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.imageTransform is not None:
            arr = self.imageTransform.transform(arr, self._rng)
            if arr.shape[:2] != (self.height, self.width):
                arr = ResizeImageTransform(
                    self.height, self.width).transform(arr, self._rng)
        return arr

    def hasNext(self):
        return self._idx < len(self._paths)

    def next(self):
        img = self._load(self._paths[self._idx])
        label = self._labels[self._idx]
        self._idx += 1
        return [img, label]

    def reset(self):
        self._idx = 0

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()


class ImageRecordDataSetIterator:
    """Bridge to DataSetIterator (≡ RecordReaderDataSetIterator over an
    ImageRecordReader): batches NHWC images + one-hot labels."""

    def __init__(self, reader, batch_size, num_classes=None,
                 preprocessor=None):
        self.reader = reader
        self.batch_size = int(batch_size)
        self.num_classes = num_classes or len(reader.getLabels())
        self.preprocessor = preprocessor

    def __iter__(self):
        self.reader.reset()
        while self.reader.hasNext():
            imgs, labels = [], []
            while self.reader.hasNext() and len(imgs) < self.batch_size:
                img, lab = self.reader.next()
                imgs.append(img)
                labels.append(lab)
            from deeplearning4j_tpu.datasets.dataset import DataSet
            x = np.stack(imgs)
            y = np.eye(self.num_classes, dtype=np.float32)[labels]
            ds = DataSet(x, y)
            if self.preprocessor is not None:
                self.preprocessor.preProcess(ds)
            yield ds

    def reset(self):
        self.reader.reset()


def _pil_decode(path, channels):
    """ONE file-decode path (PIL open + mode convert) shared by
    ImageRecordReader and NativeImageLoader — decode fixes (EXIF,
    palettes, ...) must never diverge between the two."""
    from PIL import Image
    img = Image.open(path)
    return np.asarray(img.convert("RGB" if channels == 3 else "L"))


class NativeImageLoader:
    """≡ datavec-data-image :: loader.NativeImageLoader — decode + resize
    to (height, width, channels) float32 via the NATIVE runtime (C++
    bilinear in runtime/native; strict-parity-gated numpy oracle when the
    toolchain is absent — identical output either way). The reference is
    NCHW via JavaCV; this stack is NHWC-native, and asMatrix returns
    (1, H, W, C) ready for the conv layers."""

    def __init__(self, height, width, channels=3):
        self.height, self.width = int(height), int(width)
        self.channels = int(channels)

    def _decode(self, src):
        if isinstance(src, np.ndarray):
            arr = src
            if np.issubdtype(arr.dtype, np.floating):
                # [0, 1]-normalized floats scale back to [0, 255];
                # [0, 255] floats round — NEVER a silent truncating cast.
                # 1% slack each side absorbs bilinear/bicubic over/under-
                # shoot, scaled to the detected range; the final clip maps
                # undershoot to 0. Real [-1,1] images still fail loudly,
                # as does the ambiguous (1.01, 2.0) band (a scaled-up
                # normalized image would read near-black).
                mx = float(arr.max(initial=0.0))
                lo_tol = 1e-2 * (1.0 if mx <= 1.0 + 1e-2 else 255.0)
                if float(arr.min(initial=0.0)) < -lo_tol:
                    raise ValueError(
                        "NativeImageLoader: float image with negative "
                        "values is ambiguous ([-1,1]-normalized?) — "
                        "rescale to [0,1] or [0,255] first")
                if 1.0 + 1e-2 < mx < 2.0:
                    raise ValueError(
                        "NativeImageLoader: float image with max "
                        f"{mx:.4f} is ambiguous (overshot [0,1] or a "
                        "dim [0,255] image?) — rescale explicitly")
                scale = 255.0 if mx <= 1.0 + 1e-2 else 1.0
                arr = np.rint(
                    np.clip(arr.astype(np.float32) * scale, 0.0, 255.0))
        else:
            arr = _pil_decode(src, self.channels)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        have = arr.shape[-1]
        if have != self.channels:
            if self.channels == 1 and have >= 3:
                # luminance, same weights as the reference's grayscale
                arr = np.rint(
                    arr[..., :3].astype(np.float32)
                    @ np.array([0.299, 0.587, 0.114], np.float32)
                )[..., None]
            elif self.channels == 1 and have == 2:
                arr = arr[..., :1]           # LA: drop alpha
            elif self.channels == 3 and have == 1:
                arr = np.repeat(arr, 3, axis=-1)
            elif self.channels == 3 and have > 3:
                arr = arr[..., :3]           # RGBA: drop alpha
            else:
                raise ValueError(
                    f"NativeImageLoader: cannot map {have} "
                    f"channels to {self.channels}")
        return np.clip(arr, 0, 255).astype(np.uint8)

    def asMatrix(self, src):
        """path | (H, W[, C]) array → (1, height, width, channels) f32."""
        from deeplearning4j_tpu.runtime.native_lib import resize_bilinear_u8
        u8 = self._decode(src)
        out = resize_bilinear_u8(u8, self.height, self.width)
        return out[None]

    def asImageMatrix(self, src):
        return self.asMatrix(src)
