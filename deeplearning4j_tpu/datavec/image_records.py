"""Image record reading (≡ datavec-data-image ::
org.datavec.image.recordreader.ImageRecordReader +
loader.NativeImageLoader + transform.ImageTransform family +
api.io.labels.ParentPathLabelGenerator).

PIL decodes (present in this environment — the reference used JavaCV);
output is NHWC float32 batches, the layout every conv in this framework
consumes directly (no NCHW permute step like the reference's loader).
"""
from __future__ import annotations

import os

import numpy as np


class ParentPathLabelGenerator:
    """≡ ParentPathLabelGenerator — label = parent directory name."""

    def getLabelForPath(self, path):
        return os.path.basename(os.path.dirname(os.path.abspath(path)))


class ImageTransform:
    def transform(self, img_array, rng):
        raise NotImplementedError


class ResizeImageTransform(ImageTransform):
    def __init__(self, newHeight, newWidth):
        self.h, self.w = int(newHeight), int(newWidth)

    def transform(self, img, rng):
        from PIL import Image
        pil = Image.fromarray(img.astype(np.uint8))
        return np.asarray(pil.resize((self.w, self.h)), np.float32)


class FlipImageTransform(ImageTransform):
    """Random horizontal flip (p=0.5)."""

    def transform(self, img, rng):
        return img[:, ::-1] if rng.random() < 0.5 else img


class CropImageTransform(ImageTransform):
    """Random crop by up to `crop` pixels per edge, then pad back."""

    def __init__(self, crop):
        self.crop = int(crop)

    def transform(self, img, rng):
        c = self.crop
        if c <= 0:
            return img
        top = rng.integers(0, c + 1)
        left = rng.integers(0, c + 1)
        h, w = img.shape[:2]
        out = img[top:h - (c - top) or h, left:w - (c - left) or w]
        pad = [(top, c - top), (left, c - left)] + \
            [(0, 0)] * (img.ndim - 2)
        return np.pad(out, pad, mode="edge")


class PipelineImageTransform(ImageTransform):
    def __init__(self, *transforms):
        self.transforms = list(transforms)

    def transform(self, img, rng):
        for t in self.transforms:
            img = t.transform(img, rng)
        return img


_IMG_EXTS = {".jpg", ".jpeg", ".png", ".bmp", ".gif", ".ppm"}


class ImageRecordReader:
    """≡ ImageRecordReader(height, width, channels, labelGenerator).

    initialize() walks a directory tree; next() yields
    [image (H,W,C) float32 0-255, label index].
    """

    def __init__(self, height, width, channels=3, labelGenerator=None,
                 imageTransform=None, seed=0):
        self.height, self.width = int(height), int(width)
        self.channels = int(channels)
        self.labelGenerator = labelGenerator or ParentPathLabelGenerator()
        self.imageTransform = imageTransform
        self._rng = np.random.default_rng(seed)
        self._paths = []
        self._labels = []
        self._label_names = []
        self._idx = 0

    def initialize(self, path_or_split, shuffle=False):
        root = getattr(path_or_split, "rootDir", path_or_split)
        paths = []
        for dirpath, _, files in sorted(os.walk(str(root))):
            for fn in sorted(files):
                if os.path.splitext(fn)[1].lower() in _IMG_EXTS:
                    paths.append(os.path.join(dirpath, fn))
        if not paths:
            raise FileNotFoundError(f"no images under {root}")
        names = sorted({self.labelGenerator.getLabelForPath(p)
                        for p in paths})
        self._label_names = names
        lookup = {n: i for i, n in enumerate(names)}
        if shuffle:
            self._rng.shuffle(paths)
        self._paths = paths
        self._labels = [lookup[self.labelGenerator.getLabelForPath(p)]
                        for p in paths]
        self._idx = 0
        return self

    def getLabels(self):
        return list(self._label_names)

    def numExamples(self):
        return len(self._paths)

    def _load(self, path):
        from PIL import Image
        img = Image.open(path)
        img = img.convert("RGB" if self.channels == 3 else "L")
        img = img.resize((self.width, self.height))
        arr = np.asarray(img, np.float32)
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.imageTransform is not None:
            arr = self.imageTransform.transform(arr, self._rng)
            if arr.shape[:2] != (self.height, self.width):
                arr = ResizeImageTransform(
                    self.height, self.width).transform(arr, self._rng)
        return arr

    def hasNext(self):
        return self._idx < len(self._paths)

    def next(self):
        img = self._load(self._paths[self._idx])
        label = self._labels[self._idx]
        self._idx += 1
        return [img, label]

    def reset(self):
        self._idx = 0

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()


class ImageRecordDataSetIterator:
    """Bridge to DataSetIterator (≡ RecordReaderDataSetIterator over an
    ImageRecordReader): batches NHWC images + one-hot labels."""

    def __init__(self, reader, batch_size, num_classes=None,
                 preprocessor=None):
        self.reader = reader
        self.batch_size = int(batch_size)
        self.num_classes = num_classes or len(reader.getLabels())
        self.preprocessor = preprocessor

    def __iter__(self):
        self.reader.reset()
        while self.reader.hasNext():
            imgs, labels = [], []
            while self.reader.hasNext() and len(imgs) < self.batch_size:
                img, lab = self.reader.next()
                imgs.append(img)
                labels.append(lab)
            from deeplearning4j_tpu.datasets.dataset import DataSet
            x = np.stack(imgs)
            y = np.eye(self.num_classes, dtype=np.float32)[labels]
            ds = DataSet(x, y)
            if self.preprocessor is not None:
                self.preprocessor.preProcess(ds)
            yield ds

    def reset(self):
        self.reader.reset()
