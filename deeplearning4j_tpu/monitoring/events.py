"""Ops event journal: one bounded, ordered record of state transitions.

Every significant state transition across the resilience, generation,
parallel, and monitoring subsystems emits one typed event

    {seq, monotonic_ts, wall_ts, subsystem, kind, severity, attrs,
     correlation_id}

into a process-wide ring bounded by ``DL4J_EVENT_RING`` (default 512).
The journal is the *causal* record the per-subsystem counters can't
give: counters say a rollback happened, the journal says the rollback
followed a retry that followed a divergence check at step 41, and that
the whole episode resolved in 1.8 s.

Incident correlation rides on top of the ring: an error-severity event
opens an **incident** that absorbs causally-adjacent events — same
correlation id, or within ``DL4J_INCIDENT_WINDOW`` seconds of the
incident's last event — until a resolving event closes it (resolution =
that event's kind) or a quiet period of ``DL4J_INCIDENT_QUIET`` seconds
passes (resolution = None). Each incident yields
``{trigger, actions[], resolution, duration_s}`` — the machine-readable
drain/replace/autoscale signal ROADMAP item 1's fleet router consumes.

Zero-cost when monitoring is disabled: ``emit`` is a no-op behind one
branch, every producer hook is one guarded branch
(``if _mon.enabled(): _events.emit(...)``), and
``scripts/check_fastpath.py`` enforces both (guard presence in the
producer modules, no device syncs reachable from the emit path).
``scripts/check_event_coverage.py`` asserts every kind declared below
is exercised by at least one test.

Served by the dashboard as ``GET /events?last=N`` and ``GET
/incidents``; ``write_bundle`` assembles the seven-section post-mortem
JSON (event tail, incidents, metrics registry, step-recorder tail,
request ring, health/SLO state, open spans) invoked from crash dumps,
stall/peer reports, and ``POST /debug/bundle``.
"""
from __future__ import annotations

import json
import os
import threading
import time
from collections import deque

from deeplearning4j_tpu.monitoring.state import STATE

# --------------------------------------------------------------------------
# Event-kind catalog.  Module-level UPPER = "dotted.kind" constants, AST-
# parseable by scripts/check_event_coverage.py exactly like the fault-site
# constants in resilience/faults.py.  Severity: "info" < "warn" < "error".
# An error-severity event opens an incident (unless absorbed by one already
# open); a kind listed in _RESOLVING closes the incident that absorbs it.

#: guardian loss-spike ladder requested an lr-scaled retry
GUARDIAN_RETRY = "guardian.retry"
#: guardian requested (or completed, attrs["phase"]) a checkpoint rollback
GUARDIAN_ROLLBACK = "guardian.rollback"
#: guardian exhausted its ladder — training marked unhealthy
GUARDIAN_DIVERGED = "guardian.diverged"
#: guardian saw enough clean checks to restore lr_scale to 1.0
GUARDIAN_RECOVERED = "guardian.recovered"
#: watchdog tripped: no heartbeat within the stall timeout
WATCHDOG_STALL = "watchdog.stall"
#: heartbeats resumed after a stall
WATCHDOG_RECOVERED = "watchdog.recovered"
#: an armed FaultPlan fired at a chaos site
FAULT_INJECTED = "fault.injected"
#: serving pressure ladder climbed a rung (attrs: level, action)
PRESSURE_ESCALATED = "pressure.escalated"
#: serving pressure ladder stepped down (resolves at level 0)
PRESSURE_RELIEVED = "pressure.relieved"
#: admission refused a request (attrs: status = shed|timeout|rejected)
SERVER_REFUSED = "server.refused"
#: queued requests shed under memory pressure (attrs: shed)
SERVER_SHED = "server.shed"
#: decode cache grew to a larger rung (attrs: to_rung)
CACHE_GROWN = "cache.grown"
#: decode cache rung capacity shrunk under pressure (attrs: cap)
CACHE_SHRUNK = "cache.shrunk"
#: KV page pool could not cover an admission/growth
PAGES_EXHAUSTED = "pages.exhausted"
#: cold KV pages evicted to relieve pressure (attrs: evicted)
PAGES_EVICTED = "pages.evicted"
#: a device fault interrupted serving; crash-replay starting
SERVER_DISRUPTED = "server.disrupted"
#: one in-flight request re-admitted bit-identically after a crash
SERVER_REPLAY = "server.replay"
#: supervised restart rebuilt the server after a failed recovery
SERVER_RESTARTED = "server.restarted"
#: serving recovered — replay or supervised restart succeeded
SERVER_RECOVERED = "server.recovered"
#: restart budget exhausted: server permanently dead (attrs: reason)
SERVER_DEAD = "server.dead"
#: membership committed a new epoch (attrs: epoch, joins, leaves)
MEMBERSHIP_EPOCH = "membership.epoch"
#: this host was admitted into the cluster at an epoch boundary
MEMBERSHIP_JOINED = "membership.joined"
#: this host announced an orderly leave
MEMBERSHIP_LEAVE = "membership.leave"
#: a lost host was replaced and the mesh re-formed (attrs: lost)
MEMBERSHIP_REPLACED = "membership.replaced"
#: a peer host was declared lost (heartbeat/barrier failure)
PEER_LOST = "peer.lost"
#: peers disagreed on coordinated state (step desync)
PEER_DESYNC = "peer.desync"
#: an SLO objective's burn rate breached (attrs: objective, exemplars)
SLO_BREACH = "slo.breach"
#: a breached SLO objective recovered
SLO_RECOVER = "slo.recover"
#: the fleet router stopped admitting to a replica (attrs: replica,
#: reason = dead|burn_rate) — the replica-lost incident trigger
REPLICA_UNHEALTHY = "replica.unhealthy"
#: the supervisor drained a lost replica before replacement (attrs:
#: replica, open_requests re-routed through failover)
REPLICA_DRAINED = "replica.drained"
#: a fresh replica took the lost one's roster slot (warm spin-up from
#: the shared FunctionStore: attrs carry compiled/from_disk counts) —
#: resolves the replica-lost incident
REPLICA_REPLACED = "replica.replaced"
#: one in-flight request re-routed to a healthy replica (journal
#: replay, delivered prefix suppressed; attrs: from, to, delivered)
REQUEST_FAILOVER = "request.failover"

#: kind -> default severity.  Every kind the journal accepts is here.
KIND_SEVERITY = {
    GUARDIAN_RETRY: "error",
    GUARDIAN_ROLLBACK: "error",
    GUARDIAN_DIVERGED: "error",
    GUARDIAN_RECOVERED: "info",
    WATCHDOG_STALL: "error",
    WATCHDOG_RECOVERED: "info",
    FAULT_INJECTED: "info",
    PRESSURE_ESCALATED: "error",
    PRESSURE_RELIEVED: "info",
    SERVER_REFUSED: "warn",
    SERVER_SHED: "warn",
    CACHE_GROWN: "info",
    CACHE_SHRUNK: "warn",
    PAGES_EXHAUSTED: "warn",
    PAGES_EVICTED: "info",
    SERVER_DISRUPTED: "error",
    SERVER_REPLAY: "info",
    SERVER_RESTARTED: "warn",
    SERVER_RECOVERED: "info",
    SERVER_DEAD: "error",
    MEMBERSHIP_EPOCH: "info",
    MEMBERSHIP_JOINED: "info",
    MEMBERSHIP_LEAVE: "info",
    MEMBERSHIP_REPLACED: "warn",
    PEER_LOST: "error",
    PEER_DESYNC: "error",
    SLO_BREACH: "error",
    SLO_RECOVER: "info",
    REPLICA_UNHEALTHY: "error",
    REPLICA_DRAINED: "warn",
    REPLICA_REPLACED: "info",
    REQUEST_FAILOVER: "warn",
}

#: kinds that close the incident absorbing them (resolution = kind).
_RESOLVING = frozenset({
    GUARDIAN_RECOVERED,
    WATCHDOG_RECOVERED,
    SERVER_RECOVERED,
    SLO_RECOVER,
    REPLICA_REPLACED,
})

_DEFAULT_RING = 512
_DEFAULT_WINDOW_S = 5.0
_DEFAULT_QUIET_S = 10.0
_CLOSED_KEEP = 64
_ACTIONS_KEEP = 64


def _env_float(name, default):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        return default


class Incident:
    """One correlated episode: trigger -> actions[] -> resolution."""

    __slots__ = ("id", "trigger", "actions", "dropped_actions",
                 "resolution", "opened_ts", "last_ts", "closed_ts",
                 "correlation_id", "state")

    def __init__(self, incident_id, trigger):
        self.id = incident_id
        self.trigger = trigger
        self.actions = deque(maxlen=_ACTIONS_KEEP)
        self.dropped_actions = 0
        self.resolution = None
        self.opened_ts = trigger["monotonic_ts"]
        self.last_ts = trigger["monotonic_ts"]
        self.closed_ts = None
        self.correlation_id = trigger["correlation_id"]
        self.state = "open"

    def absorb(self, event):
        if len(self.actions) == self.actions.maxlen:
            self.dropped_actions += 1
        self.actions.append(event)
        self.last_ts = event["monotonic_ts"]
        if self.correlation_id is None:
            self.correlation_id = event["correlation_id"]

    def close(self, ts, resolution):
        self.state = "resolved"
        self.closed_ts = ts
        self.resolution = resolution

    def snapshot(self):
        events = [self.trigger] + list(self.actions)
        end = self.closed_ts if self.closed_ts is not None else self.last_ts
        links = {"trace": "/trace"}
        requests = []
        for e in events:
            rid = (e.get("attrs") or {}).get("request")
            if rid and rid not in requests:
                requests.append(rid)
        if requests:
            links["requests"] = ["/requests/%s" % r for r in requests]
        return {
            "id": self.id,
            "state": self.state,
            "trigger": self.trigger,
            "actions": list(self.actions),
            "dropped_actions": self.dropped_actions,
            "resolution": self.resolution,
            "correlation_id": self.correlation_id,
            "opened_ts": self.opened_ts,
            "closed_ts": self.closed_ts,
            "duration_s": round(end - self.opened_ts, 6),
            "kinds": [e["kind"] for e in events],
            "links": links,
        }


class EventJournal:
    """Bounded ordered ring of ops events + the incident correlator.

    Appends are rare (state transitions, not per-token work) so a small
    lock keeps seq/ring/incident state consistent across threads; the
    disabled path never reaches here (module-level ``emit`` returns
    before touching the journal).
    """

    def __init__(self, capacity=None, window_s=None, quiet_s=None,
                 clock=time.monotonic):
        if capacity is None:
            try:
                capacity = int(os.environ.get(
                    "DL4J_EVENT_RING", str(_DEFAULT_RING)))
            except ValueError:
                capacity = _DEFAULT_RING
        self.capacity = max(1, capacity)
        self.window_s = (window_s if window_s is not None
                         else _env_float("DL4J_INCIDENT_WINDOW",
                                         _DEFAULT_WINDOW_S))
        self.quiet_s = (quiet_s if quiet_s is not None
                        else _env_float("DL4J_INCIDENT_QUIET",
                                        _DEFAULT_QUIET_S))
        self._clock = clock
        self._lock = threading.Lock()
        self._ring = deque(maxlen=self.capacity)
        self._seq = 0
        self.dropped = 0
        self._open = []
        self._closed = deque(maxlen=_CLOSED_KEEP)
        self._incident_seq = 0
        self.resolved_total = 0

    # -- emission ----------------------------------------------------------

    def emit(self, subsystem, kind, attrs=None, correlation_id=None,
             severity=None, resolves=None):
        if severity is None:
            severity = KIND_SEVERITY.get(kind, "info")
        if resolves is None:
            resolves = kind in _RESOLVING
        now = self._clock()
        with self._lock:
            self._seq += 1
            event = {
                "seq": self._seq,
                "monotonic_ts": now,
                "wall_ts": time.time(),
                "subsystem": subsystem,
                "kind": kind,
                "severity": severity,
                "attrs": dict(attrs) if attrs else {},
                "correlation_id": correlation_id,
            }
            if len(self._ring) == self._ring.maxlen:
                self.dropped += 1
            self._ring.append(event)
            self._correlate(event, now, resolves)
            self._publish_locked()
        return event

    def _correlate(self, event, now, resolves):
        self._sweep_quiet(now)
        target = None
        for inc in reversed(self._open):
            same_corr = (event["correlation_id"] is not None
                         and inc.correlation_id == event["correlation_id"])
            if same_corr or now - inc.last_ts <= self.window_s:
                target = inc
                break
        if target is not None:
            event["incident"] = target.id
            if resolves:
                target.absorb(event)
                self._close(target, now, event["kind"])
            else:
                target.absorb(event)
            return
        if event["severity"] == "error" and not resolves:
            self._incident_seq += 1
            inc = Incident(self._incident_seq, event)
            event["incident"] = inc.id
            self._open.append(inc)

    def _sweep_quiet(self, now):
        # iterate a copy: _close() removes from self._open in place
        for inc in list(self._open):
            if now - inc.last_ts > self.quiet_s:
                self._close(inc, now, None)

    def _close(self, inc, ts, resolution):
        inc.close(ts, resolution)
        if inc in self._open:
            self._open.remove(inc)
        self._closed.append(inc)
        self.resolved_total += 1

    def _publish_locked(self):
        # Journal state -> metrics.  Reached only from emit(), which the
        # module-level guard already limits to enabled monitoring.
        try:
            from deeplearning4j_tpu.monitoring import registry as _registry
            reg = _registry.get_registry()
            reg.counter(_registry.EVENTS_EMITTED,
                        help="ops events emitted into the journal").inc()
            if self.dropped:
                reg.gauge(
                    _registry.EVENTS_DROPPED,
                    help="ops events dropped from the bounded ring",
                ).set(self.dropped)
            reg.gauge(_registry.INCIDENTS_OPEN,
                      help="currently open correlated incidents").set(
                          len(self._open))
            reg.gauge(_registry.INCIDENTS_RESOLVED,
                      help="incidents closed since startup").set(
                          self.resolved_total)
        except Exception:
            pass

    # -- read side ---------------------------------------------------------

    def snapshot(self, last=64):
        with self._lock:
            self._sweep_quiet(self._clock())
            events = list(self._ring)
            emitted = self._seq
            dropped = self.dropped
        if last is not None and last >= 0:
            # slice via len(): events[-0:] would be the WHOLE ring
            events = events[len(events) - min(last, len(events)):]
        return {
            "events": events,
            "capacity": self.capacity,
            "emitted": emitted,
            "dropped": dropped,
        }

    def incidents(self):
        with self._lock:
            self._sweep_quiet(self._clock())
            open_snap = [inc.snapshot() for inc in self._open]
            recent = [inc.snapshot() for inc in reversed(self._closed)]
        return {
            "open": open_snap,
            "recent": recent,
            "resolved_total": self.resolved_total,
            "window_s": self.window_s,
            "quiet_s": self.quiet_s,
        }


_JOURNAL = None
_JOURNAL_LOCK = threading.Lock()


def journal():
    """The process-wide journal (created on first use)."""
    global _JOURNAL
    if _JOURNAL is None:
        with _JOURNAL_LOCK:
            if _JOURNAL is None:
                _JOURNAL = EventJournal()
    return _JOURNAL


def reset(**kwargs):
    """Swap in a fresh journal (tests); kwargs forward to EventJournal."""
    global _JOURNAL
    with _JOURNAL_LOCK:
        _JOURNAL = EventJournal(**kwargs)
    return _JOURNAL


def emit(subsystem, kind, attrs=None, correlation_id=None,
         severity=None, resolves=None):
    """Record one ops event.  No-op (one branch) when monitoring is off."""
    if not STATE.enabled:
        return None
    return journal().emit(subsystem, kind, attrs=attrs,
                          correlation_id=correlation_id,
                          severity=severity, resolves=resolves)


def snapshot(last=64):
    """Tail of the event ring (``GET /events?last=N``)."""
    return journal().snapshot(last=last)


def incidents():
    """Open + recent correlated incidents (``GET /incidents``)."""
    return journal().incidents()


# --------------------------------------------------------------------------
# Post-mortem bundle: one JSON with everything an operator opens first.

BUNDLE_SECTIONS = ("events", "incidents", "metrics", "steps",
                   "requests", "health", "spans")


def bundle(headline=None):
    """Assemble the seven-section post-mortem document (best-effort:
    a section that fails to snapshot becomes None, never an exception —
    this runs from crash paths)."""
    doc = {"meta": {
        "headline": headline,
        "written_wall_ts": time.time(),
        "pid": os.getpid(),
        "monitoring_enabled": STATE.enabled,
        "sections": list(BUNDLE_SECTIONS),
    }}
    j = journal()
    try:
        doc["events"] = j.snapshot(last=None)
    except Exception:
        doc["events"] = None
    try:
        doc["incidents"] = j.incidents()
    except Exception:
        doc["incidents"] = None
    try:
        from deeplearning4j_tpu.monitoring import registry as _registry
        doc["metrics"] = _registry.get_registry().snapshot()
    except Exception:
        doc["metrics"] = None
    try:
        from deeplearning4j_tpu.monitoring import steps as _steps
        rec = _steps.recorder()
        doc["steps"] = {"records": rec.records(last=64),
                        "summary": rec.summary()}
    except Exception:
        doc["steps"] = None
    try:
        from deeplearning4j_tpu.monitoring import requests as _requests
        doc["requests"] = _requests.request_log().snapshot(last=64)
    except Exception:
        doc["requests"] = None
    try:
        from deeplearning4j_tpu import resilience as _resilience
        doc["health"] = _resilience.health_snapshot()
    except Exception:
        doc["health"] = None
    try:
        from deeplearning4j_tpu.monitoring.tracing import get_tracer
        doc["spans"] = {str(tid): stack for tid, stack
                        in get_tracer().open_spans().items()}
    except Exception:
        doc["spans"] = None
    return doc


def write_bundle(path=None, dump_dir=None, headline=None,
                 prefix="dl4j-bundle"):
    """Write the post-mortem bundle as one JSON file; returns the path
    (or None if even the write failed — crash paths must not re-raise)."""
    try:
        doc = bundle(headline=headline)
        if path is None:
            directory = dump_dir or os.environ.get(
                "DL4J_CRASH_DUMP_DIR") or os.getcwd()
            os.makedirs(directory, exist_ok=True)
            stamp = time.strftime("%Y%m%d-%H%M%S")
            path = os.path.join(
                directory, "%s-%s-%d.json" % (prefix, stamp, os.getpid()))
        with open(path, "w") as f:
            json.dump(doc, f, indent=1, default=str)
        return path
    except Exception:
        return None


def event_tail_lines(last=20):
    """The shared human-readable journal-tail section embedded in every
    text debug artifact (crash dumps, stall reports, peer reports)."""
    lines = ["Ops event journal (tail):"]
    try:
        snap = journal().snapshot(last=last)
        events = snap["events"]
        if not events:
            lines.append("  (no events recorded)")
        for e in events:
            corr = e.get("correlation_id")
            attrs = e.get("attrs") or {}
            extra = " ".join("%s=%s" % (k, attrs[k]) for k in sorted(attrs))
            lines.append("  #%-4d [%s] %s %s%s%s" % (
                e["seq"], e["severity"], e["subsystem"], e["kind"],
                (" " + extra) if extra else "",
                (" corr=%s" % corr) if corr else ""))
        if snap["dropped"]:
            lines.append("  (+%d older events dropped from the ring)"
                         % snap["dropped"])
    except Exception as exc:
        lines.append("  (journal unavailable: %r)" % (exc,))
    return lines


__all__ = [
    "EventJournal", "Incident", "KIND_SEVERITY", "BUNDLE_SECTIONS",
    "journal", "reset", "emit", "snapshot", "incidents",
    "bundle", "write_bundle", "event_tail_lines",
    "GUARDIAN_RETRY", "GUARDIAN_ROLLBACK", "GUARDIAN_DIVERGED",
    "GUARDIAN_RECOVERED", "WATCHDOG_STALL", "WATCHDOG_RECOVERED",
    "FAULT_INJECTED", "PRESSURE_ESCALATED", "PRESSURE_RELIEVED",
    "SERVER_REFUSED", "SERVER_SHED", "CACHE_GROWN", "CACHE_SHRUNK",
    "PAGES_EXHAUSTED", "PAGES_EVICTED", "SERVER_DISRUPTED",
    "SERVER_REPLAY", "SERVER_RESTARTED", "SERVER_RECOVERED",
    "SERVER_DEAD", "MEMBERSHIP_EPOCH", "MEMBERSHIP_JOINED",
    "MEMBERSHIP_LEAVE", "MEMBERSHIP_REPLACED", "PEER_LOST",
    "PEER_DESYNC", "SLO_BREACH", "SLO_RECOVER",
    "REPLICA_UNHEALTHY", "REPLICA_DRAINED", "REPLICA_REPLACED",
    "REQUEST_FAILOVER",
]
