"""Per-host step timelines + straggler attribution over the
coordination KV — the training-side twin of the cluster metrics plane
(`monitoring/cluster.py`).

In a multi-host run each process's step flight recorder
(`monitoring/steps.py`) is an island: per-step skew between hosts —
the signal elastic scale-up/replace decisions need — is invisible.
This module makes it visible with the same zero-cost discipline as the
cluster plane:

- **Publish** — at every coordination SYNC POINT (behind
  `_mon.enabled()`, best-effort), each process writes ONE compact JSON
  digest of its flight-recorder ring to `steps/<pid>` (overwrite —
  exactly one bounded key per process, nothing to reap): per-phase
  p50/p99 for data_next/stage/dispatch/exchange/listeners,
  host-blocked and compile totals, steps/s, and a short record tail
  for trace lanes. Zero new collectives, zero new host syncs — the
  digest is JSON over numbers the recorder already holds, and the lint
  (`scripts/check_fastpath.py`) walks this module to prove it.
- **Attribute** — process 0 (or any reader) gathers the digests and
  computes per-host ATTRIBUTED step time (sum of the `SUM_PHASES`
  p50s), the max-host / median-host ratio, and the culprit: the
  slowest host AND the phase with the largest excess over the
  cross-host median of that phase. Surfaces: `GET /stragglers`, new
  columns in the `GET /health` peer table, the
  `dl4j.dist.straggler_*` gauges (the labels ARE the culprit), the
  `StragglerObjective` SLO (`monitoring/slo.py`), and one named
  training lane per host in the merged Chrome trace (`GET /trace`).
- **Derive** — `derived_exchange_ms()` estimates the exposed exchange
  cost on any host count without issuing a collective: in a lockstep
  collective step every host leaves the exchange together, so the
  cross-host spread in dispatch-phase p50 is wall time the exchange
  exposed on the fast hosts (a conservative lower bound; the
  single-process probe in `parallel/multihost.py` remains the
  standalone upper bound).

The median is the LOWER median (`sorted[(n-1)//2]`): with two hosts it
is the fast host, so the ratio degrades to max/min instead of
saturating at 2× — small fleets still produce an actionable signal.
"""
from __future__ import annotations

import json
import time

from deeplearning4j_tpu.monitoring import registry as _registry
from deeplearning4j_tpu.monitoring import steps as _steps
from deeplearning4j_tpu.monitoring.state import STATE

__all__ = ["publish", "gather", "attribution", "annotate_peer_table",
           "derived_exchange_ms", "chrome_events"]

#: KV key prefix (under the coordinator's namespace)
KEY_PREFIX = "steps/"

#: records shipped per publish — enough for a trace lane's recent
#: history, bounded regardless of the local ring size
TAIL = 16

#: Chrome-trace pid band for the per-host training lanes: far above the
#: request lanes' tid space (1_000_000+) and real OS pids, and disjoint
#: per host so each renders as its own named process lane
LANE_BASE = 2_000_000


def publish(coordinator, recorder=None, extra=None):
    """Write this process's flight-recorder digest to `steps/<pid>`
    (one bounded, overwritten key). Called from the coordinator's sync
    point behind the enabled-guard; best-effort — a full KV store must
    never fail a training step."""
    rec = recorder or _steps.recorder()
    snap = {"t": time.time(), "step": coordinator.step,
            "timeline": rec.compact_summary(tail=TAIL)}
    if extra:
        snap.update(extra)
    coordinator.publish(f"{KEY_PREFIX}{coordinator.process_id}",
                        json.dumps(snap), overwrite=True)
    return snap


def gather(coordinator):
    """{pid: published digest} for every host that has published one
    (this process included when it has)."""
    out = {}
    for suffix, v in coordinator.fetch_dir(KEY_PREFIX):
        try:
            out[int(suffix)] = json.loads(v)
        except (ValueError, TypeError):
            continue
    return out


def _median(vals):
    """Lower median — for two hosts this is the FAST one, so the
    straggler ratio degrades to max/min instead of capping at 2x."""
    s = sorted(vals)
    return s[(len(s) - 1) // 2] if s else None


def attribution(coordinator, snaps=None):
    """The straggler verdict: per-host attributed step time, the
    max/median ratio, and the culprit host + phase. None when the KV is
    unreachable; `ratio`/`slowest` are None below two usable hosts.

    Step time is the sum of the per-phase p50s (`steps.SUM_PHASES`) —
    attribution, not raw wall: wall anchors end-of-step to end-of-step
    and would charge inter-step idle to whichever host paused.

    On process 0 with monitoring enabled the verdict also lands on the
    `dl4j.dist.straggler_*` gauges with the culprit as labels."""
    try:
        snaps = gather(coordinator) if snaps is None else snaps
    except Exception:  # noqa: BLE001 — KV service down
        return None
    now = time.time()
    hosts = {}
    for pid, snap in sorted(snaps.items()):
        tl = snap.get("timeline") or {}
        phases = tl.get("phases") or {}
        p50s = {k: float(v["p50"]) for k, v in phases.items()
                if isinstance(v, dict) and v.get("p50") is not None}
        step_ms = sum(p50s.get(p, 0.0) for p in _steps.SUM_PHASES)
        wall = (tl.get("wall_ms") or {}).get("p50")
        hosts[str(pid)] = {
            "step_ms": round(step_ms, 3),
            "wall_p50_ms": wall,
            "phases_p50_ms": {k: round(v, 3) for k, v in p50s.items()},
            "steps_per_s": snap.get("steps_per_s"),
            "snapshot_age_s": round(max(0.0, now - snap.get("t", now)),
                                    3),
        }
    out = {"hosts": hosts, "published": len(hosts),
           "ratio": None, "median_step_ms": None, "slowest": None}
    usable = {h: d for h, d in hosts.items() if d["step_ms"] > 0}
    if len(usable) < 2:
        return out
    med = _median([d["step_ms"] for d in usable.values()])
    slow_host = max(usable, key=lambda h: usable[h]["step_ms"])
    slow = usable[slow_host]
    if not med or med <= 0:
        return out
    ratio = slow["step_ms"] / med
    # culprit phase: largest excess of the slow host's p50 over the
    # cross-host median for the SAME phase — "host 1 is slow, and it's
    # the dispatch phase", not just "host 1 is slow"
    phase, excess = None, 0.0
    keys = set()
    for d in usable.values():
        keys.update(d["phases_p50_ms"])
    for k in sorted(keys):
        pm = _median([d["phases_p50_ms"].get(k, 0.0)
                      for d in usable.values()])
        e = slow["phases_p50_ms"].get(k, 0.0) - (pm or 0.0)
        if e > excess:
            phase, excess = k, e
    out["ratio"] = round(ratio, 4)
    out["median_step_ms"] = round(med, 3)
    out["slowest"] = {"host": slow_host, "phase": phase,
                      "step_ms": slow["step_ms"],
                      "excess_ms": round(excess, 3),
                      "ratio": out["ratio"]}
    if STATE.enabled and coordinator.process_id == 0 \
            and phase is not None:
        reg = _registry.get_registry()
        labels = {"host": slow_host, "phase": phase}
        reg.gauge(_registry.DIST_STRAGGLER_RATIO, labels=labels,
                  help="max-host / median-host attributed step time; "
                       "the labels name the culprit host and phase"
                  ).set(ratio)
        reg.gauge(_registry.DIST_STRAGGLER_SKEW_MS, labels=labels,
                  help="slowest host's attributed step time excess "
                       "over the median host (ms)"
                  ).set(slow["step_ms"] - med)
    return out


def annotate_peer_table(coordinator, table, att=None):
    """Fold the per-host timeline columns + the straggler verdict into
    the `GET /health` peer table (best-effort, never raises)."""
    try:
        att = attribution(coordinator) if att is None else att
    except Exception:  # noqa: BLE001
        return table
    if att is None:
        return table
    for h, d in att["hosts"].items():
        try:
            pid = int(h)
        except ValueError:
            continue
        entry = table.setdefault(pid, {})
        entry["step_ms_p50"] = d["step_ms"]
        if d.get("wall_p50_ms") is not None:
            entry["step_wall_p50_ms"] = d["wall_p50_ms"]
    slow = att.get("slowest")
    if slow is not None:
        try:
            pid = int(slow["host"])
        except (ValueError, TypeError):
            return table
        table.setdefault(pid, {})["straggler"] = {
            "phase": slow["phase"], "ratio": slow["ratio"]}
    return table


def derived_exchange_ms(coordinator, snaps=None):
    """Multi-host exposed-exchange estimate from the per-phase
    attribution: the cross-host spread (max - min) of the
    dispatch-phase p50. In a lockstep collective step every host
    leaves the exchange together, so a host that reaches it late
    forces every other host to expose at least that difference waiting
    in the collective — a conservative lower bound on the exposure,
    measured on any host count without issuing a collective (the
    single-process probe stays the standalone upper bound). None below
    two reporting hosts."""
    try:
        snaps = gather(coordinator) if snaps is None else snaps
    except Exception:  # noqa: BLE001
        return None
    vals = []
    for snap in snaps.values():
        p = ((snap.get("timeline") or {}).get("phases") or {}) \
            .get("dispatch")
        if isinstance(p, dict) and p.get("p50") is not None:
            vals.append(float(p["p50"]))
    if len(vals) < 2:
        return None
    return max(vals) - min(vals)


def chrome_events(coordinator, epoch_ns=None):
    """One named Chrome-trace lane per host from the published record
    tails: a process-name metadata event (`train host <pid>`) plus one
    "X" slice per step, so a skewed step is visually obvious next to
    the local span lanes in Perfetto. Cross-host alignment rides the
    records' unix `ts`, mapped onto the tracer's perf-counter timebase
    via one (now_unix, now_perf) correspondence taken at export time —
    approximate to NTP skew, which is fine for eyeballing skew that
    the attribution already quantifies."""
    try:
        snaps = gather(coordinator)
    except Exception:  # noqa: BLE001
        return []
    now_perf_ns = time.perf_counter_ns()
    now_unix = time.time()
    base_ns = epoch_ns if epoch_ns is not None else now_perf_ns

    def to_us(unix_ts):
        return ((now_perf_ns - base_ns) / 1e3
                + (unix_ts - now_unix) * 1e6)

    out = []
    for pid, snap in sorted(snaps.items()):
        tail = (snap.get("timeline") or {}).get("tail") or []
        lane = LANE_BASE + int(pid)
        out.append({"ph": "M", "name": "process_name", "pid": lane,
                    "tid": 0, "args": {"name": f"train host {pid}"}})
        for r in tail:
            ts_end = r.get("ts")
            dur_ms = r.get("wall_ms")
            if dur_ms is None:
                dur_ms = sum((r.get("phases") or {}).values())
            if ts_end is None or not dur_ms:
                continue
            args = {"host": str(pid)}
            args.update(r.get("phases") or {})
            out.append({"ph": "X", "cat": "train",
                        "name": f"step {r.get('step')}",
                        "ts": to_us(ts_end) - dur_ms * 1e3,
                        "dur": dur_ms * 1e3,
                        "pid": lane, "tid": 0, "args": args})
    return out
