"""MetricsRegistry: dependency-free counters, gauges, and streaming
histograms with Prometheus text exposition.

Host-side observability for the XLA-fused world: device ops collapse into
one step executable (SURVEY §1 inversion), so the actionable numbers are
host-side — dispatch wall time, jit-cache compile events, host↔device
transfer bytes, device memory watermarks. This registry is where all of
them land; `ui/server.py` exposes it at `GET /metrics` and
`optimize/listeners.MetricsListener` feeds it per iteration.

Division of labour with the rest of the repo's observability:
- `optimize/xplane.py` + `ProfilerListener` — DEVICE-side per-op traces
  (jax.profiler / xplane.pb, viewable in TensorBoard/Perfetto);
- `ui/stats.py` StatsListener — learning diagnostics (score, update
  ratios, activation histograms) for the training dashboard;
- this module — HOST-side operational metrics in Prometheus shape, plus
  `monitoring.tracing` for span-level phase timing.

Everything is JSON-native (`snapshot()`), same idiom as `ui/stats.py`.
"""
from __future__ import annotations

import collections
import math
import re
import threading
import time

from deeplearning4j_tpu.monitoring.state import STATE

# canonical metric names used by the built-in collectors (dots are
# sanitized to underscores in the Prometheus exposition)
JIT_CACHE_MISSES = "dl4j.jit.cache_misses"
JIT_COMPILE_SECONDS = "dl4j.jit.compile_seconds"
# persistent-compilation-cache tier split (runtime/executables.py wires
# jax's cache events): a "hit" skipped the XLA compile (served from the
# cross-process on-disk cache); "requests" counts every compile that
# consulted the cache, so live compiles = requests - hits. NOTE jax's
# "miss" event fires only when a NEW entry is WRITTEN — sub-threshold
# compiles (jax_persistent_cache_min_compile_time_secs/_entry_size) are
# not persisted and land in neither hits nor misses, only in requests.
JIT_PERSISTENT_HITS = "dl4j.jit.persistent_hits"
JIT_PERSISTENT_MISSES = "dl4j.jit.persistent_misses"
JIT_PERSISTENT_REQUESTS = "dl4j.jit.persistent_requests"
OP_DISPATCHES = "dl4j.op.dispatches"

# AOT serving-executable store (runtime/executables.py): two-tier cache
# of pre-compiled bucketed forwards. Steady-state serving must show ZERO
# compiles — every forward resolves in the in-memory tier; a restarted
# replica warms via disk_hits (deserialize, no XLA compile)
EXEC_COMPILES = "dl4j.exec.compiles"
EXEC_COMPILE_SECONDS = "dl4j.exec.compile_seconds"
EXEC_DISK_HITS = "dl4j.exec.disk_hits"
EXEC_DESERIALIZE_FAILURES = "dl4j.exec.deserialize_failures"
EXEC_SERIALIZE_FAILURES = "dl4j.exec.serialize_failures"
# XLA cost model per cached executable, recorded once at compile/load
# time (labels: store, signature) — the per-dispatch FLOPs/bytes
# denominator behind "as fast as the hardware allows"
EXEC_FLOPS = "dl4j.exec.flops"
EXEC_BYTES_ACCESSED = "dl4j.exec.bytes_accessed"

# shape-bucketed continuous batching (parallel/inference.py AOT path):
# padding waste = padded_rows / (rows + padded_rows); occupancy is the
# per-dispatch fill ratio rows/bucket; splits count oversized batches
# served across several max-bucket dispatches instead of a novel shape
SERVING_ROWS = "dl4j.serving.rows"
SERVING_PADDED_ROWS = "dl4j.serving.padded_rows"
SERVING_BUCKET_OCCUPANCY = "dl4j.serving.bucket_occupancy"
SERVING_SPLITS = "dl4j.serving.splits"
SERVING_STAGED_BUFFERS = "dl4j.serving.staged_buffers"
SERVING_STAGING_OCCUPANCY = "dl4j.serving.staging_occupancy"
SERVING_AOT_FALLBACKS = "dl4j.serving.aot_fallbacks"
TRANSFER_H2D_BYTES = "dl4j.transfer.host_to_device_bytes"
DEVICE_MEMORY_BYTES = "dl4j.device.memory_bytes"
DEVICE_MEMORY_SUPPORTED = "dl4j.device.memory_stats_supported"
HOST_RSS_BYTES = "dl4j.host.rss_bytes"

# resilience subsystem (resilience/ + the hardened serving/training
# paths): every retry, breaker trip, shed request, skipped batch, and
# checkpoint resume lands on one of these
RESILIENCE_RETRIES = "dl4j.resilience.retries"
RESILIENCE_BACKOFF_SECONDS = "dl4j.resilience.backoff_seconds"
RESILIENCE_BREAKER_TRIPS = "dl4j.resilience.breaker_trips"
RESILIENCE_FAULTS_INJECTED = "dl4j.resilience.faults_injected"
RESILIENCE_BATCHES_SKIPPED = "dl4j.resilience.batches_skipped"
RESILIENCE_CHECKPOINT_SAVES = "dl4j.resilience.checkpoint_saves"
RESILIENCE_RESUMES = "dl4j.resilience.resumes"
RESILIENCE_RESUME_STEP = "dl4j.resilience.resume_step"
RESILIENCE_INFERENCE_SHED = "dl4j.resilience.inference_shed"
RESILIENCE_INFERENCE_TIMEOUTS = "dl4j.resilience.inference_timeouts"
RESILIENCE_COLLECTOR_RESTARTS = "dl4j.resilience.collector_restarts"
RESILIENCE_CKPT_ORPHANS_REMOVED = "dl4j.resilience.ckpt_orphans_removed"
RESILIENCE_CKPT_FALLBACKS = "dl4j.resilience.ckpt_restore_fallbacks"

# training guardian (resilience/guardian.py): model-state health —
# device-side per-step verdicts, skipped (never-applied) updates, and
# the escalation ladder's LR retries / checkpoint rollbacks
GUARDIAN_CHECKS = "dl4j.guardian.checks"
GUARDIAN_SKIPPED_UPDATES = "dl4j.guardian.skipped_updates"
GUARDIAN_LR_RETRIES = "dl4j.guardian.lr_retries"
GUARDIAN_ROLLBACKS = "dl4j.guardian.rollbacks"
GUARDIAN_SAVES_GATED = "dl4j.guardian.saves_gated"
GUARDIAN_LAST_GOOD_STEP = "dl4j.guardian.last_good_step"

# stall watchdog (resilience/watchdog.py): per-trainer heartbeat age and
# stall trips (a step exceeding DL4J_STALL_TIMEOUT)
WATCHDOG_STALLS = "dl4j.watchdog.stalls"
WATCHDOG_BEAT_AGE_SECONDS = "dl4j.watchdog.beat_age_seconds"
WATCHDOG_DUMPS = "dl4j.watchdog.dumps"

# multi-host coordination (parallel/multihost.py): peer liveness, the
# preemption drain, barrier health, and the compressed gradient
# exchange's wire/residual telemetry
DIST_PEERS = "dl4j.dist.peers"
DIST_PEER_LOST = "dl4j.dist.peer_lost"
DIST_PREEMPTIONS = "dl4j.dist.preemptions"
DIST_BARRIER_TIMEOUTS = "dl4j.dist.barrier_timeouts"
DIST_ENCODED_BYTES = "dl4j.dist.encoded_bytes"
DIST_RESIDUAL_NORM = "dl4j.dist.residual_norm"
# in-step accumulation + bucketed/overlapped exchange (parallel/buckets
# + the accumulating trainer steps): configured knobs and the measured
# standalone exchange cost (the time overlap exists to hide)
DIST_ACCUM_MICROBATCHES = "dl4j.dist.accum_microbatches"
DIST_EXCHANGE_BUCKETS = "dl4j.dist.exchange_buckets"
DIST_BUCKET_BYTES = "dl4j.dist.bucket_bytes"
DIST_EXPOSED_EXCHANGE_MS = "dl4j.dist.exposed_exchange_ms"
DIST_ENCODER_MIGRATIONS = "dl4j.dist.encoder_migrations"
# elastic membership (parallel/membership.py): agreed membership
# changes, executed mesh re-forms (labels: kind=join|leave|replace) and
# the wall cost of the last re-form (drain save + rebuild + re-place)
DIST_REFORMS_AGREED = "dl4j.dist.reforms_agreed"
DIST_REFORMS = "dl4j.dist.reforms"
DIST_REFORM_MS = "dl4j.dist.reform_ms"
DIST_WIRE_BYTES = "dl4j.dist.wire_bytes"
# straggler attribution (monitoring/stragglers.py): process 0 computes
# per-step skew across the published per-host step timelines and names
# the slowest host AND phase — the labels on these gauges ARE the
# culprit (labels: host, phase). `ratio` is max-host / median-host
# attributed step time; `skew_ms` the slow host's absolute excess over
# the median host.
DIST_STRAGGLER_RATIO = "dl4j.dist.straggler_ratio"
DIST_STRAGGLER_SKEW_MS = "dl4j.dist.straggler_skew_ms"

# host pipeline (runtime/pipeline.py): is the host running ahead of the
# device, or blocking on it? `syncs` counts every host-blocking
# materialization (a listener-free fit should record ZERO per-step syncs),
# `host_blocked_ms` is how long each one stalled the host, and
# `prefetch_depth` samples the staging queue occupancy (0 = the device is
# waiting on the loader; full = the loader is comfortably ahead)
PIPELINE_SYNCS = "dl4j.pipeline.syncs"
PIPELINE_HOST_BLOCKED_MS = "dl4j.pipeline.host_blocked_ms"
PIPELINE_PREFETCH_DEPTH = "dl4j.pipeline.prefetch_depth"
PIPELINE_STAGED_BATCHES = "dl4j.pipeline.staged_batches"

# device profiling (monitoring/profiler.py ProfileSession): one on-demand
# jax.profiler window around k training steps, rolled up to a per-op table
PROFILE_SESSIONS = "dl4j.profile.sessions"
PROFILE_CAPTURED_STEPS = "dl4j.profile.captured_steps"
PROFILE_DEVICE_MS = "dl4j.profile.device_ms"
PROFILE_OP_MS = "dl4j.profile.op_ms"
PROFILE_OP_COUNT = "dl4j.profile.op_count"

# step-time attribution flight recorder (monitoring/steps.py)
STEP_WALL_MS = "dl4j.step.wall_ms"
STEP_PHASE_MS = "dl4j.step.phase_ms"

# model memory footprint estimates from the live trees
# (monitoring/memory.py)
MODEL_PARAMS_BYTES = "dl4j.model.params_bytes"
MODEL_OPT_STATE_BYTES = "dl4j.model.opt_state_bytes"
MODEL_LAYER_STATE_BYTES = "dl4j.model.layer_state_bytes"

# quantization (quantize/): the memory-traffic diet's observability —
# how many layers actually serve int8, how their activation scales were
# obtained, which weight-bearing layers fell back to fp (dequant
# fallbacks), and the per-model activation-traffic estimate by
# precision policy (quantize/traffic.py gauge; labels: model, policy)
QUANT_INT8_LAYERS = "dl4j.quant.int8_layers"
QUANT_CALIBRATIONS = "dl4j.quant.calibrations"
QUANT_DEQUANT_FALLBACKS = "dl4j.quant.dequant_fallbacks"
QUANT_ACTIVATION_BYTES = "dl4j.quant.activation_traffic_bytes"

# request-scoped serving metrics (monitoring/requests.py wires the
# timelines; the latency histograms carry EXEMPLAR trace ids so a bad
# p99 clicks through to an actual slow-request timeline on /requests)
INFERENCE_REQUEST_MS = "dl4j.inference.request_ms"

# SLO tracker (monitoring/slo.py): declarative objectives evaluated on
# a multi-window burn-rate rule over the histograms / flight recorder
# already collected. `breaches` counts objective trips (labels:
# objective), `burn_rate` is the current error-budget burn per window
# (labels: objective, window), `breached` is 0/1 per objective.
SLO_BREACHES = "dl4j.slo.breaches"
SLO_BURN_RATE = "dl4j.slo.burn_rate"
SLO_BREACHED = "dl4j.slo.breached"

# ops event journal + incident correlation (monitoring/events.py):
# emitted/dropped count the bounded ring's intake, open/resolved track
# the correlator — an open incident is the fleet router's drain signal
EVENTS_EMITTED = "dl4j.events.emitted"
EVENTS_DROPPED = "dl4j.events.dropped"
INCIDENTS_OPEN = "dl4j.incidents.open"
INCIDENTS_RESOLVED = "dl4j.incidents.resolved"

# cluster metrics plane (monitoring/cluster.py): per-host snapshot age
# as seen from process 0 (labels: host; host="cluster" is the max age —
# a stale host means its publishing process stopped syncing)
CLUSTER_SNAPSHOT_AGE = "dl4j.cluster.snapshot_age_seconds"

# autoregressive generation (generation/server.py): KV-cache decode loop
# with continuous-batching admission
GEN_TOKENS = "dl4j.gen.tokens"
GEN_ACTIVE_SLOTS = "dl4j.gen.active_slots"
GEN_ADMISSIONS = "dl4j.gen.admissions"
GEN_RETIREMENTS = "dl4j.gen.retirements"
GEN_PREFILL_MS = "dl4j.gen.prefill_ms"
GEN_PER_TOKEN_MS = "dl4j.gen.per_token_ms"
# serving survivability: crash-replay re-admissions, supervised decode
# restarts, and memory-pressure degradation-ladder events
GEN_REPLAYS = "dl4j.gen.replays"
GEN_RESTARTS = "dl4j.gen.restarts"
GEN_DEGRADATIONS = "dl4j.gen.degradations"
# decode superstep pipeline: multi-token block dispatches (superstep /
# draft-verify), live tokens delivered per decode dispatch, the window
# the async token fetch overlapped the next dispatch, and greedy-draft
# acceptance accounting
GEN_SUPERSTEPS = "dl4j.gen.supersteps"
GEN_TOKENS_PER_DISPATCH = "dl4j.gen.tokens_per_dispatch"
GEN_FETCH_OVERLAP_MS = "dl4j.gen.fetch_overlap_ms"
GEN_DRAFT_ACCEPTS = "dl4j.gen.draft_accepts"
GEN_DRAFT_REJECTS = "dl4j.gen.draft_rejects"
# paged KV cache: pool occupancy/sharing gauges plus prefix-dedup and
# cold-page-eviction counters (emitted on the decode dispatch boundary)
GEN_PAGES_ACTIVE = "dl4j.gen.pages_active"
GEN_PAGES_SHARED = "dl4j.gen.pages_shared"
GEN_PAGE_EVICTIONS = "dl4j.gen.page_evictions"
GEN_PREFIX_HITS = "dl4j.gen.prefix_hits"

# fleet router (generation/fleet.py): health-driven routing across
# GenerationServer replicas. `routed` counts admissions per replica
# (labels: replica), `failovers` mid-stream re-routes via journal
# replay, `replacements` supervisor-built replacement replicas;
# `healthy` and `desired_replicas` are the live roster gauge and the
# autoscale signal (queue depth x SLO burn)
FLEET_ROUTED = "dl4j.fleet.routed"
FLEET_FAILOVERS = "dl4j.fleet.failovers"
FLEET_REPLACEMENTS = "dl4j.fleet.replacements"
FLEET_HEALTHY = "dl4j.fleet.healthy"
FLEET_DESIRED_REPLICAS = "dl4j.fleet.desired_replicas"

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")
_LABEL_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name):
    n = _NAME_RE.sub("_", str(name))
    return "_" + n if n[:1].isdigit() else n


def _esc_label_value(v):
    """Label-value escaping per the text exposition format: backslash
    first, then newline and double quote — a value containing any of
    them must round-trip through a strict scraper."""
    return str(v).replace("\\", "\\\\").replace("\n", "\\n") \
                 .replace('"', '\\"')


def _esc_help(text):
    """HELP-line escaping (the spec escapes `\\` and line feeds only;
    quotes are legal in help text)."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _prom_value(v):
    """Sample-value rendering: the format spec requires `+Inf` / `-Inf`
    / `NaN` spellings — Python's `inf`/`nan` break strict scrapers."""
    if isinstance(v, float):
        if math.isnan(v):
            return "NaN"
        if math.isinf(v):
            return "+Inf" if v > 0 else "-Inf"
        return f"{v:.9g}"
    return str(v)


def _prom_labels(labels, extra=()):
    items = list(labels) + list(extra)
    if not items:
        return ""
    body = ",".join(f'{_LABEL_RE.sub("_", str(k))}="{_esc_label_value(v)}"'
                    for k, v in items)
    return "{" + body + "}"


def _render_family_header(lines, pname, kind, help_text=None):
    """The `# HELP` / `# TYPE` lines for one family — ONE rule shared
    by the local renderer and the cluster plane (monitoring/cluster.py)
    so the conformance guarantees cannot drift between them."""
    if help_text is not None:
        lines.append(f"# HELP {pname} {_esc_help(help_text)}")
    lines.append(f"# TYPE {pname} "
                 f"{'summary' if kind == 'histogram' else kind}")


def _render_sample_lines(lines, pname, kind, labelitems, rec):
    """The sample lines for one series. `rec` carries `quantiles`
    ((q, value) pairs, Nones skipped) + `count`/`sum` for histograms,
    `value` otherwise — escaping and the `+Inf`/`NaN` spellings all
    route through `_prom_labels`/`_prom_value` here, for every
    renderer."""
    if kind == "histogram":
        for q, qv in rec.get("quantiles", ()):
            if qv is not None:
                lines.append(
                    f"{pname}"
                    f"{_prom_labels(labelitems, [('quantile', q)])}"
                    f" {_prom_value(float(qv))}")
        lines.append(f"{pname}_count{_prom_labels(labelitems)} "
                     f"{int(rec.get('count', 0))}")
        lines.append(f"{pname}_sum{_prom_labels(labelitems)} "
                     f"{_prom_value(float(rec.get('sum', 0.0)))}")
    else:
        lines.append(f"{pname}{_prom_labels(labelitems)} "
                     f"{_prom_value(rec.get('value', 0))}")


class Counter:
    """Monotonic counter. inc() is lock-free on CPython (int += under the
    GIL is not torn; a lost increment under extreme contention is an
    acceptable metrics trade, same as statsd)."""

    __slots__ = ("name", "labels", "_value")
    kind = "counter"

    def __init__(self, name, labels=()):
        self.name = name
        self.labels = labels
        self._value = 0

    def inc(self, amount=1):
        self._value += amount

    @property
    def value(self):
        return self._value


class Gauge:
    __slots__ = ("name", "labels", "_value")
    kind = "gauge"

    def __init__(self, name, labels=()):
        self.name = name
        self.labels = labels
        self._value = 0.0

    def set(self, value):
        self._value = float(value)

    def inc(self, amount=1.0):
        self._value += amount

    def dec(self, amount=1.0):
        self._value -= amount

    @property
    def value(self):
        return self._value


class Histogram:
    """Streaming histogram: exact count/sum/min/max plus quantiles
    (p50/p95/p99) over a bounded ring-buffer reservoir of the most recent
    observations — O(reservoir) memory however long training runs."""

    __slots__ = ("name", "labels", "_lock", "_count", "_sum", "_min",
                 "_max", "_ring", "_ring_n", "_idx", "_exemplars")
    kind = "histogram"

    #: recent (value, trace_id, ts) observations retained for exemplar
    #: lookup — bounded, newest wins on eviction
    EXEMPLAR_WINDOW = 64

    def __init__(self, name, labels=(), reservoir=2048):
        self.name = name
        self.labels = labels
        self._lock = threading.Lock()
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf
        self._ring = [0.0] * int(reservoir)
        self._ring_n = 0
        self._idx = 0
        self._exemplars = None      # allocated on first traced observe

    def observe(self, value, trace_id=None):
        """Record one observation; `trace_id` (optional) attaches a
        request-timeline exemplar — the top values of the recent window
        keep their trace ids (`exemplars()`), so a bad p99 links to an
        actual slow request on `GET /requests/<id>`."""
        v = float(value)
        with self._lock:
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v
            self._ring[self._idx] = v
            self._idx = (self._idx + 1) % len(self._ring)
            if self._ring_n < len(self._ring):
                self._ring_n += 1
            if trace_id is not None:
                if self._exemplars is None:
                    self._exemplars = collections.deque(
                        maxlen=self.EXEMPLAR_WINDOW)
                self._exemplars.append((v, str(trace_id), time.time()))

    def exemplars(self, top=5):
        """The highest-valued recent traced observations, descending:
        [{"value", "trace_id", "ts"}]. These are the trace ids behind
        the current tail of the distribution — the p99 click-through."""
        with self._lock:
            recent = list(self._exemplars) if self._exemplars else []
        recent.sort(key=lambda e: e[0], reverse=True)
        return [{"value": v, "trace_id": t, "ts": ts}
                for v, t, ts in recent[:int(top)]]

    @property
    def count(self):
        return self._count

    @property
    def sum(self):
        return self._sum

    def quantile(self, q):
        """Quantile over the reservoir (recent window); None when empty."""
        with self._lock:
            window = sorted(self._ring[:self._ring_n])
        if not window:
            return None
        pos = min(len(window) - 1,
                  max(0, int(math.ceil(q * len(window)) - 1)))
        return window[pos]

    def snapshot(self):
        with self._lock:
            window = sorted(self._ring[:self._ring_n])
            out = {"count": self._count, "sum": self._sum,
                   "min": None if self._count == 0 else self._min,
                   "max": None if self._count == 0 else self._max}
            has_ex = bool(self._exemplars)
        if has_ex:
            out["exemplars"] = self.exemplars()
        for label, q in (("p50", 0.50), ("p95", 0.95), ("p99", 0.99)):
            if window:
                pos = min(len(window) - 1,
                          max(0, int(math.ceil(q * len(window)) - 1)))
                out[label] = window[pos]
            else:
                out[label] = None
        return out


class MetricsRegistry:
    """Named metric families, each a set of label-keyed children.

    counter/gauge/histogram are get-or-create: the same (name, labels)
    always returns the same object, so call sites never cache handles
    unless they want to skip the dict lookup."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics = {}          # (name, labelitems) -> metric
        self._help = {}             # name -> help string
        #: bumped by clear() so hot paths that cache metric handles
        #: (runtime/executioner.py) know to re-resolve them
        self.generation = 0

    def _get(self, cls, name, labels, help=None, **kw):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            m = self._metrics.get(key)
            if m is None:
                m = cls(name, labels=key[1], **kw)
                self._metrics[key] = m
            elif not isinstance(m, cls):
                raise TypeError(f"metric {name!r} already registered as "
                                f"{m.kind}, not {cls.kind}")
            if help:
                self._help.setdefault(name, help)
        return m

    def counter(self, name, labels=None, help=None):
        return self._get(Counter, name, labels, help)

    def gauge(self, name, labels=None, help=None):
        return self._get(Gauge, name, labels, help)

    def histogram(self, name, labels=None, help=None, reservoir=2048):
        return self._get(Histogram, name, labels, help,
                         reservoir=reservoir)

    def get(self, name, labels=None):
        """Existing metric or None (never creates)."""
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            return self._metrics.get(key)

    def clear(self):
        with self._lock:
            self._metrics.clear()
            self._help.clear()
            self.generation += 1

    # -- export ----------------------------------------------------------
    def help_texts(self):
        """{metric name: help string} — the cluster renderer
        (monitoring/cluster.py) reuses the local help lines for the
        per-host-labeled families."""
        with self._lock:
            return dict(self._help)

    def snapshot(self):
        """JSON-native dump (same idiom as ui/stats records):
        {name: [{labels: {...}, ...metric fields}]}."""
        with self._lock:
            items = list(self._metrics.items())
        out = {}
        for (name, labelitems), m in items:
            rec = {"labels": dict(labelitems), "kind": m.kind}
            if isinstance(m, Histogram):
                rec.update(m.snapshot())
            else:
                rec["value"] = m.value
            out.setdefault(name, []).append(rec)
        return out

    def prometheus_text(self):
        """Prometheus text exposition format 0.0.4. Histograms are emitted
        as summaries (streaming quantiles, not cumulative buckets).
        Conformance guarantees (unit-tested): every family gets a
        `# TYPE` line (and a `# HELP` line whenever a help string was
        registered, escaped per the spec), label values escape `\\`,
        `"` and newlines, and non-finite samples render as `+Inf` /
        `-Inf` / `NaN` — strict scrapers must never choke on a value
        that came out of the registry."""
        with self._lock:
            items = sorted(self._metrics.items(), key=lambda kv: kv[0])
            helps = dict(self._help)
        lines = []
        seen_header = set()
        for (name, labelitems), m in items:
            pname = _prom_name(name)
            if pname not in seen_header:
                seen_header.add(pname)
                _render_family_header(lines, pname, m.kind,
                                      helps.get(name))
            if isinstance(m, Histogram):
                snap = m.snapshot()
                rec = {"count": snap["count"], "sum": snap["sum"],
                       "quantiles": [("0.5", snap["p50"]),
                                     ("0.95", snap["p95"]),
                                     ("0.99", snap["p99"])]}
            else:
                rec = {"value": m.value}
            _render_sample_lines(lines, pname, m.kind, labelitems, rec)
        return "\n".join(lines) + "\n"


_global_registry = MetricsRegistry()


def get_registry():
    """THE process-global registry every built-in collector feeds."""
    return _global_registry


# -- built-in collectors ---------------------------------------------------
def record_transfer(nbytes, registry=None):
    """Count host→device bytes at explicit placement points
    (jax.device_put call sites in the parallel stack). No-op when
    monitoring is disabled — one branch, no allocation."""
    if not STATE.enabled:
        return
    (registry or _global_registry).counter(
        TRANSFER_H2D_BYTES,
        help="bytes explicitly placed host-to-device").inc(int(nbytes))


def collect_device_memory(registry=None, device_stats=None):
    """Per-device memory gauges from `device.memory_stats()` (TPU/GPU
    backends; CPU returns None → the `supported 0` gauge says so instead
    of inventing numbers), plus the host RSS from /proc.

    `device_stats` lets a caller that already holds a
    `{device_str: stats_or_None}` snapshot (monitoring.memory.sample)
    feed the gauges without a second memory_stats sweep; when omitted,
    the LOCAL devices are queried — this process can only meaningfully
    gauge the chips it owns."""
    reg = registry or _global_registry
    if device_stats is None:
        from deeplearning4j_tpu.monitoring.memory import device_memory_stats
        device_stats = device_memory_stats()
    for dev, stats in device_stats.items():
        reg.gauge(DEVICE_MEMORY_SUPPORTED, labels={"device": dev},
                  help="1 when the backend exposes memory_stats()") \
           .set(0.0 if not stats else 1.0)
        if stats:
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit", "largest_free_block_bytes"):
                if key in stats:
                    reg.gauge(DEVICE_MEMORY_BYTES,
                              labels={"device": dev, "stat": key},
                              help="device memory from memory_stats()") \
                       .set(float(stats[key]))
        else:
            reg.gauge(DEVICE_MEMORY_BYTES,
                      labels={"device": dev, "stat": "bytes_in_use"},
                      help="device memory from memory_stats()").set(0.0)
    try:
        with open("/proc/self/statm") as f:
            rss_pages = int(f.read().split()[1])
        import os
        reg.gauge(HOST_RSS_BYTES, help="host process resident set size") \
           .set(rss_pages * os.sysconf("SC_PAGE_SIZE"))
    except Exception:   # noqa: BLE001 — non-Linux hosts
        pass
    return reg


def bootstrap_core_metrics(registry=None):
    """Make sure the core metric families exist (scrape targets must see
    stable series even before the first compile/transfer happens) and
    refresh the device-memory gauges. Called by the /metrics handler and
    by MetricsListener on construction."""
    reg = registry or _global_registry
    reg.counter(JIT_CACHE_MISSES,
                help="OpExecutioner.exec jit-cache misses")
    reg.histogram(JIT_COMPILE_SECONDS,
                  help="wall time of OpExecutioner.exec cache-miss "
                       "dispatches (trace+compile+first run)")
    reg.counter(OP_DISPATCHES, help="OpExecutioner.exec dispatches")
    reg.counter(TRANSFER_H2D_BYTES,
                help="bytes explicitly placed host-to-device")
    return collect_device_memory(reg)
