"""Shared on/off switch for the monitoring subsystem.

One module-level flag read by every instrumentation point in the repo:
the disabled fast path is a single attribute check (`STATE.enabled`),
no allocation, no lock — trainers stay exactly as fast as before when
nobody asked for metrics. Kept in its own module so registry.py and
tracing.py (and the instrumented call sites) share one source of truth
without import cycles.
"""
from __future__ import annotations


class _MonitoringState:
    __slots__ = ("enabled",)

    def __init__(self):
        self.enabled = False


STATE = _MonitoringState()
