"""Step-time attribution flight recorder.

Answers the question the per-op device trace cannot: for each training
step, where did the HOST wall time go — pulling the batch
(`data_next`), dispatching the jitted step (`dispatch`), running
listeners (`listeners`) — and how much of that was the host *blocked*
on the device (`host_blocked_ms`, from `runtime/pipeline.py`'s counted
syncs) or stalled in a jit cache-miss compile
(`runtime/executioner.py`)?

Mechanism: the span tracer (`monitoring/tracing.py`) already brackets
every phase of every fit loop; each completed span is forwarded here
(one dict lookup) and folded into the CURRENT step's accumulator. A
step-closing span ("train.listeners" in the trainer loops,
"sharded.dispatch" for the listener-free functional trainer) finalizes
the record into a bounded ring buffer. Wall time is measured
end-of-step to end-of-step, so `sum(phases) / wall` is a meaningful
coverage number (~1.0 when the loop is fully attributed; the gap is
un-spanned glue: array conversion, rng splits, group bookkeeping).

Zero-overhead when monitoring is disabled: spans don't record at all,
so nothing reaches the recorder — the trainers pay the same single
`STATE.enabled` branch as before.

Surfaces: `GET /steps` on the UI server, `recorder().summary()` /
`records()` programmatically, `dl4j.step.*` metrics, and the tail of
the ring embedded in OOM crash dumps (`util/crash_reporting.py`).
"""
from __future__ import annotations

import threading
import time
from collections import deque

from deeplearning4j_tpu.monitoring.state import STATE

# span name -> attributed phase. Only TOP-LEVEL step phases appear here:
# nested spans (listener.evaluate inside train.listeners) would double
# count the wall time their parent already covers, so they are tracked
# as separate "detail" keys that stay OUT of the coverage sum.
PHASE_BY_SPAN = {
    "fit.data_next": "data_next",
    "train.stage": "stage",
    "train.dispatch": "dispatch",
    "train.scan_dispatch": "dispatch",
    "parallel.dispatch": "dispatch",
    "parallel.scan_dispatch": "dispatch",
    "sharded.dispatch": "dispatch",
    # an EXPLICIT exchange span (a trainer that dispatches its gradient
    # exchange separately from the step, e.g. a parameter-server-style
    # loop). The overlapped bucketed exchange lives INSIDE the jitted
    # dispatch, so today this phase is usually empty per-host — the
    # fleet-level exchange exposure is instead DERIVED from cross-host
    # dispatch-phase skew (monitoring/stragglers.py).
    "train.exchange": "exchange",
    "multihost.exchange": "exchange",
    "train.listeners": "listeners",
}
DETAIL_BY_SPAN = {
    "listener.evaluate": "eval",
    "listener.checkpoint": "checkpoint",
}
#: spans whose completion closes the current step record. The trainer
#: loops all end a step with "train.listeners" (even when the listener
#: list is empty); the functional ShardedTrainer has no listener phase,
#: so its dispatch span is the closer.
STEP_END_SPANS = ("train.listeners", "sharded.dispatch")

#: phases that add up to (approximately) the step wall time
SUM_PHASES = ("data_next", "stage", "dispatch", "exchange", "listeners")

#: a gap larger than this between one step's end and the next step's
#: first span means the loop was IDLE in between (a later fit() call, a
#: notebook pause, inter-epoch eval) — wall is then anchored at the
#: first span instead of the previous step's end, so one record cannot
#: report an hours-long "step" that poisons the ring's percentiles and
#: coverage
_IDLE_GAP_NS = 1_000_000_000


def _percentile(sorted_vals, q):
    if not sorted_vals:
        return None
    import math
    pos = min(len(sorted_vals) - 1,
              max(0, int(math.ceil(q * len(sorted_vals)) - 1)))
    return sorted_vals[pos]


class StepRecorder:
    """Bounded ring buffer of per-step attribution records.

    A record:
        {"step": n, "wall_ms": w, "ts": unix_ts,
         "phases": {"data_next": ms, "dispatch": ms, "listeners": ms,
                    ...detail keys...},
         "host_blocked_ms": ms, "compile_count": c, "compile_ms": m}
    """

    def __init__(self, capacity=512):
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._ring = deque(maxlen=self.capacity)
        self._step = 0
        self._reset_acc()
        self._last_end_ns = None

    def _reset_acc(self):
        self._acc = {}
        self._acc_start_ns = None
        self._host_blocked_ms = 0.0
        self._compile_count = 0
        self._compile_ms = 0.0

    # -- feed points (hot path; called only when monitoring is ON) -------
    def on_span(self, name, dur_ms):
        phase = PHASE_BY_SPAN.get(name)
        if phase is None:
            detail = DETAIL_BY_SPAN.get(name)
            if detail is None:
                return
            with self._lock:
                self._mark_start_locked(dur_ms)
                self._acc[detail] = self._acc.get(detail, 0.0) + dur_ms
            return
        with self._lock:
            self._mark_start_locked(dur_ms)
            self._acc[phase] = self._acc.get(phase, 0.0) + dur_ms
            if name in STEP_END_SPANS:
                self._finalize_locked()

    def _mark_start_locked(self, dur_ms):
        # remember when this step's FIRST span began (spans report at
        # exit, so subtract the duration) — the idle-gap wall anchor
        if self._acc_start_ns is None:
            self._acc_start_ns = time.perf_counter_ns() - int(dur_ms * 1e6)

    def on_host_blocked(self, ms):
        with self._lock:
            self._host_blocked_ms += ms

    def on_compile(self, seconds):
        with self._lock:
            self._compile_count += 1
            self._compile_ms += seconds * 1e3

    def _finalize_locked(self):
        now_ns = time.perf_counter_ns()
        anchor = self._last_end_ns
        if anchor is None or (self._acc_start_ns is not None
                              and self._acc_start_ns - anchor > _IDLE_GAP_NS):
            anchor = self._acc_start_ns
        wall = None if anchor is None else (now_ns - anchor) / 1e6
        self._last_end_ns = now_ns
        self._step += 1
        rec = {
            "step": self._step,
            "wall_ms": wall,
            "ts": time.time(),
            "phases": dict(self._acc),
            "host_blocked_ms": self._host_blocked_ms,
            "compile_count": self._compile_count,
            "compile_ms": self._compile_ms,
        }
        self._ring.append(rec)
        self._reset_acc()
        # per-step metrics ride the same ON-state: one histogram observe
        # per phase per step, none of it reachable when disabled
        if STATE.enabled:
            from deeplearning4j_tpu.monitoring import registry as _reg
            reg = _reg.get_registry()
            if wall is not None:
                reg.histogram(_reg.STEP_WALL_MS,
                              help="end-to-end wall time per training "
                                   "step").observe(wall)
            for phase in SUM_PHASES:
                v = rec["phases"].get(phase)
                if v is not None:
                    reg.histogram(_reg.STEP_PHASE_MS,
                                  labels={"phase": phase},
                                  help="host wall time attributed to one "
                                       "step phase").observe(v)

    # -- read side --------------------------------------------------------
    def records(self, last=None):
        with self._lock:
            recs = list(self._ring)
        if last is None:
            return recs
        last = int(last)
        # recs[-0:] would be the WHOLE ring — a bound of 0 (or less)
        # means "no records", not "all of them"
        return recs[-last:] if last > 0 else []

    def summary(self):
        """Percentile roll-up over the ring: per-phase p50/p95/p99 + mean,
        wall percentiles, attribution coverage (sum of top-level phases /
        wall), and compile/host-blocked totals."""
        recs = self.records()
        out = {"count": len(recs), "capacity": self.capacity,
               "phases": {}, "wall_ms": None, "coverage": None,
               "host_blocked_ms_total": sum(r["host_blocked_ms"]
                                            for r in recs),
               "compile_count_total": sum(r["compile_count"]
                                          for r in recs),
               "compile_ms_total": sum(r["compile_ms"] for r in recs)}
        if not recs:
            return out
        walls = sorted(r["wall_ms"] for r in recs
                       if r["wall_ms"] is not None)
        if walls:
            out["wall_ms"] = {
                "mean": sum(walls) / len(walls),
                "p50": _percentile(walls, 0.50),
                "p95": _percentile(walls, 0.95),
                "p99": _percentile(walls, 0.99),
            }
        keys = sorted({k for r in recs for k in r["phases"]})
        for k in keys:
            vals = sorted(r["phases"][k] for r in recs if k in r["phases"])
            out["phases"][k] = {
                "mean": sum(vals) / len(vals),
                "p50": _percentile(vals, 0.50),
                "p95": _percentile(vals, 0.95),
                "p99": _percentile(vals, 0.99),
                "count": len(vals),
            }
        # coverage over steps that have a wall measurement (a step with
        # no spans at all has nothing to anchor wall on)
        covs = []
        for r in recs:
            if r["wall_ms"]:
                attributed = sum(r["phases"].get(p, 0.0)
                                 for p in SUM_PHASES)
                covs.append(attributed / r["wall_ms"])
        if covs:
            out["coverage"] = sum(covs) / len(covs)
        return out

    def compact_summary(self, tail=16):
        """Bounded, KV-publishable digest of the ring: per-phase
        p50/p99 (+mean/count), wall p50/p99, blocked/compile totals,
        and a short record tail (step, ts, wall, phases) so process 0
        can render per-host trace lanes. Everything is plain JSON
        numbers — publishing is serialization of values the recorder
        already holds, never a device touch."""
        s = self.summary()
        out = {"count": s["count"],
               "host_blocked_ms_total": round(s["host_blocked_ms_total"],
                                              3),
               "compile_count_total": s["compile_count_total"],
               "compile_ms_total": round(s["compile_ms_total"], 3),
               "wall_ms": None, "phases": {}}
        if s["wall_ms"]:
            out["wall_ms"] = {"p50": round(s["wall_ms"]["p50"], 3),
                              "p99": round(s["wall_ms"]["p99"], 3)}
        for k, v in s["phases"].items():
            out["phases"][k] = {"p50": round(v["p50"], 3),
                                "p99": round(v["p99"], 3),
                                "mean": round(v["mean"], 3),
                                "count": v["count"]}
        out["tail"] = [
            {"step": r["step"], "ts": r["ts"],
             "wall_ms": (None if r["wall_ms"] is None
                         else round(r["wall_ms"], 3)),
             "phases": {k: round(v, 3) for k, v in r["phases"].items()}}
            for r in self.records(last=tail)]
        return out

    def clear(self):
        with self._lock:
            self._ring.clear()
            self._step = 0
            self._reset_acc()
            self._last_end_ns = None

    def crash_lines(self, last=8):
        """Human-readable tail for crash dumps (never raises)."""
        try:
            lines = []
            s = self.summary()
            if not s["count"]:
                return ["  (no step records)"]
            if s["wall_ms"]:
                lines.append(
                    f"  wall_ms p50={s['wall_ms']['p50']:.2f} "
                    f"p95={s['wall_ms']['p95']:.2f} over {s['count']} steps")
            for k, v in s["phases"].items():
                lines.append(f"  {k}_ms p50={v['p50']:.2f} "
                             f"p95={v['p95']:.2f}")
            lines.append(f"  compiles={s['compile_count_total']} "
                         f"({s['compile_ms_total']:.1f} ms), host_blocked="
                         f"{s['host_blocked_ms_total']:.1f} ms")
            for r in self.records(last=last):
                ph = " ".join(f"{k}={v:.2f}"
                              for k, v in sorted(r["phases"].items()))
                wall = "?" if r["wall_ms"] is None else f"{r['wall_ms']:.2f}"
                lines.append(f"  step {r['step']}: wall={wall} ms  {ph}")
            return lines
        except Exception as e:  # noqa: BLE001 — crash dumps must not raise
            return [f"  (flight recorder unavailable: {e})"]


def _default_capacity():
    import os
    try:
        return max(16, int(os.environ.get("DL4J_STEP_RING", "512")))
    except ValueError:
        return 512


_global_recorder = StepRecorder(capacity=_default_capacity())


def recorder():
    """THE process-global flight recorder the span tracer feeds.
    Ring size comes from DL4J_STEP_RING (default 512)."""
    return _global_recorder
