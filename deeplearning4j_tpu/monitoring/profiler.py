"""On-demand XLA profiling sessions (ProfileSession).

The device-side complement of the host metrics/span layer: arm a
session, run training, and the next k steps are captured with
`jax.profiler.trace`; the resulting xplane.pb is decoded by
`optimize/xplane.py` (no TensorBoard dependency) into a per-op cost
table — self-time, category/FLOPs rollups, memory movers — published
three ways:

- programmatic: `session = profile_next_steps(3)` ... `session.report`
  (dict) / `session.render()` (text) / `last_report()`;
- HTTP: `POST /profile?steps=k` on the UI server arms one,
  `GET /profile` returns the latest report JSON (the dashboard's
  "Device profile" tab renders it);
- metrics: `dl4j.profile.*` (sessions, captured steps, device ms, and
  per-op gauges for the top ops).

Cost model: ZERO when disarmed — every trainer hook is one module-level
`ACTIVE is not None` branch (the `resilience/faults.py` pattern), so an
uninstrumented `fit()` pays a single pointer compare per step. While a
session IS armed, `jax.profiler` tracing costs whatever XLA charges for
the window (that's the point: profiling is a scoped decision, not an
always-on tax).

This subsumes the old `optimize.listeners.ProfilerListener` trace-window
duty: the listener remains as a thin compatibility shim that arms a
ProfileSession from its `iterationDone` cadence.
"""
from __future__ import annotations

import json
import os
import tempfile
import threading
import time

from deeplearning4j_tpu.monitoring import registry as _registry

__all__ = ["ProfileSession", "active_session", "last_report",
           "last_session", "profile_next_steps"]

#: the armed session, or None (the one-branch trainer fast path:
#: `if _prof.ACTIVE is not None: _prof.ACTIVE.step_start()`)
ACTIVE = None

_lock = threading.Lock()
_last_session = None


class ProfileSession:
    """One profiling window over k training steps.

    Lifecycle: armed → tracing → done (or failed). Trainers drive it
    through two hooks at each step boundary: `step_start()` (starts the
    jax.profiler trace on the first step after arming, so the window
    always covers WHOLE steps) and `step_end()` (counts captured steps;
    on the k-th, stops the trace, decodes it, and publishes the report).
    `finish()` force-closes a window the loop abandoned early (fit
    raised / iterator exhausted); re-arming via `profile_next_steps()`
    calls it on a still-tracing predecessor so `jax.profiler` is never
    double-started. A window that outlives one `fit()` simply keeps
    capturing the next trainer's steps — that is the contract ("the
    next k steps of whatever runs next")."""

    def __init__(self, steps=None, trace_dir=None, device_substr=None,
                 top=25, registry=None, keep_trace=None):
        if steps is None:   # DL4J_PROFILE_STEPS sets the default window
            try:
                steps = int(os.environ.get("DL4J_PROFILE_STEPS", "3"))
            except ValueError:
                steps = 3
        self.steps = max(1, int(steps))
        # the temp dir is created LAZILY in _begin(): an armed-but-
        # replaced (or never-run) session must not leak an empty
        # dl4j-profile-* directory per POST /profile
        self._own_trace_dir = trace_dir is None
        self.trace_dir = str(trace_dir) if trace_dir is not None else None
        # None → auto: prefer the TPU/GPU device plane, fall back to the
        # host-thread planes CPU traces use
        self.device_substr = device_substr
        self.top = int(top)
        self.registry = registry
        self.keep_trace = (not self._own_trace_dir if keep_trace is None
                           else bool(keep_trace))
        self.state = "armed"
        self.captured_steps = 0
        self.report = None
        self.error = None
        self._t_begin = None
        # serializes the armed→tracing→done/failed transitions: the
        # trainer thread (k-th step_end) and an HTTP re-arm thread
        # (profile_next_steps → finish) can both reach _end(); only one
        # may stop the trace and publish
        self._window_lock = threading.Lock()

    # -- trainer hooks (hot path only while armed) -----------------------
    def step_start(self):
        if self.state == "armed":
            self._begin()

    def step_end(self):
        if self.state != "tracing":
            return
        self.captured_steps += 1
        if self.captured_steps >= self.steps:
            self._end()

    # -- window control ---------------------------------------------------
    def begin(self):
        """Manually open the trace window (listener-driven mode —
        optimize.listeners.ProfilerListener; the armed/global mode uses
        step_start instead)."""
        if self.state == "armed":
            self._begin()
        return self

    def end(self):
        """Manually close the window: stop the trace, decode the xplane,
        publish the report/metrics."""
        if self.state == "tracing":
            self._end()
        return self

    def _begin(self):
        import jax
        with self._window_lock:
            if self.state != "armed":   # lost the race to another opener
                return
            try:
                if self.trace_dir is None:
                    self.trace_dir = tempfile.mkdtemp(
                        prefix="dl4j-profile-")
                jax.profiler.start_trace(self.trace_dir)
            except Exception as e:  # noqa: BLE001 — must not kill fit
                self.state, self.error = "failed", f"start_trace: {e}"
            else:
                self._t_begin = time.perf_counter()
                self.state = "tracing"
                return
        _deactivate(self)
        if self._own_trace_dir and not self.keep_trace:
            self._cleanup_trace()

    def _end(self):
        import jax
        with self._window_lock:
            if self.state != "tracing":   # another thread closed it first
                return
            wall_ms = (time.perf_counter() - self._t_begin) * 1e3 \
                if self._t_begin else None
            try:
                # flush queued device work so the trace contains the
                # whole k-th step, then stop
                from deeplearning4j_tpu.runtime.executioner import \
                    OpExecutioner
                OpExecutioner.getInstance().commit()
                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                self.state, self.error = "failed", f"stop_trace: {e}"
            else:
                try:
                    self.report = self._build_report(wall_ms)
                    self.state = "done"
                    self._publish_metrics()
                except Exception as e:  # noqa: BLE001 — a decode bug
                    self.state = "failed"        # must not kill fit
                    self.error = f"decode: {e}"
        _deactivate(self)
        if self._own_trace_dir and not self.keep_trace:
            self._cleanup_trace()

    def finish(self):
        """Force-close: stop a still-open trace window and build the
        report from however many steps were captured. No-op unless
        armed or tracing."""
        with self._window_lock:
            never_ran = self.state == "armed"
            if never_ran:
                # never saw a step: nothing to decode
                self.state = "failed"
                self.error = "no steps ran while armed"
        if never_ran:
            _deactivate(self)
            return
        self._end()   # no-op unless tracing (checked under the lock)

    # -- decoding ---------------------------------------------------------
    def _build_report(self, wall_ms):
        from deeplearning4j_tpu.optimize import xplane
        if self.device_substr is not None:
            candidates = [self.device_substr]
        else:
            candidates = ["TPU", "GPU", ""]
        rows, used, lines = [], "", []
        for sub in candidates:
            # one decode per candidate plane; both tables derive from it
            lines = xplane.collect_lines(self.trace_dir,
                                         device_substr=sub)
            rows = xplane.op_table(self.trace_dir, lines=lines)
            if rows:
                used = sub
                break
        memory = xplane.memory_breakdown(self.trace_dir, lines=lines)
        report = {
            "steps": self.captured_steps,
            "wall_ms": wall_ms,
            "trace_dir": self.trace_dir if self.keep_trace else None,
            "device_substr": used,
            "device_self_ms": sum(r["self_ms"] for r in rows),
            "op_count": len(rows),
            "ops": rows[:self.top],
            "categories": xplane.category_rollup(rows),
            "memory": [{"name": n, "total_ms": ms, "bytes_accessed": b,
                        "gb_per_s": gbps}
                       for n, ms, b, gbps in memory[:self.top]],
            "ts": time.time(),
        }
        return report

    def _publish_metrics(self):
        reg = self.registry if self.registry is not None \
            else _registry.get_registry()
        reg.counter(_registry.PROFILE_SESSIONS,
                    help="completed ProfileSession windows").inc()
        reg.gauge(_registry.PROFILE_CAPTURED_STEPS,
                  help="steps captured by the last profile window") \
           .set(self.captured_steps)
        reg.gauge(_registry.PROFILE_DEVICE_MS,
                  help="device self time decoded from the last profile "
                       "window").set(self.report["device_self_ms"])
        for r in self.report["ops"][:10]:
            labels = {"op": r["name"][:80]}
            reg.gauge(_registry.PROFILE_OP_MS, labels=labels,
                      help="per-op self ms from the last profile window") \
               .set(r["self_ms"])
            reg.gauge(_registry.PROFILE_OP_COUNT, labels=labels,
                      help="per-op occurrences in the last profile "
                           "window").set(r["count"])

    def _cleanup_trace(self):
        if self.trace_dir is None:
            return
        import shutil
        try:
            shutil.rmtree(self.trace_dir, ignore_errors=True)
        except Exception:  # noqa: BLE001
            pass

    # -- presentation -----------------------------------------------------
    def render(self, top=None):
        """Text report (top-K ops + category rollup + memory movers)."""
        if self.report is None:
            return f"<ProfileSession {self.state}" + \
                (f": {self.error}>" if self.error else ">")
        from deeplearning4j_tpu.optimize import xplane
        mem = [(m["name"], m["total_ms"], m["bytes_accessed"],
                m["gb_per_s"]) for m in self.report["memory"]]
        head = (f"ProfileSession: {self.report['steps']} steps, "
                f"{self.report['wall_ms']:.1f} ms wall\n"
                if self.report.get("wall_ms") else "")
        return head + xplane.render_report(self.report["ops"], mem,
                                           top=top or self.top)

    def to_json(self):
        return json.dumps({"state": self.state, "error": self.error,
                           "report": self.report})


def _deactivate(session):
    global ACTIVE, _last_session
    with _lock:
        if ACTIVE is session:
            ACTIVE = None
        # a session that failed before its window ever OPENED carries no
        # report — don't let it clobber a real one in last_report() /
        # GET /profile (e.g. a ProfilerListener whose start_trace lost to
        # an already-open global window)
        if (session._t_begin is None and session.report is None
                and _last_session is not None
                and _last_session.report is not None):
            return
        _last_session = session


def profile_next_steps(steps=None, **kwargs):
    """Arm a ProfileSession over the next `steps` training steps of
    WHATEVER trainer runs next (MultiLayerNetwork/ComputationGraph fit,
    ParallelWrapper, ShardedTrainer). Returns the session; its `.report`
    appears once the window closes. Re-arming replaces a still-armed
    session (an in-flight tracing window is finished first so
    jax.profiler isn't double-started)."""
    global ACTIVE
    with _lock:
        prev = ACTIVE
    if prev is not None:
        # unconditionally: a still-"armed" predecessor may be racing a
        # trainer thread through step_start — finish() marks it failed
        # under its window lock, so that in-flight _begin becomes a
        # no-op instead of opening a trace nothing will ever close
        prev.finish()
    session = ProfileSession(steps=steps, **kwargs)
    with _lock:
        ACTIVE = session
    return session


def active_session():
    return ACTIVE


def last_session():
    """The most recently completed (or failed) session."""
    with _lock:
        return _last_session


def last_report():
    s = last_session()
    return None if s is None else s.report


def status():
    """JSON-able status for GET /profile: the armed session (if any) and
    the last completed report."""
    with _lock:
        active, last = ACTIVE, _last_session
    out = {"active": None, "last": None}
    if active is not None:
        out["active"] = {"state": active.state, "steps": active.steps,
                         "captured_steps": active.captured_steps}
    if last is not None:
        out["last"] = {"state": last.state, "error": last.error,
                       "report": last.report}
    return out
