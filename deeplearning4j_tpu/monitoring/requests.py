"""Request-scoped tracing: one bounded lifecycle timeline per served
request, with trace ids that link everything else together.

Per-process aggregates (the metrics registry) answer "is p99 bad"; this
module answers "WHICH request was slow and WHERE did its time go" —
queue vs prefill vs superstep blocks vs crash-replay. Every
`GenerationServer` and `ParallelInference` request gets a trace id at
admission; lifecycle events (enqueue, admit/prefill, each decode
block's dispatch+delivery, grow, replay, retire/shed/timeout) append to
a bounded per-request timeline. Completed timelines land in a bounded
recent ring; `GET /requests` serves the ring + the in-flight set and
`GET /requests/<id>` one timeline. The latency histograms carry
EXEMPLAR trace ids (`Histogram.observe(v, trace_id=...)`), so "p99 is
bad" on `/metrics` clicks through to an actual slow-request timeline
here. `merged_chrome_trace()` renders every timeline as its own lane
merged with the host-side `Tracer` spans — one Perfetto-loadable file
showing spans AND requests.

Cost contract (lint-enforced by scripts/check_fastpath.py):

- **Disabled path**: `start()` is ONE flag check returning None; every
  instrumented call site holds a `timeline is None` (or enabled-guard)
  branch and nothing allocates. Same discipline as `tracing.span`.
- **Hot path**: an `event()` append is pure host-side bookkeeping — a
  perf-counter read and a dict append onto a bounded list. In the
  generation decode loop the appends ride the EXISTING `_deliver_block`
  / `_fetch_tokens` host boundary (the fetched token block is already
  host data), so request tracing adds ZERO device syncs; the fast-path
  sync lint walks this module to prove no materialization hides here.
- **Bounded everywhere**: per-timeline events cap at `max_events`
  (overflow counts on `dropped`, never grows), the recent ring at
  `DL4J_REQUEST_RING` (default 256), and the active set is keyed by
  live requests only.
"""
from __future__ import annotations

import itertools
import os
import threading
import time
from collections import deque

from deeplearning4j_tpu.monitoring.state import STATE

__all__ = ["RequestTimeline", "RequestLog", "log", "request_log",
           "start", "merged_chrome_trace"]


class RequestTimeline:
    """Bounded event list for ONE request. Appends are GIL-atomic list
    ops (same trade as Counter.inc: a torn read under extreme
    contention is an acceptable metrics trade, never a crash)."""

    __slots__ = ("trace_id", "kind", "meta", "status", "events",
                 "dropped", "max_events", "t0_ns", "ts", "ts_end",
                 "_log")

    def __init__(self, log, trace_id, kind, meta=None, max_events=256):
        self._log = log
        self.trace_id = trace_id
        self.kind = kind
        self.meta = dict(meta) if meta else {}
        self.status = None            # None while in flight
        self.events = []
        self.dropped = 0
        self.max_events = int(max_events)
        self.t0_ns = time.perf_counter_ns()
        self.ts = time.time()
        self.ts_end = None

    def event(self, name, **fields):
        """Append one lifecycle event (host-side only: a perf-counter
        read + a dict append; MUST stay free of device access — the
        fast-path sync lint walks this). A finished timeline is
        immutable: a worker racing the client's timeout (claim vs
        cancel) must not append a 'dispatch' after the terminal
        event."""
        if self.status is not None:
            return self
        if len(self.events) >= self.max_events:
            self.dropped += 1
            return self
        ev = {"t_ms": round((time.perf_counter_ns() - self.t0_ns) / 1e6,
                            3),
              "event": name}
        if fields:
            ev.update(fields)
        self.events.append(ev)
        return self

    def finish(self, status="ok"):
        """Terminal: record the status, move from the active set to the
        recent ring. Idempotent — the first status wins (a request must
        never finish twice with different verdicts)."""
        if self.status is not None:
            return self
        self.status = str(status)
        self.ts_end = time.time()
        if self._log is not None:
            self._log._retire(self)
        return self

    def snapshot(self):
        out = {"trace_id": self.trace_id, "kind": self.kind,
               "status": self.status, "ts": self.ts,
               "ts_end": self.ts_end, "events": list(self.events)}
        if self.meta:
            out["meta"] = dict(self.meta)
        if self.dropped:
            out["dropped_events"] = self.dropped
        return out


class RequestLog:
    """Process-global request-timeline store: the in-flight set plus a
    bounded ring of recently finished timelines."""

    def __init__(self, capacity=None):
        if capacity is None:
            try:
                capacity = max(16, int(os.environ.get(
                    "DL4J_REQUEST_RING", "256")))
            except ValueError:
                capacity = 256
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._active = {}                     # trace_id -> timeline
        self._ring = deque(maxlen=self.capacity)
        self._seq = itertools.count(1)
        self._lanes = {}                      # trace_id -> chrome lane id
        self._lane_seq = itertools.count(1_000_000)
        self._pid_tag = f"{os.getpid():x}"

    def start(self, kind, meta=None, trace_id=None, max_events=256):
        if trace_id is None:
            trace_id = f"{kind[:3]}-{self._pid_tag}-{next(self._seq):06x}"
        tl = RequestTimeline(self, trace_id, kind, meta=meta,
                             max_events=max_events)
        with self._lock:
            self._active[trace_id] = tl
        return tl

    def _retire(self, tl):
        with self._lock:
            self._active.pop(tl.trace_id, None)
            self._ring.append(tl)

    def get(self, trace_id):
        """Timeline by trace id — in-flight first, then the recent
        ring; None when it aged out (or never existed)."""
        with self._lock:
            tl = self._active.get(trace_id)
            if tl is None:
                for cand in reversed(self._ring):
                    if cand.trace_id == trace_id:
                        tl = cand
                        break
        return tl

    def snapshot(self, last=32):
        """The `GET /requests` payload: in-flight timelines plus the
        `last` most recent finished ones (newest last)."""
        with self._lock:
            active = list(self._active.values())
            recent = list(self._ring)
        last = int(last)
        recent = recent[-last:] if last > 0 else []
        return {"active": [t.snapshot() for t in active],
                "recent": [t.snapshot() for t in recent],
                "ring_capacity": self.capacity}

    def clear(self):
        with self._lock:
            self._active.clear()
            self._ring.clear()
            self._lanes.clear()

    # -- chrome-trace export ----------------------------------------------
    def _lane(self, trace_id):
        lane = self._lanes.get(trace_id)
        if lane is None:
            # request lanes live far above real thread ids so they never
            # collide with the span tracer's tid space; the counter is
            # monotonic so an evicted lane id is never reissued
            lane = next(self._lane_seq)
            self._lanes[trace_id] = lane
        return lane

    def chrome_events(self, epoch_ns=None):
        """Chrome trace events rendering each timeline as its own lane:
        a thread-name metadata event per request, one "X" slice per
        stage (event k → event k+1), an instant for the terminal event.
        `epoch_ns` aligns timestamps with a Tracer's timebase."""
        pid = os.getpid()
        out = []
        with self._lock:
            timelines = list(self._active.values()) + list(self._ring)
            # lane ids stay stable across exports but never outlive
            # their timelines — the map is bounded by active + ring
            live = {tl.trace_id for tl in timelines}
            for stale in [t for t in self._lanes if t not in live]:
                del self._lanes[stale]
            lanes = {tl.trace_id: self._lane(tl.trace_id)
                     for tl in timelines}
        for tl in timelines:
            tid = lanes[tl.trace_id]
            base_us = (tl.t0_ns - (epoch_ns if epoch_ns is not None
                                   else tl.t0_ns)) / 1e3
            out.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid,
                        "args": {"name": f"req {tl.trace_id} "
                                         f"({tl.kind})"}})
            evs = list(tl.events)
            for i, ev in enumerate(evs):
                ts = base_us + ev["t_ms"] * 1e3
                args = {k: v for k, v in ev.items()
                        if k not in ("t_ms", "event")}
                args["trace_id"] = tl.trace_id
                if i + 1 < len(evs):
                    dur = (evs[i + 1]["t_ms"] - ev["t_ms"]) * 1e3
                    out.append({"ph": "X", "cat": "request",
                                "name": ev["event"], "ts": ts,
                                "dur": max(dur, 0.0), "pid": pid,
                                "tid": tid, "args": args})
                else:
                    out.append({"ph": "i", "cat": "request",
                                "name": ev["event"], "ts": ts, "s": "t",
                                "pid": pid, "tid": tid, "args": args})
        return out


_global_log = RequestLog()


def log():
    """THE process-global request log (`GET /requests` serves it)."""
    return _global_log


#: package-namespace alias (`monitoring.request_log()` reads better
#: than `monitoring.log()` next to the metrics/span accessors)
request_log = log


def start(kind, meta=None, trace_id=None, max_events=256):
    """THE instrumentation entry point: a new request timeline, or None
    when monitoring is disabled — call sites keep the one-branch
    discipline by checking `timeline is not None` before every append
    (same contract as `tracing.span`)."""
    if not STATE.enabled:
        return None
    return _global_log.start(kind, meta=meta, trace_id=trace_id,
                             max_events=max_events)


def merged_chrome_trace():
    """One Chrome trace-event document merging the host-side span
    tracer (its own per-thread lanes, now with process metadata) with
    every request timeline as a dedicated lane — and, when a peer
    coordinator is active, one named training lane per HOST from the
    published step timelines (monitoring/stragglers.py), so cross-host
    step skew is visually obvious next to the local phases. Load in
    Perfetto."""
    import sys
    from deeplearning4j_tpu.monitoring.tracing import get_tracer
    tracer = get_tracer()
    doc = tracer.to_chrome_trace()
    events = list(doc["traceEvents"]) + \
        _global_log.chrome_events(epoch_ns=tracer.epoch_ns)
    # sys.modules, never a fresh import: a trace export must not pull
    # the parallel stack (and jax.distributed with it) into a process
    # that never used it
    coord_mod = sys.modules.get("deeplearning4j_tpu.parallel.coordination")
    coord = getattr(coord_mod, "ACTIVE", None) if coord_mod else None
    if coord is not None:
        try:
            from deeplearning4j_tpu.monitoring import stragglers as _sg
            events += _sg.chrome_events(coord, epoch_ns=tracer.epoch_ns)
        except Exception:  # noqa: BLE001 — lanes are best-effort
            pass
    doc["traceEvents"] = events
    return doc
