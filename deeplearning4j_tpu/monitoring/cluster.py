"""Cluster metrics plane: per-host metric snapshots over the
coordination KV, aggregated and served from process 0.

In a multi-host run each process's MetricsRegistry is an island —
`GET /metrics` on process 0 shows one host of an N-host job. This
module makes the fleet visible without any new collectives or syncs:

- **Publish** — at every coordination SYNC POINT (the guardian-flush
  cadence `parallel/coordination.py` already piggybacks on), each
  process with monitoring enabled writes ONE compact JSON snapshot of
  its registry to the KV store under `metrics/<pid>` (overwrite-
  allowed: exactly one bounded key per process, the PR 7 reap
  discipline taken to its fixed-point — nothing to reap). Publishing
  is host-side serialization of numbers the registry already holds;
  the train step itself is untouched.
- **Serve** — process 0's `GET /metrics` renders every host's series
  with a `host="<pid>"` label plus CLUSTER AGGREGATES under
  `host="cluster"` (counters and histogram count/sum summed across
  hosts; gauges stay per-host — summing occupancies would lie), and
  `dl4j.cluster.snapshot_age_seconds{host=...}` says how stale each
  host's view is (max over hosts rides `host="cluster"`: one wedged
  publisher is visible at a glance). `GET /health`'s "distributed"
  section carries the same per-host meta (step, steps/s, exchange
  bytes, age).

Zero-cost discipline: everything here is reached either from a sync
point (bounded cadence, behind `_mon.enabled()`) or from an endpoint
(pull). No hot path imports this module.
"""
from __future__ import annotations

import json
import time

from deeplearning4j_tpu.monitoring import registry as _registry

__all__ = ["compact_snapshot", "publish", "gather", "health_meta",
           "cluster_prometheus_text"]

#: KV key prefix (under the coordinator's namespace)
KEY_PREFIX = "metrics/"


def compact_snapshot(registry=None):
    """JSON-compact registry dump for the KV wire: counters/gauges keep
    their value, histograms shrink to count/sum/p50/p99 (quantiles
    cannot aggregate across hosts anyway — they serve per-host)."""
    reg = registry or _registry.get_registry()
    metrics = {}
    for name, entries in reg.snapshot().items():
        out = []
        for e in entries:
            rec = {"labels": e["labels"], "kind": e["kind"]}
            if e["kind"] == "histogram":
                rec["count"] = e["count"]
                rec["sum"] = e["sum"]
                rec["p50"] = e["p50"]
                rec["p99"] = e["p99"]
            else:
                rec["value"] = e["value"]
            out.append(rec)
        metrics[name] = out
    return metrics


def publish(coordinator, registry=None, extra=None):
    """Write this process's snapshot to `metrics/<pid>` (one bounded,
    overwritten key). Called from the coordinator's sync point behind
    the enabled-guard; best-effort — a full KV store must never fail a
    training step."""
    snap = {"t": time.time(), "step": coordinator.step,
            "metrics": compact_snapshot(registry)}
    if extra:
        snap.update(extra)
    coordinator.publish(f"{KEY_PREFIX}{coordinator.process_id}",
                        json.dumps(snap), overwrite=True)
    return snap


def gather(coordinator):
    """{pid: published snapshot} for every host that has published one
    (this process included when it has)."""
    out = {}
    for suffix, v in coordinator.fetch_dir(KEY_PREFIX):
        try:
            out[int(suffix)] = json.loads(v)
        except (ValueError, TypeError):
            continue
    return out


def health_meta(coordinator, snaps=None):
    """The `GET /health` cluster section: per-host snapshot age, step,
    steps/s and exchange bytes, plus the max age (the wedged-publisher
    tell). Never raises — health must always answer."""
    try:
        snaps = gather(coordinator) if snaps is None else snaps
    except Exception:  # noqa: BLE001 — KV service down
        return None
    now = time.time()
    hosts, ages = {}, []
    for pid, snap in sorted(snaps.items()):
        age = round(max(0.0, now - snap.get("t", now)), 3)
        ages.append(age)
        hosts[str(pid)] = {
            "snapshot_age_s": age,
            "step": snap.get("step"),
            "steps_per_s": snap.get("steps_per_s"),
            "exchange_bytes": snap.get("exchange_bytes"),
        }
    return {"hosts": hosts,
            "max_snapshot_age_s": max(ages) if ages else None,
            "published": len(hosts)}


def _merge_host(families, pid, metrics):
    for name, entries in metrics.items():
        fam = families.setdefault(name, {"kind": entries[0]["kind"]
                                         if entries else "gauge",
                                         "series": []})
        for e in entries:
            labels = dict(e["labels"])
            labels["host"] = str(pid)
            fam["series"].append((labels, e))


def _aggregate(families):
    """host="cluster" series: counters and histogram count/sum summed
    across hosts per distinct non-host label set. Gauges don't
    aggregate (summing a fill ratio across hosts is a lie); their
    fleet view is the per-host series themselves."""
    for fam in families.values():
        if fam["kind"] == "counter":
            sums = {}
            for labels, e in fam["series"]:
                key = tuple(sorted((k, v) for k, v in labels.items()
                                   if k != "host"))
                sums[key] = sums.get(key, 0) + e.get("value", 0)
            for key, total in sorted(sums.items()):
                labels = dict(key)
                labels["host"] = "cluster"
                fam["series"].append((labels, {"kind": "counter",
                                               "value": total}))
        elif fam["kind"] == "histogram":
            sums = {}
            for labels, e in fam["series"]:
                key = tuple(sorted((k, v) for k, v in labels.items()
                                   if k != "host"))
                c, s = sums.get(key, (0, 0.0))
                sums[key] = (c + e.get("count", 0), s + e.get("sum", 0.0))
            for key, (c, s) in sorted(sums.items()):
                labels = dict(key)
                labels["host"] = "cluster"
                fam["series"].append((labels, {"kind": "histogram",
                                               "count": c, "sum": s,
                                               "p50": None,
                                               "p99": None}))


def cluster_prometheus_text(coordinator, registry=None):
    """The process-0 `/metrics` body in a multi-host run: every host's
    series labeled `host="<pid>"` (this process rendered LIVE from its
    own registry, peers from their last published snapshots), cluster
    aggregates under `host="cluster"`, and the per-host snapshot-age
    gauge. Output is the same strict exposition format the local
    renderer guarantees — one TYPE header per family, escaped labels,
    `+Inf`/`NaN` spellings."""
    reg = registry or _registry.get_registry()
    me = coordinator.process_id
    snaps = gather(coordinator)
    snaps[me] = {"t": time.time(), "metrics": compact_snapshot(reg)}
    families = {}
    for pid, snap in sorted(snaps.items()):
        _merge_host(families, pid, snap.get("metrics", {}))
    _aggregate(families)
    now = time.time()
    age_fam = families.setdefault(
        _registry.CLUSTER_SNAPSHOT_AGE, {"kind": "gauge", "series": []})
    ages = []
    for pid, snap in sorted(snaps.items()):
        age = max(0.0, now - snap.get("t", now))
        ages.append(age)
        age_fam["series"].append(({"host": str(pid)},
                                  {"kind": "gauge", "value": age}))
    if ages:
        age_fam["series"].append(({"host": "cluster"},
                                  {"kind": "gauge", "value": max(ages)}))
    helps = dict(reg.help_texts())
    helps.setdefault(_registry.CLUSTER_SNAPSHOT_AGE,
                     "age of each host's published metrics snapshot "
                     "(host=cluster is the max)")
    lines = []
    for name in sorted(families):
        fam = families[name]
        pname = _registry._prom_name(name)
        # header + sample rendering are the registry's own helpers —
        # escaping, ±Inf/NaN spellings and the summary line shapes stay
        # one rule for the local and the cluster scrape alike
        _registry._render_family_header(lines, pname, fam["kind"],
                                        helps.get(name))
        for labels, e in fam["series"]:
            rec = dict(e)
            if fam["kind"] == "histogram":
                # the compact KV wire carries p50/p99 only (quantiles
                # cannot aggregate across hosts; cluster rows are None)
                rec["quantiles"] = [("0.5", e.get("p50")),
                                    ("0.99", e.get("p99"))]
            _registry._render_sample_lines(lines, pname, fam["kind"],
                                           sorted(labels.items()), rec)
    return "\n".join(lines) + "\n"
