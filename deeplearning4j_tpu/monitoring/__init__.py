"""Unified host-side metrics + span tracing (the monitoring subsystem).

Dependency-free, disabled by default, and wired through the trainers
(`nn/multilayer.py`, `nn/graph.py`), the parallel stack
(`parallel/wrapper.py`, `parallel/sharded_trainer.py`,
`parallel/inference.py`), the executioner (`runtime/executioner.py`),
and the dashboard (`ui/server.py` serves `GET /metrics` in Prometheus
text format and a live metrics tab).

Quick start (one line at each end):

    net.setListeners(MetricsListener())          # optimize/listeners.py
    UIServer.getInstance().start()               # GET /metrics

or explicitly:

    from deeplearning4j_tpu import monitoring
    monitoring.enable()
    ... fit / serve ...
    monitoring.export_chrome_trace("/tmp/fit_trace.json")  # Perfetto
    print(monitoring.get_registry().prometheus_text())

Scope split across the repo's three observability layers:
- monitoring (this package) — HOST-side: where did the step's wall time
  go (data-iter / stage / dispatch / listeners / eval / checkpoint
  spans), jit compile events, transfer bytes, the step-time attribution
  flight recorder (`steps.py`, `GET /steps`), and device memory
  telemetry + OOM forensics (`memory.py`);
- `profiler.ProfileSession` + `optimize/xplane.py` — DEVICE-side: an
  on-demand jax.profiler window over the next k steps decoded to a
  per-op self-time/FLOPs/bytes table (`profile_next_steps(k)` /
  `POST /profile?steps=k`; subsumes the old ProfilerListener window);
- `ui/stats.StatsListener` — LEARNING diagnostics: score curves, update
  ratios, activation histograms.
"""
from __future__ import annotations

from deeplearning4j_tpu.monitoring.state import STATE
from deeplearning4j_tpu.monitoring import cluster  # noqa: F401
from deeplearning4j_tpu.monitoring import memory  # noqa: F401
from deeplearning4j_tpu.monitoring import profiler  # noqa: F401
from deeplearning4j_tpu.monitoring import requests  # noqa: F401
from deeplearning4j_tpu.monitoring import events  # noqa: F401
from deeplearning4j_tpu.monitoring import slo  # noqa: F401
from deeplearning4j_tpu.monitoring import steps  # noqa: F401
from deeplearning4j_tpu.monitoring import stragglers  # noqa: F401
from deeplearning4j_tpu.monitoring.requests import (  # noqa: F401
    RequestLog, RequestTimeline, merged_chrome_trace, request_log)
from deeplearning4j_tpu.monitoring.slo import (  # noqa: F401
    LatencyObjective, RatioObjective, SloTracker, StepTimeObjective,
    StragglerObjective, ThroughputObjective, standard_objectives)
from deeplearning4j_tpu.monitoring.memory import (  # noqa: F401
    MemoryMonitor)
from deeplearning4j_tpu.monitoring.profiler import (  # noqa: F401
    ProfileSession, last_report, profile_next_steps)
from deeplearning4j_tpu.monitoring.steps import (  # noqa: F401
    StepRecorder, recorder as step_recorder)
from deeplearning4j_tpu.monitoring.registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry,
    JIT_CACHE_MISSES, JIT_COMPILE_SECONDS, OP_DISPATCHES,
    JIT_PERSISTENT_HITS, JIT_PERSISTENT_MISSES,
    JIT_PERSISTENT_REQUESTS,
    EXEC_COMPILES, EXEC_COMPILE_SECONDS, EXEC_DISK_HITS,
    EXEC_DESERIALIZE_FAILURES, EXEC_SERIALIZE_FAILURES,
    EXEC_FLOPS, EXEC_BYTES_ACCESSED,
    SERVING_ROWS, SERVING_PADDED_ROWS, SERVING_BUCKET_OCCUPANCY,
    SERVING_SPLITS, SERVING_STAGED_BUFFERS, SERVING_STAGING_OCCUPANCY,
    SERVING_AOT_FALLBACKS,
    TRANSFER_H2D_BYTES, DEVICE_MEMORY_BYTES, DEVICE_MEMORY_SUPPORTED,
    HOST_RSS_BYTES,
    RESILIENCE_RETRIES, RESILIENCE_BACKOFF_SECONDS,
    RESILIENCE_BREAKER_TRIPS, RESILIENCE_FAULTS_INJECTED,
    RESILIENCE_BATCHES_SKIPPED, RESILIENCE_CHECKPOINT_SAVES,
    RESILIENCE_RESUMES, RESILIENCE_RESUME_STEP,
    RESILIENCE_INFERENCE_SHED, RESILIENCE_INFERENCE_TIMEOUTS,
    RESILIENCE_COLLECTOR_RESTARTS, RESILIENCE_CKPT_ORPHANS_REMOVED,
    RESILIENCE_CKPT_FALLBACKS,
    GUARDIAN_CHECKS, GUARDIAN_SKIPPED_UPDATES, GUARDIAN_LR_RETRIES,
    GUARDIAN_ROLLBACKS, GUARDIAN_SAVES_GATED, GUARDIAN_LAST_GOOD_STEP,
    WATCHDOG_STALLS, WATCHDOG_BEAT_AGE_SECONDS, WATCHDOG_DUMPS,
    DIST_PEERS, DIST_PEER_LOST, DIST_PREEMPTIONS,
    DIST_BARRIER_TIMEOUTS, DIST_ENCODED_BYTES, DIST_RESIDUAL_NORM,
    DIST_ACCUM_MICROBATCHES, DIST_EXCHANGE_BUCKETS, DIST_BUCKET_BYTES,
    DIST_EXPOSED_EXCHANGE_MS, DIST_ENCODER_MIGRATIONS,
    DIST_REFORMS_AGREED, DIST_REFORMS, DIST_REFORM_MS, DIST_WIRE_BYTES,
    DIST_STRAGGLER_RATIO, DIST_STRAGGLER_SKEW_MS,
    PIPELINE_SYNCS, PIPELINE_HOST_BLOCKED_MS, PIPELINE_PREFETCH_DEPTH,
    PIPELINE_STAGED_BATCHES,
    PROFILE_SESSIONS, PROFILE_CAPTURED_STEPS, PROFILE_DEVICE_MS,
    PROFILE_OP_MS, PROFILE_OP_COUNT,
    STEP_WALL_MS, STEP_PHASE_MS,
    MODEL_PARAMS_BYTES, MODEL_OPT_STATE_BYTES, MODEL_LAYER_STATE_BYTES,
    GEN_TOKENS, GEN_ACTIVE_SLOTS, GEN_ADMISSIONS, GEN_RETIREMENTS,
    GEN_PREFILL_MS, GEN_PER_TOKEN_MS, GEN_REPLAYS, GEN_RESTARTS,
    GEN_DEGRADATIONS, GEN_SUPERSTEPS, GEN_TOKENS_PER_DISPATCH,
    GEN_FETCH_OVERLAP_MS, GEN_DRAFT_ACCEPTS, GEN_DRAFT_REJECTS,
    GEN_PAGES_ACTIVE, GEN_PAGES_SHARED, GEN_PAGE_EVICTIONS,
    GEN_PREFIX_HITS,
    FLEET_ROUTED, FLEET_FAILOVERS, FLEET_REPLACEMENTS, FLEET_HEALTHY,
    FLEET_DESIRED_REPLICAS,
    QUANT_INT8_LAYERS, QUANT_CALIBRATIONS, QUANT_DEQUANT_FALLBACKS,
    QUANT_ACTIVATION_BYTES,
    INFERENCE_REQUEST_MS, SLO_BREACHES, SLO_BURN_RATE, SLO_BREACHED,
    EVENTS_EMITTED, EVENTS_DROPPED, INCIDENTS_OPEN, INCIDENTS_RESOLVED,
    CLUSTER_SNAPSHOT_AGE,
    bootstrap_core_metrics, collect_device_memory, get_registry,
    record_transfer)
from deeplearning4j_tpu.monitoring.tracing import (  # noqa: F401
    NULL_SPAN, Span, Tracer, export_chrome_trace, get_tracer, span,
    traced_iter)

__all__ = [
    "enable", "disable", "enabled", "span", "traced_iter",
    "export_chrome_trace", "get_tracer", "get_registry",
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "Tracer",
    "bootstrap_core_metrics", "collect_device_memory", "record_transfer",
    "memory", "profiler", "steps",
    "MemoryMonitor", "ProfileSession", "StepRecorder",
    "last_report", "profile_next_steps", "step_recorder",
    "PROFILE_SESSIONS", "PROFILE_CAPTURED_STEPS", "PROFILE_DEVICE_MS",
    "PROFILE_OP_MS", "PROFILE_OP_COUNT",
    "STEP_WALL_MS", "STEP_PHASE_MS",
    "MODEL_PARAMS_BYTES", "MODEL_OPT_STATE_BYTES",
    "MODEL_LAYER_STATE_BYTES",
    "JIT_CACHE_MISSES", "JIT_COMPILE_SECONDS", "OP_DISPATCHES",
    "JIT_PERSISTENT_HITS", "JIT_PERSISTENT_MISSES",
    "JIT_PERSISTENT_REQUESTS",
    "EXEC_COMPILES", "EXEC_COMPILE_SECONDS", "EXEC_DISK_HITS",
    "EXEC_DESERIALIZE_FAILURES", "EXEC_SERIALIZE_FAILURES",
    "EXEC_FLOPS", "EXEC_BYTES_ACCESSED",
    "SERVING_ROWS", "SERVING_PADDED_ROWS", "SERVING_BUCKET_OCCUPANCY",
    "SERVING_SPLITS", "SERVING_STAGED_BUFFERS",
    "SERVING_STAGING_OCCUPANCY", "SERVING_AOT_FALLBACKS",
    "TRANSFER_H2D_BYTES", "DEVICE_MEMORY_BYTES",
    "DEVICE_MEMORY_SUPPORTED", "HOST_RSS_BYTES",
    "RESILIENCE_RETRIES", "RESILIENCE_BACKOFF_SECONDS",
    "RESILIENCE_BREAKER_TRIPS", "RESILIENCE_FAULTS_INJECTED",
    "RESILIENCE_BATCHES_SKIPPED", "RESILIENCE_CHECKPOINT_SAVES",
    "RESILIENCE_RESUMES", "RESILIENCE_RESUME_STEP",
    "RESILIENCE_INFERENCE_SHED", "RESILIENCE_INFERENCE_TIMEOUTS",
    "RESILIENCE_COLLECTOR_RESTARTS", "RESILIENCE_CKPT_ORPHANS_REMOVED",
    "RESILIENCE_CKPT_FALLBACKS",
    "GUARDIAN_CHECKS", "GUARDIAN_SKIPPED_UPDATES", "GUARDIAN_LR_RETRIES",
    "GUARDIAN_ROLLBACKS", "GUARDIAN_SAVES_GATED", "GUARDIAN_LAST_GOOD_STEP",
    "WATCHDOG_STALLS", "WATCHDOG_BEAT_AGE_SECONDS", "WATCHDOG_DUMPS",
    "DIST_PEERS", "DIST_PEER_LOST", "DIST_PREEMPTIONS",
    "DIST_BARRIER_TIMEOUTS", "DIST_ENCODED_BYTES", "DIST_RESIDUAL_NORM",
    "DIST_ACCUM_MICROBATCHES", "DIST_EXCHANGE_BUCKETS",
    "DIST_BUCKET_BYTES", "DIST_EXPOSED_EXCHANGE_MS",
    "DIST_ENCODER_MIGRATIONS",
    "DIST_REFORMS_AGREED", "DIST_REFORMS", "DIST_REFORM_MS",
    "DIST_WIRE_BYTES",
    "DIST_STRAGGLER_RATIO", "DIST_STRAGGLER_SKEW_MS",
    "PIPELINE_SYNCS", "PIPELINE_HOST_BLOCKED_MS", "PIPELINE_PREFETCH_DEPTH",
    "PIPELINE_STAGED_BATCHES",
    "GEN_TOKENS", "GEN_ACTIVE_SLOTS", "GEN_ADMISSIONS",
    "GEN_RETIREMENTS", "GEN_PREFILL_MS", "GEN_PER_TOKEN_MS",
    "GEN_REPLAYS", "GEN_RESTARTS", "GEN_DEGRADATIONS",
    "GEN_SUPERSTEPS", "GEN_TOKENS_PER_DISPATCH", "GEN_FETCH_OVERLAP_MS",
    "GEN_DRAFT_ACCEPTS", "GEN_DRAFT_REJECTS",
    "GEN_PAGES_ACTIVE", "GEN_PAGES_SHARED", "GEN_PAGE_EVICTIONS",
    "GEN_PREFIX_HITS",
    "FLEET_ROUTED", "FLEET_FAILOVERS", "FLEET_REPLACEMENTS",
    "FLEET_HEALTHY", "FLEET_DESIRED_REPLICAS",
    "QUANT_INT8_LAYERS", "QUANT_CALIBRATIONS",
    "QUANT_DEQUANT_FALLBACKS", "QUANT_ACTIVATION_BYTES",
    "INFERENCE_REQUEST_MS", "SLO_BREACHES", "SLO_BURN_RATE",
    "SLO_BREACHED", "CLUSTER_SNAPSHOT_AGE",
    "EVENTS_EMITTED", "EVENTS_DROPPED", "INCIDENTS_OPEN",
    "INCIDENTS_RESOLVED",
    "requests", "slo", "cluster", "stragglers", "events",
    "RequestLog", "RequestTimeline", "request_log",
    "merged_chrome_trace",
    "SloTracker", "LatencyObjective", "ThroughputObjective",
    "RatioObjective", "StepTimeObjective", "StragglerObjective",
    "standard_objectives",
]


def enable():
    """Turn on metrics collection and span recording globally."""
    STATE.enabled = True


def disable():
    """Back to the zero-overhead default (one branch per call site)."""
    STATE.enabled = False


def enabled():
    return STATE.enabled
