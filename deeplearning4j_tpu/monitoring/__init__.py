"""Unified host-side metrics + span tracing (the monitoring subsystem).

Dependency-free, disabled by default, and wired through the trainers
(`nn/multilayer.py`, `nn/graph.py`), the parallel stack
(`parallel/wrapper.py`, `parallel/sharded_trainer.py`,
`parallel/inference.py`), the executioner (`runtime/executioner.py`),
and the dashboard (`ui/server.py` serves `GET /metrics` in Prometheus
text format and a live metrics tab).

Quick start (one line at each end):

    net.setListeners(MetricsListener())          # optimize/listeners.py
    UIServer.getInstance().start()               # GET /metrics

or explicitly:

    from deeplearning4j_tpu import monitoring
    monitoring.enable()
    ... fit / serve ...
    monitoring.export_chrome_trace("/tmp/fit_trace.json")  # Perfetto
    print(monitoring.get_registry().prometheus_text())

Scope split across the repo's three observability layers:
- monitoring (this package) — HOST-side: where did the step's wall time
  go (data-iter / dispatch / listeners / eval / checkpoint spans), jit
  compile events, transfer bytes, device memory gauges;
- `optimize/listeners.ProfilerListener` + `optimize/xplane.py` —
  DEVICE-side: the XLA per-op trace (xplane.pb);
- `ui/stats.StatsListener` — LEARNING diagnostics: score curves, update
  ratios, activation histograms.
"""
from __future__ import annotations

from deeplearning4j_tpu.monitoring.state import STATE
from deeplearning4j_tpu.monitoring.registry import (  # noqa: F401
    Counter, Gauge, Histogram, MetricsRegistry,
    JIT_CACHE_MISSES, JIT_COMPILE_SECONDS, OP_DISPATCHES,
    TRANSFER_H2D_BYTES, DEVICE_MEMORY_BYTES, DEVICE_MEMORY_SUPPORTED,
    HOST_RSS_BYTES,
    RESILIENCE_RETRIES, RESILIENCE_BACKOFF_SECONDS,
    RESILIENCE_BREAKER_TRIPS, RESILIENCE_FAULTS_INJECTED,
    RESILIENCE_BATCHES_SKIPPED, RESILIENCE_CHECKPOINT_SAVES,
    RESILIENCE_RESUMES, RESILIENCE_RESUME_STEP,
    RESILIENCE_INFERENCE_SHED, RESILIENCE_INFERENCE_TIMEOUTS,
    RESILIENCE_COLLECTOR_RESTARTS,
    PIPELINE_SYNCS, PIPELINE_HOST_BLOCKED_MS, PIPELINE_PREFETCH_DEPTH,
    PIPELINE_STAGED_BATCHES,
    bootstrap_core_metrics, collect_device_memory, get_registry,
    record_transfer)
from deeplearning4j_tpu.monitoring.tracing import (  # noqa: F401
    NULL_SPAN, Span, Tracer, export_chrome_trace, get_tracer, span,
    traced_iter)

__all__ = [
    "enable", "disable", "enabled", "span", "traced_iter",
    "export_chrome_trace", "get_tracer", "get_registry",
    "MetricsRegistry", "Counter", "Gauge", "Histogram", "Tracer",
    "bootstrap_core_metrics", "collect_device_memory", "record_transfer",
    "JIT_CACHE_MISSES", "JIT_COMPILE_SECONDS", "OP_DISPATCHES",
    "TRANSFER_H2D_BYTES", "DEVICE_MEMORY_BYTES",
    "DEVICE_MEMORY_SUPPORTED", "HOST_RSS_BYTES",
    "RESILIENCE_RETRIES", "RESILIENCE_BACKOFF_SECONDS",
    "RESILIENCE_BREAKER_TRIPS", "RESILIENCE_FAULTS_INJECTED",
    "RESILIENCE_BATCHES_SKIPPED", "RESILIENCE_CHECKPOINT_SAVES",
    "RESILIENCE_RESUMES", "RESILIENCE_RESUME_STEP",
    "RESILIENCE_INFERENCE_SHED", "RESILIENCE_INFERENCE_TIMEOUTS",
    "RESILIENCE_COLLECTOR_RESTARTS",
    "PIPELINE_SYNCS", "PIPELINE_HOST_BLOCKED_MS", "PIPELINE_PREFETCH_DEPTH",
    "PIPELINE_STAGED_BATCHES",
]


def enable():
    """Turn on metrics collection and span recording globally."""
    STATE.enabled = True


def disable():
    """Back to the zero-overhead default (one branch per call site)."""
    STATE.enabled = False


def enabled():
    return STATE.enabled
