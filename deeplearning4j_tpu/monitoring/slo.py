"""Declarative SLOs with multi-window burn-rate evaluation.

A fleet replica needs a machine-checkable "am I healthy enough for
traffic" signal, not a human staring at dashboards. This module turns
the telemetry the repo already collects — latency histograms
(`registry.Histogram`), the step-time flight recorder
(`monitoring/steps.py`), counters — into declarative OBJECTIVES:

    tracker = SloTracker([
        LatencyObjective("per_token_p99",
                         metric=registry.GEN_PER_TOKEN_MS,
                         quantile=0.99, max_value=25.0),
        ThroughputObjective("steps_rate", max_drop=0.5),
        RatioObjective("replay_rate", num=registry.GEN_REPLAYS,
                       den=registry.GEN_ADMISSIONS, max_ratio=0.2),
    ])
    tracker.install()          # GET /health now reports breaches

Evaluation is PULL-based (the `/health` and `/slo` endpoints drive it,
rate-limited to `min_interval`): nothing on any hot path ever touches
this module, so the train/decode loops pay zero cost whether or not a
tracker is installed — the PR 1 discipline, just with the guard at the
endpoint instead of the call site.

Burn-rate semantics (the multi-window rule SRE burn-rate alerts use):
each evaluation samples every objective as good/bad; `burn_rate(w)` is
the bad fraction of the samples inside window `w`, divided by the
error budget (the tolerated bad fraction, default 10%). An objective
BREACHES when both the SHORT window (is it bad right now) and the LONG
window (has it been bad long enough to matter) burn faster than budget
(rate >= 1) — a single bad scrape can't page, and a real regression
trips within one short window. It AUTO-RECOVERS the moment either
window stops burning; `dl4j.slo.breaches` counts trips,
`dl4j.slo.burn_rate{objective,window}` and
`dl4j.slo.breached{objective}` track the live state, and
`GET /health` flips to degraded with the violated objective named.
"""
from __future__ import annotations

import threading
import time
from collections import deque

from deeplearning4j_tpu.monitoring import events as _events
from deeplearning4j_tpu.monitoring import registry as _registry
from deeplearning4j_tpu.monitoring.state import STATE

__all__ = ["Objective", "LatencyObjective", "ThroughputObjective",
           "RatioObjective", "StepTimeObjective", "StragglerObjective",
           "SloTracker", "ACTIVE", "clear_tracker",
           "standard_objectives"]

#: the installed tracker `resilience.health_snapshot()` consults
#: (faults.py ACTIVE pattern; None = no SLOs declared)
ACTIVE = None


class Objective:
    """One declarative objective. Subclasses implement `measure()` →
    True (violated) / False (met) / None (no evidence yet — e.g. the
    metric has no observations; inconclusive samples are skipped, they
    neither burn nor repay budget)."""

    def __init__(self, name, description=""):
        self.name = str(name)
        self.description = description
        self.last_value = None
        self.threshold = None

    def measure(self):  # pragma: no cover — abstract
        raise NotImplementedError

    def describe(self):
        return {"name": self.name, "description": self.description,
                "last_value": self.last_value,
                "threshold": self.threshold}


class LatencyObjective(Objective):
    """A histogram quantile must stay at or under `max_value` —
    e.g. per-token p99 <= 25 ms over `registry.GEN_PER_TOKEN_MS`."""

    def __init__(self, name, metric, max_value, quantile=0.99,
                 labels=None, description=""):
        super().__init__(name, description or
                         f"{metric} p{int(quantile * 100)} <= "
                         f"{max_value}")
        self.metric = metric
        self.labels = labels
        self.quantile = float(quantile)
        self.threshold = float(max_value)

    def measure(self, registry=None):
        reg = registry or _registry.get_registry()
        h = reg.get(self.metric, self.labels)
        if h is None or getattr(h, "count", 0) == 0:
            return None
        v = h.quantile(self.quantile)
        if v is None:
            return None
        self.last_value = float(v)
        return self.last_value > self.threshold


class ThroughputObjective(Objective):
    """Steps/s must stay within `max_drop` of a rolling baseline, from
    the flight recorder's wall-time percentiles (monitoring/steps.py).
    The baseline is an EMA over HEALTHY samples only — a sustained
    regression can't drag its own reference down and self-heal the
    alert; recovery updates the baseline again."""

    def __init__(self, name, max_drop=0.5, ema=0.2, description=""):
        super().__init__(name, description or
                         f"steps/s within {max_drop:.0%} of the "
                         f"rolling baseline")
        self.max_drop = float(max_drop)
        self.ema = float(ema)
        self.baseline = None
        self.threshold = self.max_drop

    def _rate(self):
        from deeplearning4j_tpu.monitoring import steps as _steps
        s = _steps.recorder().summary()
        wall = s.get("wall_ms")
        if not wall or not wall.get("p50"):
            return None
        return 1000.0 / wall["p50"]

    def measure(self, registry=None):
        rate = self._rate()
        if rate is None:
            return None
        self.last_value = rate
        if self.baseline is None:
            self.baseline = rate
            return False
        bad = rate < self.baseline * (1.0 - self.max_drop)
        if not bad:
            self.baseline = (1 - self.ema) * self.baseline \
                + self.ema * rate
        return bad


class RatioObjective(Objective):
    """A windowed counter ratio must stay at or under `max_ratio` —
    e.g. crash-replays per admission <= 20%. Measured on counter
    DELTAS since the previous evaluation (the lifetime ratio would
    take forever to notice a regression — and forever to recover)."""

    def __init__(self, name, num, den, max_ratio, num_labels=None,
                 den_labels=None, description=""):
        super().__init__(name, description or
                         f"{num}/{den} <= {max_ratio}")
        self.num = num
        self.den = den
        self.num_labels = num_labels
        self.den_labels = den_labels
        self.threshold = float(max_ratio)
        self._last = None              # (num_value, den_value)

    def measure(self, registry=None):
        reg = registry or _registry.get_registry()
        n = reg.get(self.num, self.num_labels)
        d = reg.get(self.den, self.den_labels)
        nv = n.value if n is not None else 0
        dv = d.value if d is not None else 0
        if self._last is None:
            self._last = (nv, dv)
            return None
        dn, dd = nv - self._last[0], dv - self._last[1]
        self._last = (nv, dv)
        if dd <= 0:
            # no denominator activity this window: a numerator bump
            # with zero denominator is a violation by itself (replays
            # with no admissions), otherwise no evidence. Clear the
            # stale ratio so the breach never displays a previous
            # window's under-threshold value as its evidence.
            if dn > 0:
                self.last_value = None
                return True
            return None
        self.last_value = dn / dd
        return self.last_value > self.threshold


class StepTimeObjective(Objective):
    """A step wall-time quantile from the flight recorder
    (monitoring/steps.py) must stay at or under `max_ms` — the
    training-side twin of LatencyObjective, read from the ring's
    percentile roll-up instead of a histogram."""

    def __init__(self, name, max_ms, quantile=0.99, description=""):
        q = float(quantile)
        self._qkey = "p%d" % round(q * 100)
        super().__init__(name, description or
                         f"step wall {self._qkey} <= {max_ms} ms")
        self.quantile = q
        self.threshold = float(max_ms)

    def measure(self, registry=None):
        from deeplearning4j_tpu.monitoring import steps as _steps
        wall = _steps.recorder().summary().get("wall_ms")
        if not wall or wall.get(self._qkey) is None:
            return None
        self.last_value = float(wall[self._qkey])
        return self.last_value > self.threshold


class StragglerObjective(Objective):
    """The max-host / median-host attributed step-time ratio (straggler
    plane, monitoring/stragglers.py) must stay at or under `max_ratio`.
    Breaching carries the CULPRIT — slowest host and phase — into
    `describe()`, so `GET /health` names who to replace or rebalance,
    not just that someone is slow. Inconclusive (None) below two
    reporting hosts or with no coordinator attached."""

    def __init__(self, name, max_ratio=2.0, coordinator=None,
                 description=""):
        super().__init__(name, description or
                         f"max-host/median-host step time <= "
                         f"{max_ratio}x")
        self.threshold = float(max_ratio)
        self._coordinator = coordinator
        self.culprit = None

    def _coord(self):
        if self._coordinator is not None:
            return self._coordinator
        # late lookup so the objective can be declared before the
        # coordinator exists (and survives coordinator replacement on
        # elastic restart); sys.modules, never a fresh import — an
        # objective must not trigger module init from a health poll
        import sys
        mod = sys.modules.get("deeplearning4j_tpu.parallel.coordination")
        return getattr(mod, "ACTIVE", None) if mod else None

    def measure(self, registry=None):
        coord = self._coord()
        if coord is None:
            return None
        from deeplearning4j_tpu.monitoring import stragglers as _sg
        att = _sg.attribution(coord)
        if att is None or att.get("ratio") is None:
            return None
        self.last_value = float(att["ratio"])
        self.culprit = att.get("slowest")
        return self.last_value > self.threshold

    def describe(self):
        d = super().describe()
        if self.culprit is not None:
            d["culprit"] = {"host": self.culprit.get("host"),
                            "phase": self.culprit.get("phase")}
        return d


def _exemplar_ids(obj, top=3):
    """Trace ids behind the tail of the objective's histogram (if it
    has one) — the breach event links straight to slow requests."""
    metric = getattr(obj, "metric", None)
    if not metric:
        return []
    try:
        hist = _registry.get_registry().histogram(
            metric, labels=getattr(obj, "labels", None))
        return [e["trace_id"] for e in hist.exemplars(top=top)
                if e.get("trace_id")]
    except Exception:  # noqa: BLE001 — breach reporting must not raise
        return []


class SloTracker:
    """Evaluates a set of objectives on the multi-window burn-rate rule
    and carries the breach state `GET /health` reports.

    `budget` is the error budget (tolerated bad fraction of samples,
    default 0.1); `short_window`/`long_window` are the two burn
    windows in seconds. `min_interval` rate-limits evaluation (the
    endpoints may poll every second; sampling faster than telemetry
    changes just burns CPU). `min_samples` is the evidence floor: an
    objective cannot breach until its long window holds at least that
    many samples — at cold start (or with a scrape cadence as long as
    the windows) both windows hold the same 1-2 samples and the
    multi-window rule would otherwise degenerate to paging on a single
    bad scrape."""

    def __init__(self, objectives=(), short_window=30.0,
                 long_window=120.0, budget=0.1, min_interval=1.0,
                 min_samples=4, clock=time.monotonic):
        self.objectives = list(objectives)
        self.short_window = float(short_window)
        self.long_window = float(long_window)
        self.budget = float(budget)
        self.min_interval = float(min_interval)
        self.min_samples = int(min_samples)
        self._clock = clock
        self._lock = threading.Lock()
        self._samples = {o.name: deque() for o in self.objectives}
        self._breached = {}            # name -> since (monotonic)
        self._burn = {}                # name -> (short, long)
        self._last_eval = None
        self._prev_active = None

    # -- install / clear (faults.py pattern) -----------------------------
    def install(self):
        global ACTIVE
        if ACTIVE is not self:
            self._prev_active = ACTIVE
            ACTIVE = self
        return self

    def uninstall(self):
        global ACTIVE
        if ACTIVE is self:
            ACTIVE = self._prev_active
            self._prev_active = None
        return self

    def __enter__(self):
        return self.install()

    def __exit__(self, *exc):
        self.uninstall()
        return False

    def add(self, objective):
        with self._lock:
            self.objectives.append(objective)
            self._samples[objective.name] = deque()
        return self

    # -- evaluation -------------------------------------------------------
    def _burn_rate(self, samples, window, now):
        inside = [bad for t, bad in samples if now - t <= window]
        if not inside:
            return 0.0
        return (sum(inside) / len(inside)) / self.budget

    def evaluate(self, force=False):
        """One evaluation pass (rate-limited unless `force`): sample
        every objective, fold the burn windows, flip/clear breaches,
        publish `dl4j.slo.*`. Returns the snapshot."""
        now = self._clock()
        with self._lock:
            if not force and self._last_eval is not None \
                    and now - self._last_eval < self.min_interval:
                return self._snapshot_locked(now)
            self._last_eval = now
            for obj in self.objectives:
                try:
                    bad = obj.measure()
                except Exception:  # noqa: BLE001 — one broken objective
                    continue       # must not take down health reporting
                samples = self._samples.setdefault(obj.name, deque())
                if bad is not None:
                    samples.append((now, bool(bad)))
                while samples and now - samples[0][0] > self.long_window:
                    samples.popleft()
                bs = self._burn_rate(samples, self.short_window, now)
                bl = self._burn_rate(samples, self.long_window, now)
                self._burn[obj.name] = (bs, bl)
                breached = bs >= 1.0 and bl >= 1.0 \
                    and len(samples) >= self.min_samples
                was = obj.name in self._breached
                if breached and not was:
                    self._breached[obj.name] = now
                    if STATE.enabled:
                        _registry.get_registry().counter(
                            _registry.SLO_BREACHES,
                            labels={"objective": obj.name},
                            help="SLO objective breach trips "
                                 "(multi-window burn rule)").inc()
                        _events.emit(
                            "monitoring", _events.SLO_BREACH,
                            attrs={"objective": obj.name,
                                   "burn_short": round(bs, 4),
                                   "burn_long": round(bl, 4),
                                   "exemplars": _exemplar_ids(obj)},
                            correlation_id="slo-%s" % obj.name)
                elif not breached and was:
                    self._breached.pop(obj.name, None)
                    if STATE.enabled:
                        _events.emit(
                            "monitoring", _events.SLO_RECOVER,
                            attrs={"objective": obj.name},
                            correlation_id="slo-%s" % obj.name)
                if STATE.enabled:
                    reg = _registry.get_registry()
                    for win, b in (("short", bs), ("long", bl)):
                        reg.gauge(
                            _registry.SLO_BURN_RATE,
                            labels={"objective": obj.name,
                                    "window": win},
                            help="error-budget burn rate per window "
                                 "(>=1 burns faster than budget)"
                        ).set(b)
                    reg.gauge(
                        _registry.SLO_BREACHED,
                        labels={"objective": obj.name},
                        help="1 while the objective is breached"
                    ).set(1.0 if breached else 0.0)
            return self._snapshot_locked(now)

    def breaches(self):
        """Names of currently breached objectives (oldest first)."""
        with self._lock:
            return [n for n, _ in sorted(self._breached.items(),
                                         key=lambda kv: kv[1])]

    def _snapshot_locked(self, now):
        objs = {}
        for obj in self.objectives:
            bs, bl = self._burn.get(obj.name, (0.0, 0.0))
            d = obj.describe()
            d.update(burn_short=round(bs, 4), burn_long=round(bl, 4),
                     breached=obj.name in self._breached)
            since = self._breached.get(obj.name)
            if since is not None:
                d["breached_for_s"] = round(now - since, 3)
            objs[obj.name] = d
        return {"objectives": objs,
                "violated": [n for n, _ in sorted(self._breached.items(),
                                                  key=lambda kv: kv[1])],
                "budget": self.budget,
                "windows_s": {"short": self.short_window,
                              "long": self.long_window}}

    def snapshot(self):
        """Evaluate (rate-limited) and return the `/slo` payload —
        what `resilience.health_snapshot()` embeds."""
        return self.evaluate()


def standard_objectives(per_token_p99_ms=None, steps_drop=None,
                        replay_ratio=None, step_p99_ms=None,
                        straggler_ratio=None, failover_ratio=None):
    """The standard objective set, with env-var thresholds:
    DL4J_SLO_PER_TOKEN_P99_MS, DL4J_SLO_STEPS_DROP,
    DL4J_SLO_REPLAY_RATIO, DL4J_SLO_STEP_P99_MS,
    DL4J_SLO_STRAGGLER_RATIO, DL4J_SLO_FAILOVER_RATIO (an unset/None
    knob omits the objective)."""
    import os

    def knob(arg, env):
        if arg is not None:
            return float(arg)
        v = os.environ.get(env)
        try:
            return float(v) if v else None
        except ValueError:
            return None

    out = []
    v = knob(per_token_p99_ms, "DL4J_SLO_PER_TOKEN_P99_MS")
    if v is not None:
        out.append(LatencyObjective("per_token_p99",
                                    metric=_registry.GEN_PER_TOKEN_MS,
                                    quantile=0.99, max_value=v))
    v = knob(steps_drop, "DL4J_SLO_STEPS_DROP")
    if v is not None:
        out.append(ThroughputObjective("steps_rate", max_drop=v))
    v = knob(replay_ratio, "DL4J_SLO_REPLAY_RATIO")
    if v is not None:
        out.append(RatioObjective("replay_rate",
                                  num=_registry.GEN_REPLAYS,
                                  den=_registry.GEN_ADMISSIONS,
                                  max_ratio=v))
    v = knob(step_p99_ms, "DL4J_SLO_STEP_P99_MS")
    if v is not None:
        out.append(StepTimeObjective("step_p99", max_ms=v))
    v = knob(straggler_ratio, "DL4J_SLO_STRAGGLER_RATIO")
    if v is not None:
        out.append(StragglerObjective("straggler_ratio", max_ratio=v))
    v = knob(failover_ratio, "DL4J_SLO_FAILOVER_RATIO")
    if v is not None:
        # fleet health: mid-stream failovers per routed admission — a
        # fleet that re-routes most of its traffic is burning replicas
        # even while every individual stream still completes
        out.append(RatioObjective("failover_ratio",
                                  num=_registry.FLEET_FAILOVERS,
                                  den=_registry.FLEET_ROUTED,
                                  max_ratio=v))
    return out


def clear_tracker():
    """Force-reset the global switch — test teardown only."""
    global ACTIVE
    ACTIVE = None
