"""Lightweight span tracing with Chrome trace-event JSON export.

`span("name")` is a context manager; nesting is tracked per thread, and
the recorded events are Chrome trace-event "X" (complete) events, so the
export loads directly into Perfetto / `chrome://tracing` and shows the
host-side phase structure of a `fit()` — data-iter / dispatch / listener
/ eval / checkpoint — that the device-side xplane trace
(`optimize/xplane.py`) cannot see.

Disabled fast path: `span()` returns a shared no-op singleton after ONE
flag check — no allocation, nothing recorded. Event storage is bounded
(`max_events`), so a forgotten `enable()` cannot leak memory over a long
training run.
"""
from __future__ import annotations

import json
import os
import threading
import time

from deeplearning4j_tpu.monitoring.state import STATE
from deeplearning4j_tpu.monitoring import steps as _steps


class _NullSpan:
    """Shared do-nothing span for the disabled path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


NULL_SPAN = _NullSpan()


class Span:
    __slots__ = ("name", "args", "_tracer", "_t0")

    def __init__(self, tracer, name, args=None):
        self._tracer = tracer
        self.name = name
        self.args = args
        self._t0 = 0

    def __enter__(self):
        self._tracer._local.stack.append(self.name)
        self._t0 = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb):
        t1 = time.perf_counter_ns()
        local = self._tracer._local
        stack = local.stack
        if stack and stack[-1] == self.name:
            stack.pop()
        self._tracer._record(self, self._t0, t1, len(stack),
                             exc_type is not None)
        return False


class Tracer:
    """Collects span events; thread-safe; bounded."""

    def __init__(self, max_events=200_000):
        self.max_events = int(max_events)
        self._lock = threading.Lock()
        self._events = []
        self._dropped = 0
        self._epoch_ns = time.perf_counter_ns()
        self._local = threading.local()
        self._pid = os.getpid()   # constant; skip the syscall per record
        # tid -> the SAME list object as that thread's _local.stack, so
        # a monitor thread (resilience/watchdog.py) can snapshot what
        # every thread is doing right now without cross-thread locals
        self._stacks_by_tid = {}

    def _ensure_local(self):
        if not hasattr(self._local, "stack"):
            # registering a new thread is rare — use it to evict tids of
            # exited threads, so a watchdog-less process (where
            # open_spans() never runs) doesn't pin one stack list per
            # dead span-recording thread forever
            if len(self._stacks_by_tid) > threading.active_count():
                live = {t.ident for t in threading.enumerate()}
                for tid in list(self._stacks_by_tid):
                    if tid not in live:
                        self._stacks_by_tid.pop(tid, None)
            self._local.stack = []
            self._stacks_by_tid[threading.get_ident()] = self._local.stack

    def span(self, name, args=None):
        self._ensure_local()
        return Span(self, name, args)

    def _record(self, span, t0_ns, t1_ns, depth, failed):
        ev = {
            "name": span.name,
            "cat": "host",
            "ph": "X",
            "ts": (t0_ns - self._epoch_ns) / 1e3,      # microseconds
            "dur": (t1_ns - t0_ns) / 1e3,
            "pid": self._pid,
            "tid": threading.get_ident(),
        }
        args = dict(span.args) if span.args else {}
        args["depth"] = depth
        if failed:
            args["error"] = True
        ev["args"] = args
        with self._lock:
            if len(self._events) < self.max_events:
                self._events.append(ev)
            else:
                self._dropped += 1
        # feed the step-attribution flight recorder (monitoring/steps.py):
        # reached only when monitoring is enabled (disabled spans are the
        # shared NULL_SPAN and never get here), and on_span is one dict
        # lookup for spans it doesn't track
        _steps.recorder().on_span(span.name, (t1_ns - t0_ns) / 1e6)

    def current_stack(self):
        """The CALLING thread's open-span stack, outermost first (what
        the process was doing right now — crash_reporting embeds this in
        OOM dumps so post-mortems show the phase that died)."""
        self._ensure_local()
        return list(self._local.stack)

    def open_spans(self):
        """{thread_id: open-span stack} across ALL LIVE threads that
        have recorded a span — the cross-thread view a stall watchdog
        needs (a wedged trainer thread cannot report on itself). Exited
        threads are evicted here (cold path — their stale stacks would
        otherwise read as phantom wedged threads in a stall report, and
        pin their lists forever). Best effort: stacks mutate
        concurrently; the copy is taken per list and never raises."""
        live = {t.ident for t in threading.enumerate()}
        out = {}
        for tid, stack in list(self._stacks_by_tid.items()):
            if tid not in live:
                self._stacks_by_tid.pop(tid, None)
                continue
            try:
                snap = list(stack)
            except Exception:  # noqa: BLE001 — concurrent mutation
                snap = []
            if snap:
                out[tid] = snap
        return out

    # -- export ----------------------------------------------------------
    @property
    def epoch_ns(self):
        """perf_counter origin of this tracer's timestamps — lets other
        event sources (monitoring/requests.py lanes) align with the
        span timebase when merging into one Chrome trace."""
        return self._epoch_ns

    def events(self):
        with self._lock:
            return list(self._events)

    def clear(self):
        with self._lock:
            self._events = []
            self._dropped = 0
        self._epoch_ns = time.perf_counter_ns()

    def _process_metadata(self, process_name=None):
        """Chrome "M" metadata events naming this PROCESS (and its
        span-recording threads): merged multi-process traces then
        render each process as its own named lane group instead of
        interleaving everything under one anonymous pid. The process
        index comes from the distributed bootstrap when one ran
        (resilience.faults.PROCESS_ID / DL4J_PROCESS_ID) — no jax
        import from the export path."""
        if process_name is None:
            idx = None
            import sys
            faults = sys.modules.get(
                "deeplearning4j_tpu.resilience.faults")
            if faults is not None:
                idx = getattr(faults, "PROCESS_ID", None)
            if idx is None:
                idx = os.environ.get("DL4J_PROCESS_ID")
            tag = f"p{idx} " if idx is not None else ""
            process_name = f"dl4j {tag}(pid {self._pid})"
        meta = [
            {"ph": "M", "name": "process_name", "pid": self._pid,
             "args": {"name": process_name}},
        ]
        names = {t.ident: t.name for t in threading.enumerate()}
        for tid in list(self._stacks_by_tid):
            meta.append({"ph": "M", "name": "thread_name",
                         "pid": self._pid, "tid": tid,
                         "args": {"name": names.get(tid,
                                                    f"thread-{tid}")}})
        return meta

    def to_chrome_trace(self, process_name=None):
        """Chrome trace-event JSON object (the {"traceEvents": [...]}
        envelope both Perfetto and chrome://tracing load). Leads with
        real pid/process-name (and thread-name) metadata events, so
        traces from several processes concatenated into one document
        render as separate named lanes."""
        with self._lock:
            events = list(self._events)
            dropped = self._dropped
        doc = {"traceEvents": self._process_metadata(process_name)
               + events,
               "displayTimeUnit": "ms"}
        if dropped:
            doc["otherData"] = {"droppedEvents": dropped}
        return doc

    def export(self, path):
        with open(path, "w") as f:
            json.dump(self.to_chrome_trace(), f)
        return path


_global_tracer = Tracer()


def get_tracer():
    return _global_tracer


def span(name, args=None):
    """THE instrumentation point: a context manager timing one phase.

    Disabled (the default): one flag check, returns the shared no-op
    singleton — no allocation, no lock, nothing recorded."""
    if not STATE.enabled:
        return NULL_SPAN
    return _global_tracer.span(name, args)


def export_chrome_trace(path):
    """Write everything recorded so far as Chrome trace-event JSON."""
    return _global_tracer.export(path)


def traced_iter(iterable, name="fit.data_next"):
    """Wrap data iteration so time spent PULLING batches (host input
    pipeline) shows as its own span per batch. Disabled → returns the
    iterable untouched (zero cost)."""
    if not STATE.enabled:
        return iterable

    def gen():
        it = iter(iterable)
        while True:
            with span(name):
                try:
                    item = next(it)
                except StopIteration:
                    return
            yield item

    return gen()
