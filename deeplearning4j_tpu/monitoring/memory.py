"""Device memory telemetry: HBM gauges, live-tree footprint estimates,
and the OOM forensics snapshot.

Three consumers:
- **periodic gauges** — `sample()` refreshes the per-device
  `dl4j.device.memory_bytes` gauges (bytes-in-use / peak / limit, from
  `device.memory_stats()`; TPU/GPU backends — CPU says "unsupported"
  instead of inventing numbers) plus `dl4j.model.*_bytes` footprint
  estimates from a live model's param/optimizer/state trees.
  `MetricsListener(deviceMemoryFrequency=N)` calls it every N
  iterations; `MemoryMonitor` runs it on a background thread for
  serving processes that have no training loop to piggyback on.
- **OOM forensics** — every `sample()` keeps its reading in
  `last_sample()`; when an XLA RESOURCE_EXHAUSTED escapes,
  `util/crash_reporting.py` embeds that LAST-KNOWN-GOOD reading in the
  dump, which is forensically more useful than the post-mortem query
  (after the OOM the allocator has often already unwound, so "bytes in
  use at death" under-reports the spike that killed the run).
- **capacity planning** — `footprint(model)` alone answers "how much
  HBM do the params + optimizer state pin" before a run is launched.
"""
from __future__ import annotations

import threading
import time

from deeplearning4j_tpu.monitoring import registry as _registry
from deeplearning4j_tpu.monitoring.state import STATE

__all__ = ["MemoryMonitor", "device_memory_stats", "footprint",
           "last_sample", "sample"]

_lock = threading.Lock()
_last_sample = None


def device_memory_stats():
    """{device_str: stats_dict_or_None} from `device.memory_stats()` over
    the local devices. Never raises — backends without the API (CPU)
    report None."""
    out = {}
    try:
        import jax
        devices = jax.local_devices()
    except Exception:  # noqa: BLE001 — no backend yet / init failure
        return out
    for d in devices:
        try:
            fn = getattr(d, "memory_stats", None)
            out[str(d)] = fn() if fn is not None else None
        except Exception:  # noqa: BLE001 — telemetry must never raise
            out[str(d)] = None
    return out


def _tree_bytes(tree):
    import numpy as np

    import jax
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            total += int(np.prod(leaf.shape)) * leaf.dtype.itemsize
    return total


def footprint(model):
    """Byte estimates from the LIVE trees of a network / trainer-shaped
    object: {"params_bytes", "opt_state_bytes", "layer_state_bytes"}.
    Missing trees report 0 (e.g. an un-init()ed net)."""
    return {
        "params_bytes": _tree_bytes(getattr(model, "_params", None)),
        "opt_state_bytes": _tree_bytes(getattr(model, "_opt_state", None)),
        "layer_state_bytes": _tree_bytes(getattr(model, "_state", None)),
    }


def sample(registry=None, model=None):
    """One telemetry reading: refresh the device-memory + host-RSS gauges
    (via `registry.collect_device_memory`), add model footprint gauges
    when a model is given, and retain the reading for OOM forensics.
    Returns the snapshot dict."""
    reg = registry if registry is not None else _registry.get_registry()
    snap = {"ts": time.time(), "devices": device_memory_stats()}
    _registry.collect_device_memory(reg, device_stats=snap["devices"])
    if model is not None:
        fp = footprint(model)
        snap["model"] = fp
        reg.gauge(_registry.MODEL_PARAMS_BYTES,
                  help="bytes pinned by the live parameter tree") \
           .set(fp["params_bytes"])
        reg.gauge(_registry.MODEL_OPT_STATE_BYTES,
                  help="bytes pinned by the live optimizer state") \
           .set(fp["opt_state_bytes"])
        reg.gauge(_registry.MODEL_LAYER_STATE_BYTES,
                  help="bytes pinned by layer state (BN stats, ...)") \
           .set(fp["layer_state_bytes"])
    global _last_sample
    with _lock:
        _last_sample = snap
    return snap


def last_sample():
    """The most recent `sample()` reading (None before the first) — the
    OOM forensics hook crash_reporting embeds in memory crash dumps."""
    with _lock:
        return _last_sample


class MemoryMonitor:
    """Background periodic `sample()` for processes without a training
    loop (serving, notebooks): `MemoryMonitor(interval_s=10).start()`.
    Samples only while monitoring is enabled — a running monitor on a
    disabled registry costs one flag check per interval."""

    def __init__(self, interval_s=10.0, registry=None, model=None):
        self.interval_s = float(interval_s)
        self.registry = registry
        self.model = model
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="dl4j-memory-monitor")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            if not STATE.enabled:
                continue
            try:
                sample(self.registry, self.model)
            except Exception:  # noqa: BLE001 — telemetry must never die
                pass

    def stop(self):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5)
        return self
