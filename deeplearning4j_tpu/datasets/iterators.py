"""DataSetIterators (≡ deeplearning4j-datasets :: iterator.impl.* and
nd4j DataSetIterator protocol).

Zero-egress environment: the IDX/bin parsers read real files when present
(MNIST at ~/.deeplearning4j/mnist or a given path); otherwise iterators fall
back to DETERMINISTIC synthetic datasets with the same shapes/types, so
training code and tests behave identically either way. The native C++ fast
path (runtime.native) accelerates parsing/batching when built.
"""
from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from deeplearning4j_tpu.datasets.dataset import DataSet
from deeplearning4j_tpu.runtime.pipeline import PrefetchIterator


class DataSetIterator:
    """Protocol base: python iteration + the reference's next/hasNext/reset."""

    def __init__(self, batch_size):
        self._batch = int(batch_size)
        self._cursor = 0

    # reference surface
    def batch(self):
        return self._batch

    def hasNext(self):
        return self._cursor < self.numExamples()

    def _check_has_next(self):
        if not self.hasNext():
            # ≡ the reference's NoSuchElementException on exhausted iterator
            raise StopIteration("DataSetIterator exhausted; call reset()")

    def next(self, num=None):
        raise NotImplementedError

    def reset(self):
        self._cursor = 0

    def resetSupported(self):
        return True

    def asyncSupported(self):
        return True

    def numExamples(self):
        raise NotImplementedError

    def totalOutcomes(self):
        raise NotImplementedError

    def inputColumns(self):
        raise NotImplementedError

    def setPreProcessor(self, pp):
        self._preprocessor = pp

    def getPreProcessor(self):
        return getattr(self, "_preprocessor", None)

    def _maybe_preprocess(self, ds):
        pp = getattr(self, "_preprocessor", None)
        if pp is not None:
            pp.preProcess(ds)
        return ds

    # python iteration
    def __iter__(self):
        self.reset()
        return self

    def __next__(self):
        if not self.hasNext():
            raise StopIteration
        return self.next()


class ArrayDataSetIterator(DataSetIterator):
    """Iterate an in-memory (features, labels) pair (≡ ListDataSetIterator)."""

    def __init__(self, features, labels, batch_size, shuffle=False, seed=123):
        super().__init__(batch_size)
        self.features = np.asarray(features)
        self.labels = np.asarray(labels)
        self._shuffle = shuffle
        self._rng = np.random.default_rng(seed)
        self._order = np.arange(len(self.features))

    def numExamples(self):
        return len(self.features)

    def totalOutcomes(self):
        return int(self.labels.shape[-1])

    def inputColumns(self):
        return int(np.prod(self.features.shape[1:]))

    def reset(self):
        super().reset()
        if self._shuffle:
            self._rng.shuffle(self._order)

    def next(self, num=None):
        self._check_has_next()
        n = num or self._batch
        idx = self._order[self._cursor:self._cursor + n]
        self._cursor += len(idx)
        return self._maybe_preprocess(
            DataSet(self.features[idx], self.labels[idx]))


def _read_idx(path):
    """Parse an IDX (MNIST) file, gzipped or raw. Uncompressed files take
    the native C++ parser (runtime.native_lib) when built."""
    if not path.endswith(".gz"):
        from deeplearning4j_tpu.runtime import native_lib
        arr = native_lib.idx_read(path)
        if arr is not None:
            return arr
    op = gzip.open if path.endswith(".gz") else open
    with op(path, "rb") as f:
        data = f.read()
    zeros, dtype_code, ndim = struct.unpack(">HBB", data[:4])
    dims = struct.unpack(">" + "I" * ndim, data[4:4 + 4 * ndim])
    dtype = {8: np.uint8, 9: np.int8, 11: np.int16, 12: np.int32,
             13: np.float32, 14: np.float64}[dtype_code]
    return np.frombuffer(data, dtype=dtype, offset=4 + 4 * ndim).reshape(dims)


def _one_hot(y, n):
    out = np.zeros((len(y), n), np.float32)
    out[np.arange(len(y)), y.astype(np.int64)] = 1.0
    return out


def _synthetic_images(n, h, w, c, n_classes, seed):
    """Deterministic, linearly-separable-ish synthetic image set: each class
    has a characteristic frequency pattern plus noise (so LeNet-class models
    reach high accuracy, exercising the real training dynamics).

    Pattern parameters use independent x/y frequencies plus a golden-angle
    phase, so classes stay visually distinct up to hundreds of classes
    (the old freq=cls%5 form aliased classes 45 apart — indistinguishable
    under the noise). Noise is generated float32 per class slice: peak
    memory stays O(dataset), not O(dataset) x2 in float64."""
    rng = np.random.default_rng(seed)
    y = rng.integers(0, n_classes, size=n)
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    imgs = np.zeros((n, h, w, c), np.float32)
    for cls in range(n_classes):
        m = y == cls
        if not m.any():
            continue
        fx = 1 + cls % 6
        fy = 1 + (cls // 6) % 6
        phase = cls * 2.39996323   # golden angle: no periodic aliasing
        pattern = 0.5 + 0.5 * np.sin(fx * 2 * np.pi * xx / w + phase) \
            * np.cos(fy * 2 * np.pi * yy / h + 0.5 * phase)
        imgs[m] = pattern[None, :, :, None] + 0.15 * rng.standard_normal(
            (int(m.sum()), h, w, c), dtype=np.float32)
    np.clip(imgs, 0, 1, out=imgs)
    return (imgs * 255).astype(np.uint8), y


class MnistDataSetIterator(DataSetIterator):
    """≡ deeplearning4j-datasets :: MnistDataSetIterator.

    Emits (B, 784) float features in [0,1] + one-hot(10) labels, matching
    the reference's flattened-row convention (use
    InputType.convolutionalFlat(28,28,1) for CNNs). Reads real IDX files
    from `root` when present, else deterministic synthetic digits.
    """

    H = W = 28
    NUM_CLASSES = 10

    def __init__(self, batch_size, train=True, seed=123, root=None,
                 num_examples=None):
        super().__init__(batch_size)
        root = root or os.path.expanduser("~/.deeplearning4j/mnist")
        kind = "train" if train else "t10k"
        img_path = None
        for suffix in ("-images-idx3-ubyte.gz", "-images-idx3-ubyte"):
            p = os.path.join(root, kind + suffix)
            if os.path.exists(p):
                img_path = p
                break
        if img_path is not None:
            lbl_path = img_path.replace("images-idx3", "labels-idx1")
            images = _read_idx(img_path)
            labels = _read_idx(lbl_path)
            self._images = images.reshape(len(images), self.H, self.W, 1)
            self._labels = labels
        else:
            n = num_examples or (6000 if train else 1000)
            self._images, self._labels = _synthetic_images(
                n, self.H, self.W, 1, self.NUM_CLASSES,
                seed if train else seed + 1)
        if num_examples:
            self._images = self._images[:num_examples]
            self._labels = self._labels[:num_examples]

    def numExamples(self):
        return len(self._images)

    def totalOutcomes(self):
        return self.NUM_CLASSES

    def inputColumns(self):
        return self.H * self.W

    def next(self, num=None):
        self._check_has_next()
        n = num or self._batch
        end = min(self._cursor + n, len(self._images))
        idx = np.arange(self._cursor, end)
        self._cursor = end
        # native batch assembly: gather + u8→f32 scale + one-hot in C++
        from deeplearning4j_tpu.runtime import native_lib
        feats = native_lib.gather_batch_u8(
            self._images.reshape(len(self._images), -1), idx)
        labels = native_lib.one_hot_u8(
            np.ascontiguousarray(self._labels, np.uint8), idx,
            self.NUM_CLASSES)
        return self._maybe_preprocess(DataSet(feats, labels))


class EmnistDataSetIterator(MnistDataSetIterator):
    """≡ EmnistDataSetIterator (letters split: 26 classes)."""
    NUM_CLASSES = 26

    def __init__(self, batch_size, split="letters", train=True, seed=123,
                 num_examples=None):
        super().__init__(batch_size, train=train, seed=seed + 17,
                         num_examples=num_examples)


class CifarDataSetIterator(DataSetIterator):
    """≡ Cifar10DataSetIterator — (B, 32, 32, 3) NHWC in [0,1]."""

    H = W = 32
    NUM_CLASSES = 10

    def __init__(self, batch_size, train=True, seed=123, root=None,
                 num_examples=None):
        super().__init__(batch_size)
        root = root or os.path.expanduser("~/.deeplearning4j/cifar10")
        files = ([f"data_batch_{i}.bin" for i in range(1, 6)] if train
                 else ["test_batch.bin"])
        paths = [os.path.join(root, "cifar-10-batches-bin", f) for f in files]
        if all(os.path.exists(p) for p in paths):
            imgs, labels = [], []
            for p in paths:
                raw = np.fromfile(p, np.uint8).reshape(-1, 3073)
                labels.append(raw[:, 0])
                # stored CHW; convert to NHWC
                imgs.append(raw[:, 1:].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1))
            self._images = np.concatenate(imgs)
            self._labels = np.concatenate(labels)
        else:
            n = num_examples or (5000 if train else 1000)
            self._images, self._labels = _synthetic_images(
                n, self.H, self.W, 3, self.NUM_CLASSES,
                seed if train else seed + 1)
        if num_examples:
            self._images = self._images[:num_examples]
            self._labels = self._labels[:num_examples]

    def numExamples(self):
        return len(self._images)

    def totalOutcomes(self):
        return self.NUM_CLASSES

    def inputColumns(self):
        return self.H * self.W * 3

    def next(self, num=None):
        self._check_has_next()
        n = num or self._batch
        img = self._images[self._cursor:self._cursor + n]
        lab = self._labels[self._cursor:self._cursor + n]
        self._cursor += len(img)
        return self._maybe_preprocess(
            DataSet(img.astype(np.float32) / 255.0,
                    _one_hot(lab, self.NUM_CLASSES)))


class Cifar100DataSetIterator(CifarDataSetIterator):
    """≡ deeplearning4j-datasets :: Cifar100DataSetIterator —
    (B, 32, 32, 3) NHWC in [0,1]; fine (100) or coarse (20) labels.
    Parses the real cifar-100-binary layout when files exist (one coarse
    + one fine label byte, then 3072 CHW pixels per record);
    deterministic synthetic otherwise (zero-egress policy)."""

    def __init__(self, batch_size, train=True, useCoarseLabels=False,
                 seed=222, root=None, num_examples=None):
        DataSetIterator.__init__(self, batch_size)
        self.NUM_CLASSES = 20 if useCoarseLabels else 100
        root = root or os.path.expanduser("~/.deeplearning4j/cifar100")
        path = os.path.join(root, "cifar-100-binary",
                            "train.bin" if train else "test.bin")
        if os.path.exists(path):
            raw = np.fromfile(path, np.uint8).reshape(-1, 3074)
            self._labels = raw[:, 0 if useCoarseLabels else 1].copy()
            self._images = raw[:, 2:].reshape(-1, 3, 32, 32).transpose(
                0, 2, 3, 1)
        else:
            n = num_examples or (4000 if train else 800)
            self._images, self._labels = _synthetic_images(
                n, self.H, self.W, 3, self.NUM_CLASSES,
                seed if train else seed + 1)
        if num_examples:
            self._images = self._images[:num_examples]
            self._labels = self._labels[:num_examples]


class LFWDataSetIterator(CifarDataSetIterator):
    """≡ deeplearning4j-datasets :: LFWDataSetIterator — Labeled Faces
    in the Wild-shaped face-identification batches: (B, H, W, C) NHWC in
    [0,1] with one class per identity (reference defaults 250x250x3).
    Zero-egress environment: deterministic synthetic faces with the
    requested geometry/identity count (the reference downloads the
    tarball)."""

    def __init__(self, batch_size, num_examples=None, imgDim=(250, 250, 3),
                 numLabels=40, train=True, seed=542):
        DataSetIterator.__init__(self, batch_size)
        h, w, c = (int(d) for d in imgDim)
        self.H, self.W, self.C = h, w, c
        self.NUM_CLASSES = int(numLabels)
        # modest default at the 250x250 reference geometry (200 examples
        # ≈ 150 MB float32); pass num_examples for more
        n = num_examples or (200 if train else 50)
        self._images, self._labels = _synthetic_images(
            n, h, w, c, self.NUM_CLASSES, seed if train else seed + 1)

    def inputColumns(self):
        return self.H * self.W * self.C


class IrisDataSetIterator(DataSetIterator):
    """≡ IrisDataSetIterator — the classic 150×4, deterministic synthetic
    replica (three gaussian clusters with the reference's class structure)."""

    def __init__(self, batch_size=150, num=150, seed=6):
        super().__init__(batch_size)
        rng = np.random.default_rng(seed)
        n_per = num // 3
        centers = np.array([[5.0, 3.4, 1.5, 0.2],
                            [5.9, 2.8, 4.3, 1.3],
                            [6.6, 3.0, 5.6, 2.0]], np.float32)
        scales = np.array([[0.35, 0.38, 0.17, 0.10],
                           [0.52, 0.31, 0.47, 0.20],
                           [0.64, 0.32, 0.55, 0.27]], np.float32)
        feats, labels = [], []
        for c in range(3):
            feats.append(centers[c] + scales[c] * rng.standard_normal((n_per, 4)).astype(np.float32))
            labels.append(np.full(n_per, c))
        self.features = np.concatenate(feats)
        self.labels = _one_hot(np.concatenate(labels), 3)
        perm = rng.permutation(len(self.features))
        self.features, self.labels = self.features[perm], self.labels[perm]

    def numExamples(self):
        return len(self.features)

    def totalOutcomes(self):
        return 3

    def inputColumns(self):
        return 4

    def next(self, num=None):
        self._check_has_next()
        n = num or self._batch
        f = self.features[self._cursor:self._cursor + n]
        l = self.labels[self._cursor:self._cursor + n]
        self._cursor += len(f)
        return self._maybe_preprocess(DataSet(f, l))


class SyntheticImageNetIterator(DataSetIterator):
    """ImageNet-shaped synthetic data for zoo/bench (224×224×3, 1000
    classes) — the bench harness's data source (no egress)."""

    def __init__(self, batch_size, num_examples=1024, height=224, width=224,
                 channels=3, num_classes=1000, seed=7, dtype=np.float32):
        super().__init__(batch_size)
        self._n = num_examples
        self._shape = (height, width, channels)
        self._classes = num_classes
        self._rng = np.random.default_rng(seed)
        self._dtype = dtype

    def numExamples(self):
        return self._n

    def totalOutcomes(self):
        return self._classes

    def inputColumns(self):
        return int(np.prod(self._shape))

    def next(self, num=None):
        self._check_has_next()
        n = min(num or self._batch, self._n - self._cursor)
        self._cursor += n
        h, w, c = self._shape
        x = self._rng.random((n, h, w, c), np.float32).astype(self._dtype)
        y = _one_hot(self._rng.integers(0, self._classes, n), self._classes)
        return self._maybe_preprocess(DataSet(x, y))


class AsyncDataSetIterator(PrefetchIterator):
    """≡ AsyncDataSetIterator — background-thread prefetch so host batch
    prep overlaps device compute (the reference uses a workspace-backed
    prefetch thread; same shape here).

    Built on runtime/pipeline.PrefetchIterator, which fixes two failure
    modes of the original hand-rolled worker: a raising `base.next()` is
    re-raised in the consumer with its original traceback instead of
    masquerading as clean end-of-stream (silently truncating the epoch),
    and `hasNext` polls with a timeout + worker-liveness check so a dead
    worker thread surfaces as an error instead of deadlocking forever."""

    def __init__(self, base, queue_size=4):
        super().__init__(base, depth=queue_size)


class ListDataSetIterator(DataSetIterator):
    """≡ ListDataSetIterator(list<DataSet>, batch) — re-batches a list of
    DataSets into batches of exactly `batch` examples (merging across list
    entries like the reference; all entries must share shapes/mask layout).
    Default batch = the whole list as one batch."""

    def __init__(self, datasets, batch_size=None):
        datasets = list(datasets)
        self._merged = (DataSet.merge(datasets) if len(datasets) > 1
                        else datasets[0]) if datasets else None
        n = self._merged.numExamples() if self._merged is not None else 0
        super().__init__(batch_size if batch_size is not None else max(n, 1))

    def numExamples(self):
        return 0 if self._merged is None else self._merged.numExamples()

    def totalOutcomes(self):
        if self._merged is None or self._merged.labels is None:
            return 0
        return int(np.asarray(self._merged.labels).shape[-1])

    def inputColumns(self):
        if self._merged is None:
            return 0
        return int(np.prod(np.asarray(self._merged.features).shape[1:]))

    def next(self, num=None):
        self._check_has_next()
        n = num or self._batch
        m = self._merged
        sl = slice(self._cursor, min(self._cursor + n, m.numExamples()))
        self._cursor = sl.stop
        pick = lambda a: None if a is None else a[sl]
        ds = DataSet(m.features[sl], pick(m.labels), pick(m.featuresMask),
                     pick(m.labelsMask))
        return self._maybe_preprocess(ds)


class ListMultiDataSetIterator:
    """MultiDataSet iterator over an in-memory list (≡ nd4j-api ::
    dataset.api.iterator.MultiDataSetIterator implementations such as
    IteratorMultiDataSetIterator): yields the stored MultiDataSets in
    order without re-batching (multi-input batches cannot be merged
    generically — input arities/shapes differ per entry)."""

    def __init__(self, multidatasets):
        self._sets = list(multidatasets)
        self._cursor = 0
        self._preprocessor = None

    def setPreProcessor(self, pp):
        self._preprocessor = pp

    def reset(self):
        self._cursor = 0

    def resetSupported(self):
        return True

    def asyncSupported(self):
        return False

    def hasNext(self):
        return self._cursor < len(self._sets)

    def next(self):
        if not self.hasNext():
            raise StopIteration
        mds = self._sets[self._cursor]
        self._cursor += 1
        if self._preprocessor is not None:
            # preprocessors mutate in place (DataNormalization convention);
            # hand them a fresh shell so the stored sets never accumulate
            # repeated normalization across epochs
            from deeplearning4j_tpu.datasets.dataset import MultiDataSet
            mds = MultiDataSet(mds.features, mds.labels, mds.featuresMasks,
                               mds.labelsMasks)
            self._preprocessor.preProcess(mds)
        return mds

    def __iter__(self):
        self.reset()
        while self.hasNext():
            yield self.next()


class SingletonMultiDataSetIterator(ListMultiDataSetIterator):
    """≡ nd4j :: SingletonMultiDataSetIterator — iterates exactly one
    MultiDataSet."""

    def __init__(self, mds):
        super().__init__([mds])


class SvhnDataSetIterator(CifarDataSetIterator):
    """≡ deeplearning4j-datasets :: SvhnDataSetIterator — Street View
    House Numbers, (B, 32, 32, 3) NHWC in [0,1], 10 classes. Zero-egress
    environment: parses nothing from disk (the reference downloads .mat
    files); deterministic synthetic data with the SVHN shape/classes."""

    def __init__(self, batch_size, train=True, seed=321, num_examples=None):
        DataSetIterator.__init__(self, batch_size)
        n = num_examples or (4000 if train else 1000)
        self._images, self._labels = _synthetic_images(
            n, self.H, self.W, 3, self.NUM_CLASSES,
            seed if train else seed + 1)


class TinyImageNetDataSetIterator(CifarDataSetIterator):
    """≡ deeplearning4j-datasets :: TinyImageNetDataSetIterator —
    (B, 64, 64, 3) NHWC in [0,1], 200 classes; synthetic under zero
    egress. Shares CifarDataSetIterator's batch/next machinery (only the
    shape constants and the synthetic source differ)."""

    H = W = 64
    NUM_CLASSES = 200

    def __init__(self, batch_size, train=True, seed=777, num_examples=None):
        DataSetIterator.__init__(self, batch_size)
        n = num_examples or (2000 if train else 500)
        self._images, self._labels = _synthetic_images(
            n, self.H, self.W, 3, self.NUM_CLASSES,
            seed if train else seed + 1)


class UciSequenceDataSetIterator(DataSetIterator):
    """≡ deeplearning4j-datasets :: UciSequenceDataSetIterator — the UCI
    synthetic-control time-series classification set: 600 univariate
    sequences of length 60, 6 classes. The real set IS synthetic
    (Alcock & Manolopoulos generators); we generate the same six pattern
    families deterministically (normal / cyclic / increasing / decreasing
    / upward-shift / downward-shift), so training behaves like the
    reference's. Yields (B, 60, 1) features + one-hot labels."""

    SEQ_LEN = 60
    NUM_CLASSES = 6

    def __init__(self, batch_size, train=True, seed=1066):
        super().__init__(batch_size)
        rng = np.random.default_rng(seed if train else seed + 1)
        per = 80 if train else 20
        xs, ys = [], []
        t = np.arange(self.SEQ_LEN, dtype=np.float32)
        for cls in range(self.NUM_CLASSES):
            for _ in range(per):
                base = 30.0 + 2.0 * rng.standard_normal(self.SEQ_LEN).astype(np.float32)
                if cls == 1:    # cyclic
                    amp, period = rng.uniform(10, 15), rng.uniform(10, 15)
                    base += amp * np.sin(2 * np.pi * t / period)
                elif cls == 2:  # increasing trend
                    base += rng.uniform(0.2, 0.5) * t
                elif cls == 3:  # decreasing trend
                    base -= rng.uniform(0.2, 0.5) * t
                elif cls in (4, 5):  # up/down shift at a random time
                    at = rng.integers(self.SEQ_LEN // 3, 2 * self.SEQ_LEN // 3)
                    shift = rng.uniform(7.5, 20)
                    base[at:] += shift if cls == 4 else -shift
                xs.append(base)
                ys.append(cls)
        order = rng.permutation(len(xs))
        self._x = np.stack(xs)[order][:, :, None].astype(np.float32)
        self._y = np.asarray(ys)[order]

    def numExamples(self):
        return len(self._x)

    def totalOutcomes(self):
        return self.NUM_CLASSES

    def inputColumns(self):
        return 1

    def next(self, num=None):
        self._check_has_next()
        n = num or self._batch
        x = self._x[self._cursor:self._cursor + n]
        y = self._y[self._cursor:self._cursor + n]
        self._cursor += len(x)
        return self._maybe_preprocess(
            DataSet(x, _one_hot(y, self.NUM_CLASSES)))
