"""DataSet (≡ nd4j-api :: org.nd4j.linalg.dataset.DataSet) — features,
labels, optional feature/label masks, plus the reference's utility surface
(merge/split/shuffle/batchBy)."""
from __future__ import annotations

import numpy as np

from deeplearning4j_tpu.ops.ndarray import NDArray, as_jax


def _np(x):
    if x is None:
        return None
    if isinstance(x, NDArray):
        return x.numpy()
    return np.asarray(x)


class DataSet:
    def __init__(self, features=None, labels=None, featuresMask=None,
                 labelsMask=None):
        self.features = _np(features)
        self.labels = _np(labels)
        self.featuresMask = _np(featuresMask)
        self.labelsMask = _np(labelsMask)

    # -- accessors (reference names) -------------------------------------
    def getFeatures(self):
        return NDArray(self.features)

    def getLabels(self):
        return NDArray(self.labels)

    def getFeaturesMaskArray(self):
        return None if self.featuresMask is None else NDArray(self.featuresMask)

    def getLabelsMaskArray(self):
        return None if self.labelsMask is None else NDArray(self.labelsMask)

    def setFeatures(self, f):
        self.features = _np(f)

    def setLabels(self, l):
        self.labels = _np(l)

    def numExamples(self):
        return 0 if self.features is None else int(self.features.shape[0])

    def numInputs(self):
        return int(np.prod(self.features.shape[1:]))

    def numOutcomes(self):
        return int(self.labels.shape[-1])

    def hasMaskArrays(self):
        return self.featuresMask is not None or self.labelsMask is not None

    # -- utilities --------------------------------------------------------
    def copy(self):
        return DataSet(None if self.features is None else self.features.copy(),
                       None if self.labels is None else self.labels.copy(),
                       None if self.featuresMask is None else self.featuresMask.copy(),
                       None if self.labelsMask is None else self.labelsMask.copy())

    def shuffle(self, seed=None):
        rng = np.random.default_rng(seed)
        perm = rng.permutation(self.numExamples())
        self.features = self.features[perm]
        if self.labels is not None:
            self.labels = self.labels[perm]
        if self.featuresMask is not None:
            self.featuresMask = self.featuresMask[perm]
        if self.labelsMask is not None:
            self.labelsMask = self.labelsMask[perm]
        return self

    def splitTestAndTrain(self, fraction_or_n):
        n = self.numExamples()
        n_train = (int(round(fraction_or_n * n)) if isinstance(fraction_or_n, float)
                   else int(fraction_or_n))

        def cut(arr, sl):
            return None if arr is None else arr[sl]

        train = DataSet(self.features[:n_train], cut(self.labels, slice(None, n_train)),
                        cut(self.featuresMask, slice(None, n_train)),
                        cut(self.labelsMask, slice(None, n_train)))
        test = DataSet(self.features[n_train:], cut(self.labels, slice(n_train, None)),
                       cut(self.featuresMask, slice(n_train, None)),
                       cut(self.labelsMask, slice(n_train, None)))
        return SplitTestAndTrain(train, test)

    def batchBy(self, batch_size):
        n = self.numExamples()
        return [DataSet(self.features[i:i + batch_size],
                        None if self.labels is None else self.labels[i:i + batch_size],
                        None if self.featuresMask is None else self.featuresMask[i:i + batch_size],
                        None if self.labelsMask is None else self.labelsMask[i:i + batch_size])
                for i in range(0, n, batch_size)]

    def asList(self):
        return self.batchBy(1)

    @staticmethod
    def merge(datasets):
        return DataSet(
            np.concatenate([d.features for d in datasets]),
            None if datasets[0].labels is None else np.concatenate([d.labels for d in datasets]),
            None if datasets[0].featuresMask is None else np.concatenate([d.featuresMask for d in datasets]),
            None if datasets[0].labelsMask is None else np.concatenate([d.labelsMask for d in datasets]))

    def sample(self, n, seed=None):
        rng = np.random.default_rng(seed)
        idx = rng.choice(self.numExamples(), size=n, replace=False)
        pick = lambda a: None if a is None else a[idx]
        return DataSet(self.features[idx], pick(self.labels),
                       pick(self.featuresMask), pick(self.labelsMask))

    def scale(self):
        mx = np.abs(self.features).max()
        if mx > 0:
            self.features = self.features / mx
        return self


class MultiDataSet:
    """≡ nd4j MultiDataSet — multiple feature/label arrays for
    ComputationGraph multi-input/multi-output training."""

    def __init__(self, features, labels, featuresMasks=None, labelsMasks=None):
        def aslist(v):
            if v is None:
                return None
            if isinstance(v, (list, tuple)):
                return [(_np(x) if x is not None else None) for x in v]
            return [_np(v)]
        self.features = aslist(features)
        self.labels = aslist(labels)
        self.featuresMasks = aslist(featuresMasks)
        self.labelsMasks = aslist(labelsMasks)

    def getFeatures(self, i=None):
        return [NDArray(f) for f in self.features] if i is None else NDArray(self.features[i])

    def getLabels(self, i=None):
        return [NDArray(l) for l in self.labels] if i is None else NDArray(self.labels[i])

    def numFeatureArrays(self):
        return len(self.features)

    def numLabelsArrays(self):
        return len(self.labels)


class SplitTestAndTrain:
    def __init__(self, train, test):
        self._train, self._test = train, test

    def getTrain(self):
        return self._train

    def getTest(self):
        return self._test
