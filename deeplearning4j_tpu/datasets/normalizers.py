"""Data normalizers (≡ nd4j-api :: dataset.api.preprocessor.*:
NormalizerStandardize, NormalizerMinMaxScaler, ImagePreProcessingScaler,
VGG16ImagePreProcessor). fit(iterator) accumulates statistics; set as a
DataSetIterator preprocessor to apply on the fly, exactly like the
reference."""
from __future__ import annotations

import numpy as np


class DataNormalization:
    def fit(self, iterator_or_dataset):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        if isinstance(iterator_or_dataset, DataSet):
            self._fit_batches([iterator_or_dataset.features])
        else:
            it = iterator_or_dataset
            it.reset()
            self._fit_batches(ds.features for ds in it)
            it.reset()
        return self

    def _fit_batches(self, batches):
        pass

    def preProcess(self, dataset):
        dataset.features = self.transform_array(dataset.features)
        return dataset

    def transform(self, x_or_dataset):
        from deeplearning4j_tpu.datasets.dataset import DataSet
        if isinstance(x_or_dataset, DataSet):
            return self.preProcess(x_or_dataset)
        return self.transform_array(np.asarray(x_or_dataset))

    def revert(self, dataset):
        dataset.features = self.revert_array(dataset.features)
        return dataset

    def transform_array(self, x):
        raise NotImplementedError

    def revert_array(self, x):
        raise NotImplementedError

    # serialization
    def state_dict(self):
        return {k: v for k, v in self.__dict__.items()}

    def load_state_dict(self, d):
        self.__dict__.update(d)
        return self


class NormalizerStandardize(DataNormalization):
    """Per-feature zero-mean unit-variance (column-wise over feature dim)."""

    def __init__(self):
        self.mean = None
        self.std = None

    def _fit_batches(self, batches):
        n, s, ss = 0, None, None
        for f in batches:
            f = f.reshape(len(f), -1).astype(np.float64)
            if s is None:
                s, ss = f.sum(0), (f ** 2).sum(0)
            else:
                s += f.sum(0)
                ss += (f ** 2).sum(0)
            n += len(f)
        self.mean = (s / n).astype(np.float32)
        var = ss / n - (s / n) ** 2
        self.std = np.sqrt(np.maximum(var, 1e-12)).astype(np.float32)

    def transform_array(self, x):
        shape = x.shape
        flat = x.reshape(len(x), -1)
        return ((flat - self.mean) / self.std).reshape(shape).astype(np.float32)

    def revert_array(self, x):
        shape = x.shape
        flat = x.reshape(len(x), -1)
        return (flat * self.std + self.mean).reshape(shape).astype(np.float32)

    def getMean(self):
        return self.mean

    def getStd(self):
        return self.std


class NormalizerMinMaxScaler(DataNormalization):
    def __init__(self, minRange=0.0, maxRange=1.0):
        self.lo, self.hi = float(minRange), float(maxRange)
        self.data_min = None
        self.data_max = None

    def _fit_batches(self, batches):
        mn = mx = None
        for f in batches:
            f = f.reshape(len(f), -1)
            bmn, bmx = f.min(0), f.max(0)
            mn = bmn if mn is None else np.minimum(mn, bmn)
            mx = bmx if mx is None else np.maximum(mx, bmx)
        self.data_min, self.data_max = mn.astype(np.float32), mx.astype(np.float32)

    def transform_array(self, x):
        shape = x.shape
        flat = x.reshape(len(x), -1)
        rng = np.maximum(self.data_max - self.data_min, 1e-12)
        scaled = (flat - self.data_min) / rng
        return (self.lo + scaled * (self.hi - self.lo)).reshape(shape).astype(np.float32)

    def revert_array(self, x):
        shape = x.shape
        flat = x.reshape(len(x), -1)
        rng = self.data_max - self.data_min
        return (((flat - self.lo) / (self.hi - self.lo)) * rng + self.data_min) \
            .reshape(shape).astype(np.float32)


class ImagePreProcessingScaler(DataNormalization):
    """uint8 [0,255] → [minRange,maxRange] (default [0,1]); stateless."""

    def __init__(self, minRange=0.0, maxRange=1.0, maxPixelVal=255.0):
        self.lo, self.hi, self.maxPixel = float(minRange), float(maxRange), float(maxPixelVal)

    def fit(self, *_):
        return self

    def transform_array(self, x):
        return (self.lo + (x.astype(np.float32) / self.maxPixel) * (self.hi - self.lo))

    def revert_array(self, x):
        return ((x - self.lo) / (self.hi - self.lo) * self.maxPixel)


class VGG16ImagePreProcessor(DataNormalization):
    """Subtract ImageNet channel means (RGB), NHWC; stateless."""

    MEANS = np.array([123.68, 116.779, 103.939], np.float32)

    def fit(self, *_):
        return self

    def transform_array(self, x):
        return x.astype(np.float32) - self.MEANS

    def revert_array(self, x):
        return x + self.MEANS


class _MultiNormalizer:
    """Base for MultiDataSet normalizers (≡ nd4j
    preprocessor.MultiNormalizerStandardize / MultiNormalizerMinMaxScaler):
    one independent per-input normalizer, fit jointly from a
    MultiDataSetIterator, applied via preProcess like the reference's
    MultiDataNormalization."""

    _single_cls = None

    def __init__(self):
        self._normalizers = None

    def fit(self, iterator_or_mds):
        from deeplearning4j_tpu.datasets.dataset import MultiDataSet
        if isinstance(iterator_or_mds, MultiDataSet):
            n_inputs = iterator_or_mds.numFeatureArrays()
            self._normalizers = [self._single_cls()
                                 for _ in range(n_inputs)]
            for i, norm in enumerate(self._normalizers):
                norm._fit_batches([iterator_or_mds.features[i]])
            return self
        # iterator: one STREAMING pass per input (like the single-input
        # normalizer) instead of materializing the whole dataset
        it = iterator_or_mds
        it.reset()
        first = next(iter(it), None)
        if first is None:
            raise ValueError("empty MultiDataSet iterator")
        n_inputs = first.numFeatureArrays()
        self._normalizers = [self._single_cls() for _ in range(n_inputs)]
        for i, norm in enumerate(self._normalizers):
            it.reset()
            norm._fit_batches(mds.features[i] for mds in it)
        it.reset()
        return self

    def _check_fit(self, mds):
        if self._normalizers is None:
            raise ValueError("call fit() first")
        if mds.numFeatureArrays() != len(self._normalizers):
            raise ValueError(
                f"MultiDataSet has {mds.numFeatureArrays()} inputs, "
                f"normalizer was fit on {len(self._normalizers)}")

    def preProcess(self, mds):
        self._check_fit(mds)
        mds.features = [n.transform_array(f)
                        for n, f in zip(self._normalizers, mds.features)]
        return mds

    transform = preProcess

    def revert(self, mds):
        self._check_fit(mds)
        mds.features = [n.revert_array(f)
                        for n, f in zip(self._normalizers, mds.features)]
        return mds

    def getInputNormalizer(self, i):
        return self._normalizers[i]

    # serialization (ModelSerializer normalizer slot / pickle)
    def state_dict(self):
        return {"per_input": [n.state_dict() for n in self._normalizers]
                if self._normalizers is not None else None}

    def load_state_dict(self, d):
        per = d.get("per_input")
        if per is None:
            self._normalizers = None
        else:
            self._normalizers = []
            for nd in per:
                n = self._single_cls()
                n.load_state_dict(nd)
                self._normalizers.append(n)
        return self


class MultiNormalizerStandardize(_MultiNormalizer):
    _single_cls = NormalizerStandardize


class MultiNormalizerMinMaxScaler(_MultiNormalizer):
    _single_cls = NormalizerMinMaxScaler
