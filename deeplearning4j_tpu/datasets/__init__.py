from deeplearning4j_tpu.datasets.dataset import DataSet, SplitTestAndTrain
from deeplearning4j_tpu.datasets.iterators import (
    ArrayDataSetIterator, AsyncDataSetIterator, Cifar100DataSetIterator,
    CifarDataSetIterator, LFWDataSetIterator,
    ListDataSetIterator, ListMultiDataSetIterator,
    SingletonMultiDataSetIterator,
    DataSetIterator, EmnistDataSetIterator, IrisDataSetIterator,
    MnistDataSetIterator, SvhnDataSetIterator, SyntheticImageNetIterator,
    TinyImageNetDataSetIterator, UciSequenceDataSetIterator)
from deeplearning4j_tpu.datasets.normalizers import (
    DataNormalization, ImagePreProcessingScaler, MultiNormalizerMinMaxScaler,
    MultiNormalizerStandardize, NormalizerMinMaxScaler,
    NormalizerStandardize, VGG16ImagePreProcessor)

__all__ = [
    "DataSet", "SplitTestAndTrain", "ArrayDataSetIterator", "ListDataSetIterator",
    "AsyncDataSetIterator", "Cifar100DataSetIterator",
    "CifarDataSetIterator", "DataSetIterator", "LFWDataSetIterator",
    "EmnistDataSetIterator", "IrisDataSetIterator", "MnistDataSetIterator",
    "SyntheticImageNetIterator", "SvhnDataSetIterator",
    "TinyImageNetDataSetIterator", "UciSequenceDataSetIterator",
    "ListMultiDataSetIterator",
    "SingletonMultiDataSetIterator", "DataNormalization",
    "ImagePreProcessingScaler", "MultiNormalizerMinMaxScaler",
    "MultiNormalizerStandardize", "NormalizerMinMaxScaler",
    "NormalizerStandardize", "VGG16ImagePreProcessor",
]
