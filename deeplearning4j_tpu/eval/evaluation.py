"""Evaluation classes (≡ nd4j-api :: org.nd4j.evaluation.classification.
Evaluation / EvaluationBinary / ROC, regression.RegressionEvaluation).

Accumulator-style: call eval(labels, predictions) per batch (numpy host
side — evaluation is not on the accelerator hot path), then read metrics.
"""
from __future__ import annotations

import numpy as np


def _to2d(labels, preds, mask=None):
    labels, preds = np.asarray(labels), np.asarray(preds)
    if labels.ndim == 3:  # (B, T, C): fold time into batch, apply mask
        b, t, c = labels.shape
        labels = labels.reshape(b * t, c)
        preds = preds.reshape(b * t, -1)
        if mask is not None:
            m = np.asarray(mask).reshape(b * t).astype(bool)
            labels, preds = labels[m], preds[m]
    return labels, preds


class Evaluation:
    def __init__(self, num_classes=None, top_n=1):
        self.num_classes = num_classes
        self.top_n = top_n
        self._cm = None
        self._top_n_correct = 0
        self._count = 0

    # -- accumulate ------------------------------------------------------
    def eval(self, labels, predictions, mask=None):
        labels, predictions = _to2d(labels, predictions, mask)
        n_cls = labels.shape[-1]
        if self._cm is None:
            self.num_classes = self.num_classes or n_cls
            self._cm = np.zeros((self.num_classes, self.num_classes), np.int64)
        actual = labels.argmax(-1)
        pred = predictions.argmax(-1)
        np.add.at(self._cm, (actual, pred), 1)
        if self.top_n > 1:
            topn = np.argsort(-predictions, axis=-1)[:, :self.top_n]
            self._top_n_correct += int((topn == actual[:, None]).any(-1).sum())
        self._count += len(actual)

    # -- metrics ---------------------------------------------------------
    def accuracy(self):
        return float(np.trace(self._cm)) / max(1, self._cm.sum())

    def topNAccuracy(self):
        if self.top_n <= 1:
            return self.accuracy()
        return self._top_n_correct / max(1, self._count)

    def truePositives(self, cls):
        return int(self._cm[cls, cls])

    def falsePositives(self, cls):
        return int(self._cm[:, cls].sum() - self._cm[cls, cls])

    def falseNegatives(self, cls):
        return int(self._cm[cls, :].sum() - self._cm[cls, cls])

    def precision(self, cls=None):
        if cls is not None:
            denom = self._cm[:, cls].sum()
            return float(self._cm[cls, cls]) / denom if denom else 0.0
        vals = [self.precision(c) for c in range(self.num_classes)
                if self._cm[:, c].sum() or self._cm[c, :].sum()]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, cls=None):
        if cls is not None:
            denom = self._cm[cls, :].sum()
            return float(self._cm[cls, cls]) / denom if denom else 0.0
        vals = [self.recall(c) for c in range(self.num_classes)
                if self._cm[c, :].sum() or self._cm[:, c].sum()]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, cls=None):
        if cls is not None:
            p, r = self.precision(cls), self.recall(cls)
            return 2 * p * r / (p + r) if (p + r) else 0.0
        vals = [self.f1(c) for c in range(self.num_classes)
                if self._cm[c, :].sum() or self._cm[:, c].sum()]
        return float(np.mean(vals)) if vals else 0.0

    def confusionMatrix(self):
        return self._cm.copy()

    def getConfusionMatrix(self):
        return self._cm.copy()

    def trueNegatives(self, cls):
        return int(self._cm.sum() - self._cm[cls, :].sum()
                   - self._cm[:, cls].sum() + self._cm[cls, cls])

    def matthewsCorrelation(self, cls=None):
        """Per-class MCC from the binarised confusion counts; cls=None
        averages over classes with support (≡ Evaluation.matthewsCorrelation
        / averageMatthewsCorrelation)."""
        if cls is not None:
            tp, fp = self.truePositives(cls), self.falsePositives(cls)
            fn, tn = self.falseNegatives(cls), self.trueNegatives(cls)
            denom = np.sqrt(float(tp + fp) * (tp + fn)
                            * (tn + fp) * (tn + fn))
            return (tp * tn - fp * fn) / denom if denom else 0.0
        vals = [self.matthewsCorrelation(c) for c in range(self.num_classes)
                if self._cm[c, :].sum() or self._cm[:, c].sum()]
        return float(np.mean(vals)) if vals else 0.0

    def gMeasure(self, cls=None):
        """√(precision·recall) (≡ Evaluation.gMeasure)."""
        if cls is not None:
            return float(np.sqrt(self.precision(cls) * self.recall(cls)))
        vals = [self.gMeasure(c) for c in range(self.num_classes)
                if self._cm[c, :].sum() or self._cm[:, c].sum()]
        return float(np.mean(vals)) if vals else 0.0

    def falseAlarmRate(self, cls=None):
        """(FPR + FNR)/2 per reference definition."""
        if cls is None:
            vals = [self.falseAlarmRate(c) for c in range(self.num_classes)
                    if self._cm[c, :].sum() or self._cm[:, c].sum()]
            return float(np.mean(vals)) if vals else 0.0
        fp, tn = self.falsePositives(cls), self.trueNegatives(cls)
        fn, tp = self.falseNegatives(cls), self.truePositives(cls)
        fpr = fp / (fp + tn) if (fp + tn) else 0.0
        fnr = fn / (fn + tp) if (fn + tp) else 0.0
        return (fpr + fnr) / 2

    def stats(self, suppressWarnings=False, includeConfusion=True):
        """≡ Evaluation.stats(): headline metrics + the per-class
        precision/recall/F1/MCC table + confusion matrix."""
        lines = ["========================Evaluation Metrics========================",
                 f" # of classes:    {self.num_classes}",
                 f" Accuracy:        {self.accuracy():.4f}",
                 f" Precision:       {self.precision():.4f}",
                 f" Recall:          {self.recall():.4f}",
                 f" F1 Score:        {self.f1():.4f}",
                 f" MCC:             {self.matthewsCorrelation():.4f}",
                 f" G-Measure:       {self.gMeasure():.4f}"]
        if self.top_n > 1:
            lines.append(f" Top-{self.top_n} Accuracy: {self.topNAccuracy():.4f}")
        lines.append("")
        lines.append(f" {'Class':>6} {'TP':>6} {'FP':>6} {'FN':>6} "
                     f"{'Precision':>10} {'Recall':>8} {'F1':>8} {'MCC':>8}")
        for c in range(self.num_classes):
            if not (self._cm[c, :].sum() or self._cm[:, c].sum()):
                continue
            lines.append(
                f" {c:>6d} {self.truePositives(c):>6d} "
                f"{self.falsePositives(c):>6d} {self.falseNegatives(c):>6d} "
                f"{self.precision(c):>10.4f} {self.recall(c):>8.4f} "
                f"{self.f1(c):>8.4f} {self.matthewsCorrelation(c):>8.4f}")
        if includeConfusion:
            lines.append("=========================Confusion Matrix=========================")
            lines.append(str(self._cm))
        return "\n".join(lines)


class EvaluationBinary:
    """Per-output independent binary evaluation (sigmoid multi-label)."""

    def __init__(self, threshold=0.5):
        self.threshold = threshold
        self._tp = self._fp = self._tn = self._fn = None

    def eval(self, labels, predictions, mask=None):
        labels, predictions = _to2d(labels, predictions, mask)
        pred = (predictions >= self.threshold).astype(np.int64)
        lab = (labels >= 0.5).astype(np.int64)
        tp = ((pred == 1) & (lab == 1)).sum(0)
        fp = ((pred == 1) & (lab == 0)).sum(0)
        tn = ((pred == 0) & (lab == 0)).sum(0)
        fn = ((pred == 0) & (lab == 1)).sum(0)
        if self._tp is None:
            self._tp, self._fp, self._tn, self._fn = tp, fp, tn, fn
        else:
            self._tp += tp; self._fp += fp; self._tn += tn; self._fn += fn

    def accuracy(self, out=None):
        tp, fp, tn, fn = self._tp, self._fp, self._tn, self._fn
        acc = (tp + tn) / np.maximum(1, tp + fp + tn + fn)
        return float(acc.mean() if out is None else acc[out])

    def precision(self, out=None):
        p = self._tp / np.maximum(1, self._tp + self._fp)
        return float(p.mean() if out is None else p[out])

    def recall(self, out=None):
        r = self._tp / np.maximum(1, self._tp + self._fn)
        return float(r.mean() if out is None else r[out])

    def f1(self, out=None):
        p, r = self.precision(out), self.recall(out)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def stats(self):
        return (f"EvaluationBinary(acc={self.accuracy():.4f}, "
                f"precision={self.precision():.4f}, recall={self.recall():.4f}, "
                f"f1={self.f1():.4f})")


class ROC:
    """Binary ROC/AUC. threshold_steps=0 → exact (all unique scores)."""

    def __init__(self, threshold_steps=0):
        self.threshold_steps = threshold_steps
        self._scores = []
        self._labels = []

    def eval(self, labels, predictions, mask=None):
        labels, predictions = _to2d(labels, predictions, mask)
        if labels.shape[-1] == 2:  # [P(neg), P(pos)] convention
            labels = labels[:, 1]
            predictions = predictions[:, 1]
        self._scores.append(np.asarray(predictions).ravel())
        self._labels.append(np.asarray(labels).ravel())

    def _roc_points(self):
        scores = np.concatenate(self._scores)
        labels = np.concatenate(self._labels) >= 0.5
        order = np.argsort(-scores)
        scores, labels = scores[order], labels[order]
        tps = np.cumsum(labels)
        fps = np.cumsum(~labels)
        # tie handling: one ROC point per DISTINCT threshold (all tied
        # scores flip together), else constant scores would fake AUC=1
        distinct = np.where(np.diff(scores))[0]
        idx = np.r_[distinct, len(scores) - 1]
        P, N = max(1, labels.sum()), max(1, (~labels).sum())
        tpr = np.concatenate([[0.0], tps[idx] / P])
        fpr = np.concatenate([[0.0], fps[idx] / N])
        return fpr, tpr

    def calculateAUC(self):
        fpr, tpr = self._roc_points()
        return float(np.trapezoid(tpr, fpr))

    def getRocCurve(self):
        return self._roc_points()


class ROCMultiClass:
    def __init__(self, threshold_steps=0):
        self._rocs = {}

    def eval(self, labels, predictions, mask=None):
        labels, predictions = _to2d(labels, predictions, mask)
        for c in range(labels.shape[-1]):
            roc = self._rocs.setdefault(c, ROC())
            roc._scores.append(predictions[:, c])
            roc._labels.append(labels[:, c])

    def calculateAUC(self, cls):
        return self._rocs[cls].calculateAUC()

    def calculateAverageAUC(self):
        return float(np.mean([r.calculateAUC() for r in self._rocs.values()]))


class ROCBinary:
    """≡ evaluation.classification.ROCBinary — an independent binary ROC
    per output column (multi-label sigmoid heads), unlike ROCMultiClass's
    one-vs-rest over a softmax. Supports a per-output (N, C) mask."""

    def __init__(self, threshold_steps=0):
        self._rocs = {}

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            b, t, c = labels.shape
            labels = labels.reshape(b * t, c)
            predictions = predictions.reshape(b * t, c)
            if mask is not None:
                mask = np.asarray(mask)
                mask = (mask.reshape(b * t, c) if mask.ndim == 3
                        else mask.reshape(b * t))
        m = None if mask is None else np.asarray(mask)
        if m is not None and m.ndim == 2 and m.shape[1] == 1:
            m = m[:, 0]          # (N, 1) = per-example column convention
        if m is not None and m.ndim == 2 and m.shape[1] != labels.shape[-1]:
            raise ValueError(
                f"mask has {m.shape[1]} columns but labels have "
                f"{labels.shape[-1]} outputs; pass (N,), (N, 1) for "
                f"per-example or (N, C) for per-output masking")
        for c in range(labels.shape[-1]):
            if m is None:
                sel = slice(None)
            elif m.ndim == 1:
                sel = m.astype(bool)
            else:  # per-output mask
                sel = m[:, c].astype(bool)
            roc = self._rocs.setdefault(c, ROC())
            roc._scores.append(predictions[sel, c])
            roc._labels.append(labels[sel, c])

    def numLabels(self):
        return len(self._rocs)

    def calculateAUC(self, outputNum):
        return self._rocs[outputNum].calculateAUC()

    def calculateAverageAUC(self):
        return float(np.mean([r.calculateAUC() for r in self._rocs.values()]))

    def stats(self):
        aucs = ", ".join(f"{c}: {r.calculateAUC():.4f}"
                         for c, r in sorted(self._rocs.items()))
        return f"ROCBinary(avgAUC={self.calculateAverageAUC():.4f}; {aucs})"


class EvaluationCalibration:
    """≡ evaluation.calibration.EvaluationCalibration — reliability
    diagrams + prediction-probability histograms per class."""

    def __init__(self, reliabilityDiagNumBins=10, histogramNumBins=10):
        self.n_bins = int(reliabilityDiagNumBins)
        self.hist_bins = int(histogramNumBins)
        self._labels = []
        self._preds = []

    def eval(self, labels, predictions, mask=None):
        labels, predictions = _to2d(labels, predictions, mask)
        self._labels.append(np.asarray(labels))
        self._preds.append(np.asarray(predictions))

    def _cls(self, classIdx):
        labels = np.concatenate(self._labels)[:, classIdx]
        preds = np.concatenate(self._preds)[:, classIdx]
        return labels >= 0.5, preds

    def getReliabilityDiagram(self, classIdx):
        """(mean predicted prob per bin, observed fraction positive per
        bin, counts per bin) over equal-width probability bins — points on
        the diagonal = perfectly calibrated."""
        y, p = self._cls(classIdx)
        bins = np.clip((p * self.n_bins).astype(int), 0, self.n_bins - 1)
        mean_pred = np.zeros(self.n_bins)
        frac_pos = np.zeros(self.n_bins)
        counts = np.zeros(self.n_bins, dtype=np.int64)
        for b in range(self.n_bins):
            sel = bins == b
            counts[b] = sel.sum()
            if counts[b]:
                mean_pred[b] = p[sel].mean()
                frac_pos[b] = y[sel].mean()
        return mean_pred, frac_pos, counts

    def getProbabilityHistogram(self, classIdx):
        """Histogram of predicted probabilities for the class."""
        _, p = self._cls(classIdx)
        counts, edges = np.histogram(p, bins=self.hist_bins,
                                     range=(0.0, 1.0))
        return counts, edges

    def expectedCalibrationError(self, classIdx):
        mean_pred, frac_pos, counts = self.getReliabilityDiagram(classIdx)
        total = max(1, counts.sum())
        return float(np.sum(counts / total * np.abs(mean_pred - frac_pos)))


class RegressionEvaluation:
    def __init__(self, n_columns=None):
        self._sse = None
        self._sae = None
        self._n = 0
        self._sum_label = None
        self._sum_label_sq = None
        self._sum_pred = None
        self._sum_pred_sq = None
        self._sum_label_pred = None

    def eval(self, labels, predictions, mask=None):
        labels, predictions = _to2d(labels, predictions, mask)
        err = predictions - labels
        if self._sse is None:
            ncol = labels.shape[-1]
            z = lambda: np.zeros(ncol)
            self._sse, self._sae = z(), z()
            self._sum_label, self._sum_label_sq = z(), z()
            self._sum_pred, self._sum_pred_sq = z(), z()
            self._sum_label_pred = z()
        self._sse += (err ** 2).sum(0)
        self._sae += np.abs(err).sum(0)
        self._sum_label += labels.sum(0)
        self._sum_label_sq += (labels ** 2).sum(0)
        self._sum_pred += predictions.sum(0)
        self._sum_pred_sq += (predictions ** 2).sum(0)
        self._sum_label_pred += (labels * predictions).sum(0)
        self._n += labels.shape[0]

    def meanSquaredError(self, col=None):
        mse = self._sse / max(1, self._n)
        return float(mse.mean() if col is None else mse[col])

    def meanAbsoluteError(self, col=None):
        mae = self._sae / max(1, self._n)
        return float(mae.mean() if col is None else mae[col])

    def rootMeanSquaredError(self, col=None):
        return float(np.sqrt(self.meanSquaredError(col)))

    def rSquared(self, col=None):
        n = max(1, self._n)
        ss_tot = self._sum_label_sq - self._sum_label ** 2 / n
        r2 = 1.0 - self._sse / np.maximum(ss_tot, 1e-12)
        return float(r2.mean() if col is None else r2[col])

    def pearsonCorrelation(self, col=None):
        n = max(1, self._n)
        cov = self._sum_label_pred - self._sum_label * self._sum_pred / n
        vl = self._sum_label_sq - self._sum_label ** 2 / n
        vp = self._sum_pred_sq - self._sum_pred ** 2 / n
        pc = cov / np.maximum(np.sqrt(vl * vp), 1e-12)
        return float(pc.mean() if col is None else pc[col])

    def stats(self):
        return (f"RegressionEvaluation(MSE={self.meanSquaredError():.6f}, "
                f"MAE={self.meanAbsoluteError():.6f}, "
                f"RMSE={self.rootMeanSquaredError():.6f}, "
                f"R2={self.rSquared():.6f})")
