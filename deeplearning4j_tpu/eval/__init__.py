from deeplearning4j_tpu.eval.evaluation import (Evaluation, EvaluationBinary,
                                                RegressionEvaluation, ROC,
                                                ROCMultiClass)

__all__ = ["Evaluation", "EvaluationBinary", "RegressionEvaluation", "ROC",
           "ROCMultiClass"]
