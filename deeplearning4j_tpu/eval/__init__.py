from deeplearning4j_tpu.eval.evaluation import (Evaluation, EvaluationBinary,
                                                RegressionEvaluation, ROC,
                                                ROCBinary, ROCMultiClass)

__all__ = ["Evaluation", "EvaluationBinary", "RegressionEvaluation", "ROC",
           "ROCBinary", "ROCMultiClass"]
